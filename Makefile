# One-command hygiene check (the reference's `analyze` + `build` CI steps,
# .circleci/config.yml:18-35): `make check` = lint + full test suite.
.PHONY: check lint test bench

check: lint test

lint:
	python tools/lint.py

test:
	python -m pytest tests/ -q

bench:
	python bench.py
