# One-command hygiene check (the reference's `analyze` + `build` CI steps,
# .circleci/config.yml:18-35): `make check` = lint + full test suite.
#
# `lint` is the whole-program static analyzer (tools/analysis/ — symbol
# table + call graph; gateway reachability, concurrency lint,
# config/sensor/fault-site drift; docs/ANALYSIS.md).  It enforces the
# empty-or-shrinking baseline gate: unsuppressed findings AND stale
# baseline entries both exit nonzero; `python tools/lint.py
# --prune-baseline` is the only way the tooling writes the baseline.
.PHONY: check lint test bench bench-smoke warm-cache

check: lint test

lint:
	python tools/lint.py

# parallel when pytest-xdist is installed (whole files per worker:
# bounds per-process XLA:CPU program accumulation — see pyproject
# comment + README "Testing"); serial otherwise (conftest clears compile
# caches per module so serial runs survive, just slower)
XDIST_FLAGS := $(shell python -c "import importlib.util as u; print('-n auto --dist loadfile' if u.find_spec('xdist') else '')")

test:
	python -m pytest tests/ -q $(XDIST_FLAGS)

bench:
	python bench.py

# dispatch-budget smoke (ISSUE 16): fused megaprogram pipeline on a
# tiny CPU cluster, asserting watched-dispatch count <= plan+2 and
# >= 2x below the eager per-goal driver — fails loudly otherwise
bench-smoke:
	python tools/bench_smoke.py

# pre-populate the persistent program cache for the default goal stacks
# offline (docs/PROGRAM_CACHE.md): the next process/tenant with these
# shapes cold-starts in seconds instead of paying the AOT compile.
# Geometry via WARM_BROKERS / WARM_PARTITIONS; PROGCACHE_DIR overrides
# the directory.
warm-cache:
	python tools/program_cache.py --dir $(or $(PROGCACHE_DIR),.progcache) warm
