# One-command hygiene check (the reference's `analyze` + `build` CI steps,
# .circleci/config.yml:18-35): `make check` = lint + full test suite.
.PHONY: check lint test bench

check: lint test

lint:
	python tools/lint.py

# parallel when pytest-xdist is installed (whole files per worker:
# bounds per-process XLA:CPU program accumulation — see pyproject
# comment + README "Testing"); serial otherwise (conftest clears compile
# caches per module so serial runs survive, just slower)
XDIST_FLAGS := $(shell python -c "import importlib.util as u; print('-n auto --dist loadfile' if u.find_spec('xdist') else '')")

test:
	python -m pytest tests/ -q $(XDIST_FLAGS)

bench:
	python bench.py
