"""Observability layer (cruise_control_tpu/obs/): end-to-end solve
tracing, the flight recorder, and the OpenMetrics exporter.

The PR's acceptance pins:

* a solve-bearing REST response carries ONE `traceId` that retrieves,
  via TRACES, a span tree covering queue-wait -> rung attempts -> model
  materialization -> device segments — for all four SchedulerClasses;
* with tracing enabled the K=1 scheduled solve stays byte-identical to
  the inline solve with the SAME `jax.device_get` count (tracing does
  zero device work);
* coalesced waiters link the leader's solve, folded tenants record
  their lane, preempted/degraded solves are marked and PINNED in the
  flight recorder past ring eviction until exported;
* `/metrics` renders a scrape-parseable OpenMetrics page with every
  registered sensor, and the canonical name mapping rejects collisions
  at register time.
"""
import re
import threading
import time as _time

import conftest  # noqa: F401

import pytest

from cruise_control_tpu.obs import export as obs_export
from cruise_control_tpu.obs import recorder as obs_recorder
from cruise_control_tpu.obs import trace as obs_trace
from cruise_control_tpu.obs.recorder import FlightRecorder, phase_summary
from cruise_control_tpu.sched.policy import SchedulerClass
from cruise_control_tpu.sched.scheduler import DeviceTimeScheduler, SolveJob
from cruise_control_tpu.utils.metrics import (MetricRegistry,
                                              canonical_sensor_name,
                                              openmetrics_sensor)

from test_facade import feed_samples, make_stack

pytestmark = pytest.mark.obs

HEAL = SchedulerClass.ANOMALY_HEAL
USER = SchedulerClass.USER_INTERACTIVE
PRE = SchedulerClass.PRECOMPUTE
SWEEP = SchedulerClass.SCENARIO_SWEEP


@pytest.fixture(autouse=True)
def fresh_obs():
    """Fresh recorder + enabled unsampled tracing per test; restore
    after."""
    obs_trace.configure(enabled=True, trace_log_enabled=False,
                        sample_rate=1.0)
    obs_recorder.install(FlightRecorder())
    yield
    obs_recorder.install(FlightRecorder())
    obs_trace.configure(enabled=True, trace_log_enabled=False,
                        sample_rate=1.0)


def wait_until(cond, timeout_s=10.0):
    deadline = _time.time() + timeout_s
    while not cond():
        assert _time.time() < deadline, "condition not met in time"
        _time.sleep(0.005)


def span_names(doc, out=None):
    """Flat set of span names in a trace tree."""
    out = out if out is not None else set()
    out.add(doc["name"])
    for child in doc.get("children", []):
        span_names(child, out)
    return out


def find_span(doc, name):
    if doc["name"] == name:
        return doc
    for child in doc.get("children", []):
        hit = find_span(child, name)
        if hit is not None:
            return hit
    return None


# ---------------------------------------------------------------------------
# trace units
# ---------------------------------------------------------------------------
class TestTrace:
    def test_span_tree_shape_and_recorder_handoff(self):
        tr = obs_trace.start("rest.TEST", endpoint="TEST")
        with obs_trace.span("outer", k=1):
            with obs_trace.span("inner"):
                obs_trace.event("hello", x=2)
        obs_trace.finish(tr)
        doc = obs_recorder.get_recorder().get(tr.trace_id)
        assert doc is not None and doc["outcome"] == "ok"
        root = doc["root"]
        assert root["name"] == "rest.TEST"
        outer = find_span(root, "outer")
        assert outer["tags"]["k"] == 1
        inner = find_span(outer, "inner")
        assert inner["events"][0]["name"] == "hello"

    def test_disabled_tracing_is_a_noop(self):
        obs_trace.configure(enabled=False)
        assert obs_trace.start("x") is None
        with obs_trace.span("y") as sp:
            assert sp is None
        obs_trace.finish(None)           # no-op, no error
        assert obs_recorder.get_recorder().recorded == 0

    def test_span_cap_counts_drops(self):
        tr = obs_trace.start("capped")
        for _ in range(obs_trace.Trace.MAX_SPANS + 10):
            obs_trace.record_span("s", 0.0, 0.0)
        obs_trace.finish(tr)
        doc = obs_recorder.get_recorder().get(tr.trace_id)
        assert doc["droppedSpans"] == 10
        assert doc["numSpans"] == obs_trace.Trace.MAX_SPANS + 1

    def test_outcome_precedence_and_error_tag(self):
        tr = obs_trace.start("bad")
        obs_trace.mark("preempted")
        obs_trace.mark("degraded")
        obs_trace.finish(tr, error=RuntimeError("boom"))
        doc = obs_recorder.get_recorder().get(tr.trace_id)
        assert doc["outcome"] == "failed"      # worst flag wins
        assert "boom" in doc["tags"]["error"]

    def test_cross_thread_activation(self):
        tr = obs_trace.start_detached("async.op")
        got = {}

        def work():
            with obs_trace.span("worker-span"):
                pass
            got["tid"] = obs_trace.current_trace_id()
        t = threading.Thread(
            target=obs_trace.finishing(tr, work))
        t.start()
        t.join()
        doc = obs_recorder.get_recorder().get(tr.trace_id)
        assert find_span(doc["root"], "worker-span") is not None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def make_trace(self, name="t", outcome_flag=None):
        tr = obs_trace.Trace(name)
        if outcome_flag:
            tr.mark(outcome_flag)
        tr.ended_s = tr.started_s
        return tr

    def test_ring_eviction(self):
        rec = FlightRecorder(capacity=4)
        ids = []
        for i in range(10):
            tr = self.make_trace(f"t{i}")
            ids.append(tr.trace_id)
            rec.record(tr)
        docs = rec.query(limit=100)
        assert len(docs) == 4
        kept = {d["traceId"] for d in docs}
        assert kept == set(ids[-4:])      # oldest evicted

    def test_pinned_failures_survive_eviction_until_exported(self):
        rec = FlightRecorder(capacity=2)
        bad = self.make_trace("bad", outcome_flag="degraded")
        rec.record(bad)
        for i in range(8):                # wash the ring
            rec.record(self.make_trace(f"ok{i}"))
        # peek does not export
        assert rec.query(outcome="degraded", export=False)
        # a returning query exports (unpins) it ...
        hit = rec.query(trace_id=bad.trace_id)
        assert hit and hit[0]["outcome"] == "degraded"
        assert rec.to_json()["pinned"] == 0
        # ... after which the washed-out trace is gone for good
        assert not rec.query(trace_id=bad.trace_id)

    def test_rejected_traces_visible_but_never_pinned(self):
        """QueueFullError backpressure marks a trace 'rejected': it
        appears in the ring but is NOT pinned — a rejection storm must
        not FIFO-flush the real incident evidence."""
        from cruise_control_tpu.sched.queue import QueueFullError
        from cruise_control_tpu.sched.policy import SchedulerClass
        rec = obs_recorder.get_recorder()
        tr = obs_trace.start("rest.REBALANCE")
        obs_trace.finish(tr, error=QueueFullError(
            SchedulerClass.USER_INTERACTIVE, 6, 6, 12.0))
        doc = rec.query(trace_id=tr.trace_id, export=False)[0]
        assert doc["outcome"] == "rejected"
        assert rec.to_json()["pinned"] == 0

    def test_compact_listing_does_not_export_pins(self):
        """Only tree-delivering queries (trace_id / verbose) count as
        exports; a compact dashboard poll must not unpin incidents."""
        rec = FlightRecorder(capacity=4)
        bad = self.make_trace("bad", outcome_flag="degraded")
        rec.record(bad)
        # the REST layer peeks for compact listings
        rec.query(limit=10, export=False)
        assert rec.to_json()["pinned"] == 1
        rec.query(trace_id=bad.trace_id)           # tree fetch exports
        assert rec.to_json()["pinned"] == 0

    def test_max_pinned_bounds_retention(self):
        rec = FlightRecorder(capacity=2, max_pinned=3)
        for i in range(6):
            rec.record(self.make_trace(f"b{i}", outcome_flag="failed"))
        assert rec.to_json()["pinned"] == 3

    def test_dump_never_raises(self):
        rec = FlightRecorder()
        rec.record(self.make_trace("x", outcome_flag="failed"))
        assert rec.dump(reason="test") >= 1

    def test_sampling_thins_ok_flood_but_incident_survives(self):
        """Satellite pin: with obs.trace.sample.rate engaged, a
        degraded trace survives a 10x ring-capacity flood of ok
        traces — the flood is thinned (sampledOut counted) while the
        incident stays pinned and queryable."""
        rec = FlightRecorder(capacity=32)
        obs_recorder.install(rec)
        obs_trace.configure(sample_rate=0.1)
        bad = obs_trace.start("incident")
        obs_trace.mark("degraded")
        obs_trace.finish(bad)
        for i in range(320):                 # 10x the ring capacity
            tr = obs_trace.start(f"ok{i}")
            obs_trace.finish(tr)
        stats = rec.to_json()
        assert stats["sampledOut"] > 0
        # sampling kept roughly rate*320 ok traces, not all of them
        assert stats["recorded"] < 321
        assert stats["sampledOut"] + stats["recorded"] == 321
        hit = rec.query(trace_id=bad.trace_id, export=False)
        assert hit and hit[0]["outcome"] == "degraded"
        # the keep decision is per-trace deterministic: re-deciding
        # the same ids reproduces the exact split
        from cruise_control_tpu.obs.trace import _sampled_in
        decisions = [_sampled_in(t) for t in ("a1b2c3d400", "ffffffff00",
                                              "0000000100")]
        assert decisions == [_sampled_in(t) for t in
                             ("a1b2c3d400", "ffffffff00", "0000000100")]

    def test_query_since_and_min_duration_filters(self):
        """Satellite pin: ?since= / ?min_duration_ms= bound drill
        queries so a tail under load never pages the whole ring."""
        rec = FlightRecorder()
        rec.record({"traceId": "old-fast", "outcome": "ok",
                    "startMs": 1_000.0, "durationMs": 5.0})
        rec.record({"traceId": "old-slow", "outcome": "ok",
                    "startMs": 2_000.0, "durationMs": 900.0})
        rec.record({"traceId": "new-fast", "outcome": "ok",
                    "startMs": 9_000.0, "durationMs": 3.0})
        rec.record({"traceId": "new-slow", "outcome": "ok",
                    "startMs": 9_500.0, "durationMs": 700.0})
        since = {d["traceId"] for d in rec.query(since_ms=5_000.0,
                                                 export=False)}
        assert since == {"new-fast", "new-slow"}
        slow = {d["traceId"] for d in rec.query(min_duration_ms=500.0,
                                                export=False)}
        assert slow == {"old-slow", "new-slow"}
        both = {d["traceId"] for d in rec.query(
            since_ms=5_000.0, min_duration_ms=500.0, export=False)}
        assert both == {"new-slow"}

    def test_phase_summary(self):
        tr = obs_trace.start("solve.x")
        obs_trace.record_span("phase-a", 0.0, 0.5)
        obs_trace.record_span("phase-b", 0.5, 0.6)
        obs_trace.finish(tr)
        summary = phase_summary(obs_recorder.get_recorder().snapshot())
        assert summary["numTraces"] == 1
        phases = summary["slowest"]["phasesMs"]
        assert phases["phase-a"] == pytest.approx(500.0)
        assert phases["phase-b"] == pytest.approx(100.0, abs=0.5)


# ---------------------------------------------------------------------------
# sensor-name hygiene + OpenMetrics export
# ---------------------------------------------------------------------------
class TestMetricsExport:
    def test_canonical_mapping(self):
        assert canonical_sensor_name("proposal-computation-timer") == \
            "cc_tpu_proposal_computation_timer"
        assert canonical_sensor_name("REBALANCE-request-rate") == \
            "cc_tpu_rebalance_request_rate"
        name, labels = openmetrics_sensor("cluster.alpha.solver-rung")
        assert name == "cc_tpu_solver_rung"
        assert labels == {"cluster": "alpha"}
        # dotted tenant ids: the cluster label is everything up to the
        # LAST dot (registry sensor names are dashed, never dotted)
        name, labels = openmetrics_sensor(
            "cluster.kafka.prod.eu.solver-rung")
        assert name == "cc_tpu_solver_rung"
        assert labels == {"cluster": "kafka.prod.eu"}

    def test_register_time_collision_check(self):
        reg = MetricRegistry()
        reg.counter("a-b")
        with pytest.raises(ValueError, match="collides"):
            reg.counter("a.b")            # same canonical family
        reg.counter("a-b")                # same raw name is fine

    def test_histogram_buckets_cumulative(self):
        reg = MetricRegistry()
        reg.update_histogram("h", 0.003)
        reg.update_histogram("h", 0.03)
        reg.update_histogram("h", 999.0)
        data = reg.histogram("h").to_json()
        assert data["count"] == 3
        assert data["buckets"]["+Inf"] == 3
        assert data["buckets"]["0.005"] == 1
        assert data["sum"] == pytest.approx(999.033)

    #: sample-line grammar of the rendered page (enough of OpenMetrics
    #: to catch an invalid name/label/value sneaking through)
    SAMPLE = re.compile(
        r"^[a-zA-Z_][a-zA-Z0-9_]*"
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
        r"(-?[0-9.]+(e[+-]?[0-9]+)?|NaN)$")

    def test_render_scrape_parseable_with_every_sensor(self):
        reg = MetricRegistry()
        reg.counter("my-counter").inc(3)
        reg.meter("my-meter").mark(2)
        reg.timer("my-timer").update(0.25)
        reg.update_histogram("my-hist", 0.1)
        reg.gauge("my-gauge", lambda: 7.0)
        reg.gauge("broken-gauge", lambda: 1 / 0)
        text = obs_export.render_openmetrics(reg.to_json())
        assert text.endswith("# EOF\n")
        for line in text.splitlines()[:-1]:
            if line.startswith("# TYPE "):
                assert re.match(
                    r"^# TYPE [a-zA-Z_][a-zA-Z0-9_]* "
                    r"(counter|gauge|histogram)$", line), line
            else:
                assert self.SAMPLE.match(line), line
        for sensor in ("my-counter", "my-meter", "my-timer", "my-hist",
                       "my-gauge", "broken-gauge"):
            assert canonical_sensor_name(sensor) in text
        # histogram family is complete
        assert "cc_tpu_my_hist_seconds_bucket" in text
        assert "cc_tpu_my_hist_seconds_sum" in text
        assert "cc_tpu_my_hist_seconds_count" in text

    def test_cluster_tagged_sensors_become_labels(self):
        sensors = {
            "cluster.alpha.solver-rung": {"type": "gauge", "value": 0},
            "cluster.beta.solver-rung": {"type": "gauge", "value": 2},
        }
        text = obs_export.render_openmetrics(sensors)
        assert 'cc_tpu_solver_rung{cluster="alpha"} 0' in text
        assert 'cc_tpu_solver_rung{cluster="beta"} 2' in text
        # ONE family announcement for both tenants
        assert text.count("# TYPE cc_tpu_solver_rung gauge") == 1


# ---------------------------------------------------------------------------
# scheduler-level trace shapes (stub jobs, no device work)
# ---------------------------------------------------------------------------
class TestSchedulerTraces:
    def blocked_scheduler(self):
        """Scheduler whose dispatcher is parked on a gate job, so
        later offers queue deterministically (test_sched pattern)."""
        sched = DeviceTimeScheduler(enabled=True)
        gate = threading.Event()
        started = threading.Event()

        def gate_run():
            started.set()
            gate.wait(10.0)
            return "gate"
        t = threading.Thread(
            target=lambda: sched.submit(SolveJob(klass=SWEEP,
                                                 run=gate_run)))
        t.start()
        started.wait(5.0)
        return sched, gate, t

    def submit_async(self, sched, job):
        box = {}

        def run():
            tr = obs_trace.start(f"solve.{job.label or 'job'}")
            job.trace = obs_trace.current_context()
            try:
                box["result"] = sched.submit(job)
                obs_trace.finish(tr)
            except BaseException as exc:  # noqa: BLE001
                obs_trace.finish(tr, error=exc)
                box["exc"] = exc
            box["trace_id"] = tr.trace_id
        t = threading.Thread(target=run)
        t.start()
        return box, t

    def test_coalesced_waiter_links_leader_trace(self):
        sched, gate, gate_t = self.blocked_scheduler()
        try:
            leader = SolveJob(klass=USER, run=lambda: "r",
                              coalesce_key=("k",), label="lead")
            b1, t1 = self.submit_async(sched, leader)
            wait_until(lambda: sched.queue.depth() > 0)
            waiter = SolveJob(klass=USER, run=lambda: "r",
                              coalesce_key=("k",), label="wait")
            b2, t2 = self.submit_async(sched, waiter)
            wait_until(lambda: sched.stats.coalesced > 0)
            gate.set()
            for t in (t1, t2, gate_t):
                t.join(10.0)
            assert b1["result"] == b2["result"] == "r"
            rec = obs_recorder.get_recorder()
            waiter_doc = rec.get(b2["trace_id"])
            link = find_span(waiter_doc["root"], "sched.coalesced")
            assert link is not None
            assert link["tags"]["leaderTraceId"] == b1["trace_id"]
            # the leader's own tree has the real dispatch
            leader_doc = rec.get(b1["trace_id"])
            assert "sched.dispatch" in span_names(leader_doc["root"])
            assert "sched.queue-wait" in span_names(leader_doc["root"])
        finally:
            gate.set()
            sched.stop()

    def test_folded_members_record_their_lane(self):
        sched, gate, gate_t = self.blocked_scheduler()
        try:
            def fold_run(payloads):
                return [f"r{p}" for p in payloads]
            boxes = []
            for i in range(3):
                job = SolveJob(klass=SWEEP, run=lambda: "inline",
                               fold_key=("f",), fold_payload=i,
                               fold_run=fold_run, label=f"sweep{i}")
                boxes.append(self.submit_async(sched, job))
            wait_until(lambda: sched.queue.depth() >= 3)
            gate.set()
            for _, t in boxes:
                t.join(10.0)
            gate_t.join(10.0)
            rec = obs_recorder.get_recorder()
            # the submitting threads race for queue order, so WHICH job
            # led the fold is nondeterministic: identify the leader by
            # its dispatch span, the members by their lane spans
            docs = [rec.get(box["trace_id"]) for box, _ in boxes]
            leaders = [d for d in docs
                       if find_span(d["root"], "sched.dispatch")]
            members = [d for d in docs
                       if find_span(d["root"], "sched.fold-member")]
            assert len(leaders) == 1 and len(members) == 2
            lanes = set()
            for doc in members:
                member = find_span(doc["root"], "sched.fold-member")
                assert member["tags"]["leaderTraceId"] == \
                    leaders[0]["traceId"]
                lanes.add(member["tags"]["lane"])
            assert lanes == {1, 2}
        finally:
            gate.set()
            sched.stop()

    def test_preempted_job_trace_is_marked_and_pinned(self):
        from cruise_control_tpu.sched import runtime
        sched = DeviceTimeScheduler(enabled=True)
        try:
            entered = threading.Event()
            release_heal = threading.Event()
            calls = {"n": 0}

            def pre_run():
                calls["n"] += 1
                entered.set()
                if calls["n"] == 1:
                    # wait until the heal is queued, then hit the
                    # checkpoint and yield
                    wait_until(lambda: sched.queue.depth(HEAL) > 0)
                    runtime.segment_checkpoint()
                return "pre-done"
            job = SolveJob(klass=PRE, run=pre_run, preemptible=True,
                           label="precompute")
            box, t = self.submit_async(sched, job)
            entered.wait(5.0)

            def heal_run():
                release_heal.wait(5.0)
                return "heal"
            hbox, ht = self.submit_async(
                sched, SolveJob(klass=HEAL, run=heal_run))
            release_heal.set()
            t.join(15.0)
            ht.join(15.0)
            assert box["result"] == "pre-done"
            rec = obs_recorder.get_recorder()
            # preempted traces are pinned until exported: peek first
            pinned = rec.query(outcome="preempted", export=False)
            assert any(d["traceId"] == box["trace_id"] for d in pinned)
            doc = rec.get(box["trace_id"])
            assert doc["outcome"] == "preempted"
            assert "sched.preempted" in span_names(doc["root"])
        finally:
            sched.stop()


# ---------------------------------------------------------------------------
# lint trace-propagation rule (the static half of the invariant)
# ---------------------------------------------------------------------------
class TestTraceLintRule:
    def lint(self, tmp_path, relpath, source):
        """Per-file G108 findings from the whole-program analyzer
        (tools/analysis/ — the ISSUE-15 successor of the flat lint;
        single-file parse set = the old per-file semantics)."""
        import pathlib
        import sys
        sys.path.insert(0, str(pathlib.Path(conftest.__file__)
                               .parent.parent / "tools"))
        try:
            from analysis import cli
        finally:
            sys.path.pop(0)
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
        return [f.render() for f in cli.analyze([path], tmp_path)
                if "trace-propagation" in f.message]

    def test_solvejob_without_trace_flagged(self, tmp_path):
        bad = ("def f(sched, run):\n"
               "    return sched.submit(SolveJob(klass=k, run=run))\n")
        assert self.lint(tmp_path, "cruise_control_tpu/rogue.py", bad)
        ok = ("def f(sched, run, ctx):\n"
              "    return sched.submit(SolveJob(klass=k, run=run,\n"
              "                                 trace=ctx))\n")
        assert not self.lint(tmp_path, "cruise_control_tpu/rogue.py", ok)
        # outside the package the rule does not apply
        assert not self.lint(tmp_path, "tools/rogue.py", bad)

    def test_naked_span_construction_flagged_outside_obs(self, tmp_path):
        bad = ("def f():\n"
               "    return Span('x'), SpanRecord(1, 0, 'y', 0, 1)\n")
        assert len(self.lint(tmp_path, "cruise_control_tpu/rogue.py",
                             bad)) == 2
        assert not self.lint(
            tmp_path, "cruise_control_tpu/obs/rogue.py", bad)

    def test_ladder_attempt_outside_span_flagged(self, tmp_path):
        bad = ("def f(self):\n"
               "    return self._solve_on_rung(rung, opt)\n")
        assert self.lint(tmp_path, "cruise_control_tpu/rogue.py", bad)
        ok = ("def f(self):\n"
              "    with obs_trace.span('solve.rung-attempt'):\n"
              "        return self._solve_on_rung(rung, opt)\n")
        assert not self.lint(tmp_path, "cruise_control_tpu/rogue.py", ok)

    def test_live_package_is_clean(self):
        """The shipped package passes its own rule (facade/sched)."""
        import pathlib
        import sys
        root = pathlib.Path(conftest.__file__).parent.parent
        sys.path.insert(0, str(root / "tools"))
        try:
            from analysis import cli
        finally:
            sys.path.pop(0)
        for rel in ("cruise_control_tpu/facade.py",
                    "cruise_control_tpu/sched/scheduler.py"):
            findings = [f.render()
                        for f in cli.analyze([root / rel], root)
                        if "trace-propagation" in f.message]
            assert not findings, findings


# ---------------------------------------------------------------------------
# facade-level span trees (real solves on the test stack)
# ---------------------------------------------------------------------------
class TestSolveTraces:
    @pytest.fixture()
    def stack(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        yield sim, cc, clock
        cc.shutdown()

    ACCEPTANCE_SPANS = {"sched.queue-wait", "solve.rung-attempt",
                        "model.materialize", "device.solve",
                        "device.instrument-fetch"}

    def test_span_tree_for_every_scheduler_class(self, stack):
        """Acceptance: ONE trace per solve covering queue-wait -> rung
        attempt -> model materialization -> device segments, for all
        four SchedulerClasses (one stack; compiled programs shared)."""
        sim, cc, clock = stack
        for klass in (USER, HEAL, PRE, SWEEP):
            cc.optimizations(ignore_proposal_cache=True,
                             _scheduler_class=klass)
            docs = obs_recorder.get_recorder().query(limit=10,
                                                     export=False)
            doc = next(d for d in docs
                       if d["tags"].get("schedulerClass") == klass.name)
            names = span_names(doc["root"])
            missing = self.ACCEPTANCE_SPANS - names
            assert not missing, \
                f"{klass.name}: missing spans {missing} in {names}"
            attempt = find_span(doc["root"], "solve.rung-attempt")
            assert attempt["tags"]["rung"] in ("FUSED", "MESH")
            assert doc["outcome"] == "ok"
        # a second identical request answers from the proposal cache:
        # no additional solve trace for the same generation
        before = len(obs_recorder.get_recorder().query(limit=20,
                                                       export=False))
        cc.optimizations()
        after = len(obs_recorder.get_recorder().query(limit=20,
                                                      export=False))
        assert after == before

    def test_degraded_solve_is_marked_pinned_and_dumped(self, stack,
                                                        monkeypatch,
                                                        caplog):
        """A FUSED failure that descends the ladder produces a trace
        with two rung attempts (first error-tagged), outcome
        'degraded', pinned in the recorder, and a flight-recorder dump
        line (SolverDegraded self-capture)."""
        import logging
        from cruise_control_tpu.analyzer.degradation import SolverRung
        sim, cc, clock = stack
        cc._solver_max_retries_per_rung = 0
        orig = cc._solve_on_rung
        state = {"failed": False}

        def flaky(rung, *args, **kwargs):
            if rung is SolverRung.FUSED and not state["failed"]:
                state["failed"] = True
                raise RuntimeError("injected device fault")
            return orig(rung, *args, **kwargs)
        monkeypatch.setattr(cc, "_solve_on_rung", flaky)
        with caplog.at_level(logging.WARNING, logger="flightRecorder"):
            cc.optimizations(ignore_proposal_cache=True)
        docs = obs_recorder.get_recorder().query(outcome="degraded",
                                                 export=False)
        assert docs, "degraded trace not recorded/pinned"
        doc = docs[0]
        attempts = []

        def collect(node):
            if node["name"] == "solve.rung-attempt":
                attempts.append(node)
            for c in node.get("children", []):
                collect(c)
        collect(doc["root"])
        assert [a["tags"]["rung"] for a in attempts] == ["FUSED",
                                                         "EAGER"]
        assert "injected device fault" in attempts[0]["tags"]["error"]
        assert any("flightRecorderDump" in r.message
                   for r in caplog.records)

    def test_incremental_fallback_marks_trace(self):
        """The PR-9 fallback counters now answer WHICH request fell
        back: a dirty-region solve that fails its verdict retries full
        and the trace carries outcome=fallback + the reason event."""
        from cruise_control_tpu.analyzer.goals.base import \
            OptimizationFailure
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        try:
            feed_samples(cc, clock)
            orig = cc._solve_with_ladder

            def flaky(*args, **kwargs):
                cell = kwargs.get("incremental")
                if cell is not None:
                    cell["dirty"] = True     # pretend the region engaged
                    kwargs = dict(kwargs, incremental=None)
                    raise OptimizationFailure("restricted verdict")
                return orig(*args, **kwargs)
            cc._solve_with_ladder = flaky
            try:
                result = cc.optimizations(ignore_proposal_cache=True)
            finally:
                cc._solve_with_ladder = orig
            docs = obs_recorder.get_recorder().query(outcome="fallback",
                                                     export=False)
            # the first call raised with dirty set -> run_solve retried
            # full sweep via the ORIGINAL ladder; flaky raised once only
            assert docs and docs[0]["outcome"] == "fallback"
        finally:
            cc.shutdown()

    def test_k1_scheduled_traced_solve_byte_identical_same_device_gets(
            self, monkeypatch):
        """Acceptance: with tracing enabled the K=1 scheduled solve is
        byte-identical to the inline (scheduler-disabled, tracing-off)
        solve with the SAME jax.device_get count — tracing and
        scheduling add zero device work."""
        import jax
        import numpy as np

        def run_once(scheduler_enabled, tracing):
            obs_trace.configure(enabled=tracing)
            obs_recorder.install(FlightRecorder())
            sim, cc, clock = make_stack(
                scheduler_enabled=scheduler_enabled)
            cc.start_up(do_sampling=False, start_detection=False)
            calls = []
            real = jax.device_get

            def counting(x):
                calls.append(1)
                return real(x)
            try:
                feed_samples(cc, clock)
                monkeypatch.setattr(jax, "device_get", counting)
                result = cc.optimizations(ignore_proposal_cache=True)
            finally:
                monkeypatch.setattr(jax, "device_get", real)
                cc.shutdown()
            digest = sorted(
                (p.partition.topic, p.partition.partition,
                 tuple(r.broker_id for r in p.new_replicas))
                for p in result.proposals)
            final = (np.asarray(result.final_state.replica_broker)
                     if result.final_state is not None else None)
            return digest, final, len(calls)

        d_inline, f_inline, n_inline = run_once(False, tracing=False)
        d_sched, f_sched, n_sched = run_once(True, tracing=True)
        obs_trace.configure(enabled=True)
        assert d_inline == d_sched
        if f_inline is not None and f_sched is not None:
            assert np.array_equal(f_inline, f_sched)
        assert n_inline == n_sched, (
            f"tracing/scheduling changed the device_get count: "
            f"{n_inline} inline vs {n_sched} scheduled+traced")


# ---------------------------------------------------------------------------
# REST surface: traceId round trip, TRACES endpoint, /metrics
# ---------------------------------------------------------------------------
class TestRestSurface:
    @pytest.fixture()
    def app(self):
        from cruise_control_tpu.api.server import CruiseControlApp
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        app = CruiseControlApp(cc, async_response_timeout_s=120.0)
        yield app
        app.stop()
        cc.shutdown()

    def test_trace_id_round_trip(self, app):
        status, hdrs, body = app.handle_request(
            "POST", "/kafkacruisecontrol/rebalance", "dryrun=true", {},
            client="test")
        assert status == 200
        trace_id = body.get("traceId")
        assert trace_id and hdrs.get("Trace-Id") == trace_id
        status, _, tb = app.handle_request(
            "GET", "/kafkacruisecontrol/traces",
            f"trace_id={trace_id}", {}, client="test")
        assert status == 200
        assert len(tb["traces"]) == 1
        doc = tb["traces"][0]
        names = span_names(doc["root"])
        for want in ("sched.queue-wait", "solve.rung-attempt",
                     "model.materialize", "device.instrument-fetch"):
            assert want in names
        # USER_TASKS links the same id
        status, _, ut = app.handle_request(
            "GET", "/kafkacruisecontrol/user_tasks", "", {},
            client="test")
        assert any(t.get("TraceId") == trace_id
                   for t in ut["userTasks"])

    def test_traces_endpoint_filters(self, app):
        app.handle_request("POST", "/kafkacruisecontrol/rebalance",
                           "dryrun=true", {}, client="test")
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/traces", "outcome=degraded",
            {}, client="test")
        assert status == 200 and body["traces"] == []
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/traces", "limit=1", {},
            client="test")
        assert status == 200 and len(body["traces"]) <= 1
        # compact listing drops the tree
        if body["traces"]:
            assert "root" not in body["traces"][0]
        # drill filters: a far-future since / absurd floor match nothing
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/traces", "since=9e15", {},
            client="test")
        assert status == 200 and body["traces"] == []
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/traces",
            "min_duration_ms=9e9", {}, client="test")
        assert status == 200 and body["traces"] == []

    def test_metrics_page(self, app):
        status, _, body = app.handle_request(
            "GET", "/metrics", "", {}, client="test")
        assert status == 200
        assert "openmetrics" in body["__content_type__"]
        text = body["__raw__"].decode()
        assert text.endswith("# EOF\n")
        assert "cc_tpu_balancedness_score" in text
        assert "cc_tpu_solver_rung" in text
        # disabled endpoint answers 404 (unknown path)
        app._metrics_endpoint_enabled = False
        status, _, _ = app.handle_request("GET", "/metrics", "", {},
                                          client="test")
        assert status == 404
