"""Incremental RoundCache maintenance vs full recomputation.

The optimizer round loops carry the RoundCache and update it from each
committed action batch instead of rebuilding O(R) segment reductions per
round; these tests assert the incremental caches stay exactly consistent
with `make_round_cache` of the evolving state across mixed rounds of
moves, leadership transfers, and swaps.
"""
import conftest  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import kernels
from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)


def _assert_table_equal(cache, state):
    """The broker table must hold exactly the valid replicas of each broker
    (row order is irrelevant — holes and append order are implementation
    detail), and every fill pointer must cover its row's live entries."""
    s = cache.broker_table.shape[1]
    if not s:
        return
    tab = np.asarray(cache.broker_table)
    fill = np.asarray(cache.table_fill)
    rb = np.asarray(state.replica_broker)
    valid = np.asarray(state.replica_valid)
    num_r = state.num_replicas
    for b in range(state.num_brokers):
        row = tab[b][tab[b] < num_r]
        expect = np.nonzero(valid & (rb == b))[0]
        np.testing.assert_array_equal(np.sort(row), np.sort(expect),
                                      err_msg=f"broker {b} table row")
        live_slots = np.nonzero(tab[b] < num_r)[0]
        if live_slots.size:
            assert fill[b] > live_slots.max(), (
                f"broker {b} fill pointer below a live slot")


def _assert_cache_equal(cache, fresh, atol=1e-3):
    np.testing.assert_allclose(np.asarray(cache.broker_load),
                               np.asarray(fresh.broker_load),
                               rtol=1e-4, atol=atol)
    np.testing.assert_allclose(np.asarray(cache.replica_load),
                               np.asarray(fresh.replica_load),
                               rtol=1e-4, atol=atol)
    np.testing.assert_array_equal(np.asarray(cache.replica_count),
                                  np.asarray(fresh.replica_count))
    np.testing.assert_array_equal(np.asarray(cache.leader_count),
                                  np.asarray(fresh.leader_count))
    np.testing.assert_array_equal(np.asarray(cache.partition_rack_count),
                                  np.asarray(fresh.partition_rack_count))
    np.testing.assert_array_equal(np.asarray(cache.broker_topic_count),
                                  np.asarray(fresh.broker_topic_count))
    np.testing.assert_allclose(np.asarray(cache.potential_nw_out),
                               np.asarray(fresh.potential_nw_out),
                               rtol=1e-4, atol=atol)
    np.testing.assert_allclose(np.asarray(cache.leader_bytes_in),
                               np.asarray(fresh.leader_bytes_in),
                               rtol=1e-4, atol=atol)


@pytest.fixture(scope="module")
def cluster():
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=12, num_partitions=120, replication_factor=3,
        num_racks=4, num_topics=5, seed=7, skew_fraction=0.3))
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    return state, ctx


def test_moves_update_cache(cluster):
    state, ctx = cluster
    cache = make_round_cache(state)
    key = jax.random.PRNGKey(0)
    for step in range(5):
        key, k1, k2 = jax.random.split(key, 3)
        k = 8
        replicas = jax.random.randint(k1, (k,), 0, state.num_replicas)
        dests = jax.random.randint(k2, (k,), 0, state.num_brokers)
        # avoid duplicate replica rows in one batch (undefined scatter order)
        _, first = np.unique(np.asarray(replicas), return_index=True)
        valid = np.zeros(k, dtype=bool)
        valid[first] = True
        # no second replica of the partition on the destination
        pr = np.asarray(ctx.partition_replicas)
        rb = np.asarray(state.replica_broker)
        for i in range(k):
            sib = pr[np.asarray(state.replica_partition)[replicas[i]]]
            sib_b = rb[sib[sib >= 0]]
            if np.asarray(dests)[i] in sib_b:
                valid[i] = False
        valid = jnp.asarray(valid) & np.asarray(state.replica_valid)[replicas]
        state, cache = kernels.commit_moves_cached(state, cache, replicas,
                                                   dests, valid)
        _assert_cache_equal(cache, make_round_cache(state))


def test_leadership_update_cache(cluster):
    state, ctx = cluster
    cache = make_round_cache(state)
    pr = np.asarray(ctx.partition_replicas)
    # transfer leadership of a handful of partitions to a follower
    src, dst, ok = [], [], []
    for p in range(0, 40, 7):
        row = pr[p][pr[p] >= 0]
        leaders = [r for r in row
                   if np.asarray(state.replica_is_leader)[r]]
        followers = [r for r in row
                     if not np.asarray(state.replica_is_leader)[r]]
        if leaders and followers:
            src.append(leaders[0]); dst.append(followers[0]); ok.append(True)
    state, cache = kernels.commit_leadership_cached(
        state, cache, jnp.asarray(src, jnp.int32),
        jnp.asarray(dst, jnp.int32), jnp.asarray(ok))
    _assert_cache_equal(cache, make_round_cache(state))


def test_mixed_rounds_through_kernels(cluster):
    """Drive the real search kernels (move_round / leadership_round) and
    commit with cache maintenance; the cache must track exactly."""
    state, ctx = cluster
    cache = make_round_cache(state)
    res = int(Resource.DISK)
    for _ in range(4):
        W = cache.broker_load[:, res]
        cap = jnp.maximum(state.broker_capacity[:, res], 1e-9)
        avg = jnp.sum(W) / jnp.sum(cap)
        upper = avg * 1.05 * cap
        accept = lambda r, d: jnp.ones(
            jnp.broadcast_shapes(r.shape, d.shape), bool)
        cand_r, cand_d, cand_v = kernels.move_round(
            state, cache.replica_load[:, res], W > upper, W - upper,
            state.replica_valid & ~state.replica_offline,
            state.broker_alive, upper - W, accept, -W / cap,
            ctx.partition_replicas)
        state, cache = kernels.commit_moves_cached(state, cache, cand_r,
                                                   cand_d, cand_v)
        _assert_cache_equal(cache, make_round_cache(state))

    bonus = (state.partition_leader_bonus[state.replica_partition, res]
             * state.replica_valid)
    W = cache.broker_load[:, res]
    cap = jnp.maximum(state.broker_capacity[:, res], 1e-9)
    avg = jnp.sum(W) / jnp.sum(cap)
    upper = avg * 1.02 * cap
    accept = lambda r, d: jnp.ones(
        jnp.broadcast_shapes(r.shape, d.shape), bool)
    cand_r, cand_f, cand_v = kernels.leadership_round(
        state, bonus, W - upper,
        state.replica_valid & ~state.replica_offline,
        state.broker_alive, upper - W, accept, -W / cap,
        ctx.partition_replicas)
    state, cache = kernels.commit_leadership_cached(state, cache, cand_r,
                                                    cand_f, cand_v)
    _assert_cache_equal(cache, make_round_cache(state))


def test_swaps_update_cache(cluster):
    state, ctx = cluster
    cache = make_round_cache(state)
    res = int(Resource.DISK)
    w = cache.replica_load[:, res]
    util = cache.broker_load[:, res]
    cap = jnp.maximum(state.broker_capacity[:, res], 1e-9)
    target = jnp.sum(util) / jnp.sum(cap) * cap
    hot = util > target
    accept = lambda r, d: jnp.ones(
        jnp.broadcast_shapes(r.shape, d.shape), bool)
    out_r, in_r, cold, valid = kernels.swap_round(
        state, w, state.replica_valid & ~state.replica_offline, hot, ~hot,
        util, target, accept, ctx.partition_replicas)
    state, cache = kernels.commit_swaps_cached(state, cache, out_r, in_r,
                                               cold, valid)
    assert bool(np.asarray(valid).any())
    _assert_cache_equal(cache, make_round_cache(state))


def test_table_maintenance_through_kernels(cluster):
    """Table-carrying cache: drive real move rounds and assert the table's
    row membership tracks the state exactly (holes + append pointers)."""
    state, ctx = cluster
    cache = make_round_cache(state, ctx.table_slots)
    _assert_table_equal(cache, state)
    res = int(Resource.DISK)
    for _ in range(6):
        W = cache.broker_load[:, res]
        cap = jnp.maximum(state.broker_capacity[:, res], 1e-9)
        avg = jnp.sum(W) / jnp.sum(cap)
        upper = avg * 1.02 * cap
        accept = lambda r, d: jnp.ones(
            jnp.broadcast_shapes(r.shape, d.shape), bool)
        cand_r, cand_d, cand_v = kernels.move_round(
            state, cache.replica_load[:, res], W > upper, W - upper,
            state.replica_valid & ~state.replica_offline,
            state.broker_alive, upper - W, accept, -W / cap,
            ctx.partition_replicas, cache=cache)
        state, cache = kernels.commit_moves_cached(state, cache, cand_r,
                                                   cand_d, cand_v)
        _assert_cache_equal(cache, make_round_cache(state))
        _assert_table_equal(cache, state)


def test_table_compaction_small_slots(cluster):
    """Force the in-row sort compaction: width barely above the fullest
    broker, then out-then-in cycles on that broker — each departure leaves
    a hole, each arrival appends, so the fill pointer outruns the count
    until the compaction branch re-packs the rows.  Membership must
    survive repeated compactions exactly."""
    state, ctx = cluster
    counts = np.asarray(make_round_cache(state).replica_count)
    target = int(np.argmax(counts))
    slots = int(counts.max()) + 3
    cache = make_round_cache(state, slots)
    _assert_table_equal(cache, state)
    pr = np.asarray(ctx.partition_replicas)
    part = np.asarray(state.replica_partition)
    rng = np.random.RandomState(3)
    compacted = False

    def pick(src_mask, dst):
        rb = np.asarray(state.replica_broker)
        valid = np.asarray(state.replica_valid)
        cand = np.nonzero(valid & src_mask(rb))[0]
        rng.shuffle(cand)
        for r in cand:
            sib_b = rb[pr[part[r]][pr[part[r]] >= 0]]
            if dst not in sib_b:
                return int(r)
        return -1

    for _ in range(12):
        other = int(rng.randint(state.num_brokers))
        if other == target:
            continue
        # hole: one replica leaves the target broker
        r_out = pick(lambda rb: rb == target, other)
        if r_out < 0:
            continue
        state, cache = kernels.commit_moves_cached(
            state, cache, jnp.asarray([r_out], jnp.int32),
            jnp.asarray([other], jnp.int32), jnp.asarray([True]))
        _assert_table_equal(cache, state)
        # append: a different replica arrives — fill grows past the count
        r_in = pick(lambda rb: rb != target, target)
        if r_in < 0:
            continue
        fill_before = int(np.asarray(cache.table_fill)[target])
        state, cache = kernels.commit_moves_cached(
            state, cache, jnp.asarray([r_in], jnp.int32),
            jnp.asarray([target], jnp.int32), jnp.asarray([True]))
        fill_after = int(np.asarray(cache.table_fill)[target])
        if fill_after != fill_before + 1:
            compacted = True                # sort re-packed the rows
        _assert_table_equal(cache, state)
    assert compacted, "compaction branch never executed — raise step count"


@pytest.mark.slow
def test_dest_shortlist_truncation_and_escalation(monkeypatch):
    """Exercise the K < B shortlist path: with a tiny shortlist the
    optimizer must still converge (rounds that would commit nothing under
    the shortlist escalate to the full broker set) and self-healing must
    relocate every offline replica."""
    from cruise_control_tpu.analyzer import kernels
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.testing.verifier import run_and_verify

    monkeypatch.setattr(kernels, "DEST_SHORTLIST", 3)
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=14, num_partitions=160, replication_factor=3,
        num_racks=4, num_topics=6, seed=11, skew_fraction=0.4,
        dead_brokers=2))
    opt = GoalOptimizer(default_goals(max_rounds=32))
    result = run_and_verify(opt, state, topo)
    assert result.proposals


@pytest.mark.slow
def test_table_overflow_triggers_rerun_with_wider_table(caplog):
    """A broker-table width too small for the actual per-broker counts must
    not silently truncate rows: optimizations() detects the overflow from
    the post-heal max count and re-runs with a re-sized static width."""
    import logging

    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                           random_cluster)

    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=8, num_partitions=80, replication_factor=2,
        num_racks=4, num_topics=4, seed=3))
    opt = GoalOptimizer(default_goals(
        names=["ReplicaDistributionGoal"], max_rounds=16))
    with caplog.at_level(logging.WARNING,
                         logger="cruise_control_tpu.analyzer.optimizer"):
        result = opt.optimizations(state, topo, _table_slots_override=2)
    assert any("overflowed the broker table width" in r.message
               for r in caplog.records)
    # the re-run used an adequate width and produced a normal result
    assert result.final_state is not None
    counts = result.violated_broker_counts["ReplicaDistributionGoal"]
    assert counts[2] <= counts[0]
