"""Config keys must be WIRED, not just defined.

Builds the full service stack from a properties file with non-default
values and asserts they take effect on the constructed objects (the
VERDICT-flagged gap: ~77 of 115 keys were defined but read by nothing).
A sweep test also asserts no key regresses back to defined-but-unread.
"""
import pathlib
import re
import subprocess

import conftest  # noqa: F401
import pytest

from cruise_control_tpu.common.config import load_properties
from cruise_control_tpu.config.main_config import CruiseControlConfig
from cruise_control_tpu.main import (build_app, build_constraint,
                                     build_cruise_control, build_notifier)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _config(tmp_path, extra=""):
    props = tmp_path / "cc.properties"
    props.write_text(
        "capacity.config.file=\n"
        "sample.store.directory=" + str(tmp_path / "samples") + "\n"
        + extra)
    return CruiseControlConfig(load_properties(str(props)))


def test_constraint_from_config(tmp_path):
    config = _config(tmp_path, extra=(
        "cpu.balance.threshold=1.5\n"
        "disk.capacity.threshold=0.6\n"
        "max.replicas.per.broker=1234\n"
        "topic.replica.count.balance.threshold=2.5\n"))
    c = build_constraint(config)
    assert c.resource_balance_percentage[0] == pytest.approx(1.5)
    assert c.capacity_threshold[3] == pytest.approx(0.6)
    assert c.max_replicas_per_broker == 1234
    assert c.topic_replica_balance_percentage == pytest.approx(2.5)


def test_notifier_switches(tmp_path):
    from cruise_control_tpu.core.anomaly import AnomalyType
    config = _config(tmp_path, extra=(
        "self.healing.enabled=true\n"
        "self.healing.broker.failure.enabled=true\n"
        "self.healing.goal.violation.enabled=false\n"
        "broker.failure.alert.threshold.ms=1000\n"
        "broker.failure.self.healing.threshold.ms=5000\n"))
    notifier = build_notifier(config)
    enabled = notifier.self_healing_enabled()
    assert enabled[AnomalyType.BROKER_FAILURE]
    assert not enabled[AnomalyType.GOAL_VIOLATION]


def test_stack_wiring_end_to_end(tmp_path):
    from cruise_control_tpu.cluster.simulated import SimulatedCluster
    sim = SimulatedCluster()
    for b in range(3):
        sim.add_broker(b, rack=f"rack{b % 2}")
    sim.create_topic("t0", [[0, 1], [1, 2], [2, 0]], size_bytes=1e4)
    config = _config(tmp_path, extra=(
        "num.concurrent.partition.movements.per.broker=7\n"
        "max.num.cluster.movements=123\n"
        "leader.movement.timeout.ms=11000\n"
        "demotion.history.retention.time.ms=3600000\n"
        "max.optimization.rounds=9\n"
        "goal.balancedness.priority.weight=1.5\n"
        "goal.balancedness.strictness.weight=3.0\n"
        "monitor.state.update.interval.ms=30000\n"
        "max.active.user.tasks=11\n"
        "completed.user.task.retention.time.ms=7200000\n"
        "max.cached.completed.user.tasks=17\n"
        "two.step.verification.enabled=true\n"
        "two.step.purgatory.max.requests=3\n"
        "webserver.http.cors.enabled=true\n"
        "webserver.http.cors.origin=https://ops.example\n"
        "webserver.api.urlprefix=/custom\n"))
    cc = build_cruise_control(config, sim)
    try:
        assert cc.executor._inter_cap == 7
        assert cc.executor._max_cluster_movements == 123
        assert cc.executor._leader_timeout == pytest.approx(11.0)
        assert cc.executor._demotion_retention == pytest.approx(3600.0)
        assert all(g.max_rounds == 9 for g in cc.goal_optimizer.goals
                   if not g.is_hard)
        assert cc.goal_optimizer.balancedness_weights == (1.5, 3.0)
        assert cc.load_monitor._state_ttl_s == pytest.approx(30.0)

        app = build_app(config, cc)
        assert app.user_tasks._max_active == 11
        assert app.user_tasks._retention_s == pytest.approx(7200.0)
        assert app.user_tasks._max_cached_completed == 17
        assert app.purgatory is not None
        assert app.purgatory._max_requests == 3
        assert app._cors_headers["Access-Control-Allow-Origin"] == \
            "https://ops.example"
        assert app.base_path == "/custom"
        # the custom prefix actually routes
        status, _, body = app.handle_request(
            "GET", "/custom/state", "", {}, client="t")
        assert status == 200
        status, _, _ = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "", {}, client="t")
        assert status == 404
    finally:
        cc.shutdown()


def test_goal_list_sanity_rules(tmp_path):
    config = _config(tmp_path, extra=(
        "goals=RackAwareGoal,ReplicaCapacityGoal\n"
        "hard.goals=RackAwareGoal\n"
        "anomaly.detection.goals=RackAwareGoal\n"
        "default.goals=DiskCapacityGoal\n"))
    from cruise_control_tpu.main import _goal_lists
    with pytest.raises(ValueError, match="default.goals"):
        _goal_lists(config)


def test_every_defined_key_is_read_somewhere():
    """Sweep: every `d.define`d key must be referenced outside the config
    definition module (the reference wires every constant it defines)."""
    src = (REPO / "cruise_control_tpu" / "config"
           / "main_config.py").read_text()
    keys = re.findall(r'd\.define\("([^"]+)"', src)
    assert len(keys) > 100
    unread = []
    for key in keys:
        out = subprocess.run(
            ["grep", "-rl", "--include=*.py", f'"{key}"',
             str(REPO / "cruise_control_tpu")],
            capture_output=True, text=True).stdout
        hits = [l for l in out.splitlines()
                if "config/main_config.py" not in l
                and "docgen" not in l]
        if not hits:
            unread.append(key)
    assert not unread, f"defined but never read: {unread}"
