"""Swap-phase tests for ResourceDistributionGoal.

Reference behavior being covered: when plain replica moves cannot balance a
resource — e.g. every broker is replica-count-constrained so a move OUT
would be rejected by a previously-optimized count goal — the reference
falls back to replica SWAPS between an over- and an under-utilized broker
(reference ResourceDistributionGoal.java:307-433, swap budget :53).
"""
import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context)
from cruise_control_tpu.analyzer.goals.capacity import ReplicaCapacityGoal
from cruise_control_tpu.analyzer.goals.resource_distribution import (
    DiskUsageDistributionGoal)
from cruise_control_tpu.common.resources import Resource as R
from cruise_control_tpu.model import state as S
from cruise_control_tpu.model.builder import ClusterModelBuilder

CAPACITY = {R.CPU: 100.0, R.NW_IN: 1000.0, R.NW_OUT: 1000.0, R.DISK: 1000.0}


def _tight_hot_cold():
    """Two brokers, 4 single-replica partitions each, max 4 replicas per
    broker.  Broker 0 holds the big-disk partitions (800 total = 80% fill),
    broker 1 the small ones (80 total = 8%).  A move would put 5 replicas
    on one broker — rejected by ReplicaCapacityGoal — so only swaps can
    balance disk."""
    b = ClusterModelBuilder()
    b.add_broker(0, "A", CAPACITY)
    b.add_broker(1, "B", CAPACITY)
    for p in range(4):
        b.add_partition("hot", p, 0, [],
                        {R.CPU: 5.0, R.NW_IN: 10.0, R.NW_OUT: 10.0,
                         R.DISK: 200.0})
    for p in range(4):
        b.add_partition("cold", p, 1, [],
                        {R.CPU: 5.0, R.NW_IN: 10.0, R.NW_OUT: 10.0,
                         R.DISK: 20.0})
    return b.build()


def _disk_spread(state):
    from cruise_control_tpu.testing.fixtures import util_spread
    return util_spread(state, R.DISK)


def test_swaps_balance_when_moves_cannot():
    state, topo = _tight_hot_cold()
    constraint = BalancingConstraint(max_replicas_per_broker=4)
    ctx = make_context(state, constraint, OptimizationOptions(), topo)
    cap_goal = ReplicaCapacityGoal()
    goal = DiskUsageDistributionGoal(max_rounds=32)

    before = _disk_spread(state)
    out = goal.optimize(state, ctx, (cap_goal,))
    after = _disk_spread(out)

    counts = np.asarray(S.broker_replica_count(out))
    assert counts.tolist() == [4, 4], "swap must preserve replica counts"
    assert after < before - 0.1, (
        f"swaps should have balanced disk: spread {before:.3f} -> {after:.3f}")


def test_no_swaps_when_disabled():
    state, topo = _tight_hot_cold()
    constraint = BalancingConstraint(max_replicas_per_broker=4)
    ctx = make_context(state, constraint, OptimizationOptions(), topo)
    goal = DiskUsageDistributionGoal(max_rounds=32, max_swap_rounds=0)
    out = goal.optimize(state, ctx, (ReplicaCapacityGoal(),))
    # with the swap phase off and moves blocked, nothing can change
    assert _disk_spread(out) == pytest.approx(_disk_spread(state))


def test_fast_mode_skips_swap_phase():
    state, topo = _tight_hot_cold()
    constraint = BalancingConstraint(max_replicas_per_broker=4)
    ctx = make_context(state, constraint,
                       OptimizationOptions(fast_mode=True), topo)
    goal = DiskUsageDistributionGoal(max_rounds=32)
    out = goal.optimize(state, ctx, (ReplicaCapacityGoal(),))
    assert _disk_spread(out) == pytest.approx(_disk_spread(state))
