"""Device-resident incremental workload model (model/store.py +
monitor/deltas.py + the facade's dirty-region warm-start solving).

The pins here are the PR's contracts:

* delta-applied resident model == from-scratch rebuild, byte for byte,
  for EVERY delta kind (capacity, per-partition load, demote, add/new,
  remove) and for chains of them;
* an all-dirty mask solves byte-identically to the full sweep, and a
  warm-started dirty-subset solve stays feasible and within the full
  solve's balancedness;
* generation gaps, over-long chains and ladder descents below FUSED
  fall back to a full rebuild (metered), never a wrong answer;
* a fault mid-`apply_delta` QUARANTINES the store (chaos pin): the next
  solve rebuilds, a half-applied model is never served;
* warm seeds are tagged (tenant scope, model generation): a seed never
  warm-starts another tenant or a generation it did not see, and
  fleet-folded results now carry per-lane final states that seed warm
  starts exactly like inline solves (fleet/router.py).
"""
import dataclasses

import conftest  # noqa: F401

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 restrict_context_to_dirty)
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.model.store import DeviceModelStore
from cruise_control_tpu.monitor.deltas import (BrokerAdd, ModelDelta,
                                               ModelDeltaError,
                                               PartitionLoadUpdate)
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.sampling.sampler import (
    SimulatedClusterSampler)
from cruise_control_tpu.sched.policy import SchedulerClass
from cruise_control_tpu.utils import faults

pytestmark = pytest.mark.incremental

INCR_GOALS = ["RackAwareGoal", "DiskCapacityGoal",
              "ReplicaDistributionGoal", "DiskUsageDistributionGoal"]


def _build_sim(num_brokers=6, partitions=20, rf=3):
    sim = SimulatedCluster()
    for b in range(num_brokers):
        sim.add_broker(b, rack=f"rack{b % 3}")
    assignments = [[(p + i) % num_brokers for i in range(rf)]
                   for p in range(partitions)]
    sim.create_topic("t0", assignments, size_bytes=1e4)
    for p in range(partitions):
        sim.set_partition_load(TopicPartition("t0", p),
                               leader_cpu=2.0 + p * 0.1,
                               nw_in=100.0 + p, nw_out=300.0)
    return sim


def _make_monitor(sim, clock):
    mon = LoadMonitor(sim, SimulatedClusterSampler(sim), num_windows=3,
                      window_ms=10_000, min_samples_per_window=1,
                      time_fn=lambda: clock["now"])
    mon.task_runner.start(do_sampling=False)
    for _ in range(6):
        mon.task_runner.sample_once()
        sim.advance(5)
        clock["now"] += 5
    return mon


def _states_equal(a, b) -> bool:
    for f in dataclasses.fields(type(a)):
        x, y = getattr(a, f.name), getattr(b, f.name)
        if hasattr(x, "shape"):
            if np.asarray(x).shape != np.asarray(y).shape \
                    or not np.array_equal(np.asarray(x), np.asarray(y)):
                return False
        elif x != y:
            return False
    return True


def make_stack(skewed=True, **cc_kwargs):
    """A live facade over the simulated cluster (the incremental path's
    real substrate: monitor generations, delta log, device store)."""
    sim = SimulatedCluster()
    clock = {"now": 10_000.0}
    for b in range(4):
        sim.add_broker(b, rack=f"rack{b % 2}")
    assignments = [([0, 1] if skewed else [p % 4, (p + 1) % 4])
                   for p in range(12)]
    sim.create_topic("t0", assignments, size_bytes=1e4)
    for p in range(12):
        sim.set_partition_load(TopicPartition("t0", p), leader_cpu=2.0,
                               nw_in=100.0, nw_out=300.0)
    cc = CruiseControl(
        sim, SimulatedClusterSampler(sim),
        time_fn=lambda: clock["now"],
        sleep_fn=lambda s: (sim.advance(s),
                            clock.__setitem__("now", clock["now"] + s)),
        monitor_kwargs=dict(num_windows=3, window_ms=10_000,
                            min_samples_per_window=1,
                            sampling_interval_ms=5_000),
        executor_kwargs=dict(progress_check_interval_s=1.0),
        auto_warmup=False, goal_names=list(INCR_GOALS), **cc_kwargs)
    cc.start_up(do_sampling=False, start_detection=False)
    for _ in range(8):
        cc.load_monitor.task_runner.sample_once()
        sim.advance(5)
        clock["now"] += 5
    return sim, cc, clock


# ---------------------------------------------------------------------------
# delta application == rebuild, byte for byte
# ---------------------------------------------------------------------------
class TestDeltaByteEquality:
    @pytest.fixture()
    def rig(self):
        sim = _build_sim()
        clock = {"now": 10_000.0}
        mon = _make_monitor(sim, clock)
        gen = mon.model_generation()
        state, topo = mon.cluster_model()
        store = DeviceModelStore()
        store.install(gen, state, topo, True,
                      mon.follower_cpu_estimator())
        yield sim, mon, store
        mon.shutdown()

    @pytest.mark.parametrize("delta", [
        ModelDelta(capacity_overrides={2: {"disk": 5e5, "cpu": 80.0}}),
        ModelDelta(load_updates=(
            PartitionLoadUpdate("t0", 5, (6.0, 140.0, 420.0, 3e4)),
            PartitionLoadUpdate("t0", 11, (1.0, 10.0, 30.0, 1e3)))),
        ModelDelta(demote_brokers=(4,)),
        ModelDelta(add_brokers=(BrokerAdd(broker_id=1),)),
        ModelDelta(remove_brokers=(5,)),
    ], ids=["capacity", "load", "demote", "add-new", "remove"])
    def test_every_delta_kind_byte_equals_rebuild(self, rig, delta):
        _sim, mon, store = rig
        g_from = store.generation
        g_to = mon.apply_model_delta(delta)
        chain = mon.deltas_between(g_from, g_to)
        assert chain and len(chain) == 1
        got = store.advance(chain, g_to)
        assert got is not None, store.last_fallback_reason
        rebuilt, _ = mon.cluster_model()
        assert _states_equal(got[0], rebuilt)
        assert store.last_dirty_brokers >= 1

    def test_chain_of_deltas_byte_equals_rebuild(self, rig):
        _sim, mon, store = rig
        g0 = store.generation
        for delta in (
                ModelDelta(capacity_overrides={0: {"nw_in": 3e5}}),
                ModelDelta(load_updates=(PartitionLoadUpdate(
                    "t0", 2, (3.0, 50.0, 90.0, 2e4)),)),
                ModelDelta(demote_brokers=(1,))):
            g_to = mon.apply_model_delta(delta)
        chain = mon.deltas_between(g0, g_to)
        assert chain and len(chain) == 3
        got = store.advance(chain, g_to)
        assert got is not None
        rebuilt, _ = mon.cluster_model()
        assert _states_equal(got[0], rebuilt)
        # the dirty union covers every delta since g0
        dirty = store.dirty_since(g0)
        assert dirty is not None
        assert np.asarray(dirty)[[0, 1]].all()

    def test_unlogged_change_breaks_the_chain(self, rig):
        sim, mon, store = rig
        g0 = store.generation
        g1 = mon.apply_model_delta(
            ModelDelta(capacity_overrides={0: {"disk": 9e5}}))
        # fresh samples move the load generation with NO delta record
        mon.task_runner.sample_once()
        g2 = mon.model_generation()
        assert g2 != g1
        assert mon.deltas_between(g0, g2) is None
        assert store.advance([], g2) is None
        assert store.fallbacks >= 1

    def test_capacity_flag_mismatch_never_fast_forwards(self, rig):
        """Review finding: a delta chain preserves the resident build's
        allow_capacity_estimation flag — a consult with the OTHER flag
        must rebuild, not advance (the facade gateway's guard)."""
        sim = _build_sim()
        clock = {"now": 10_000.0}
        mon = _make_monitor(sim, clock)
        store = DeviceModelStore()
        gen = mon.model_generation()
        state, topo = mon.cluster_model()
        store.install(gen, state, topo, True,
                      mon.follower_cpu_estimator())
        mon.apply_model_delta(
            ModelDelta(capacity_overrides={0: {"disk": 9e5}}))
        assert store.capacity_flag is True
        # the facade-level guard is what prevents the advance; at store
        # level the flag is exposed for exactly that comparison
        assert store.get(mon.model_generation(), False) is None
        mon.shutdown()

    def test_train_moves_the_generation(self, rig):
        """Review finding: TRAIN changes follower-CPU attribution (what
        the next build produces) — the generation must move so neither
        the store nor the proposal cache serves pre-TRAIN results."""
        sim = _build_sim()
        clock = {"now": 10_000.0}
        mon = LoadMonitor(sim, SimulatedClusterSampler(sim),
                          num_windows=3, window_ms=10_000,
                          min_samples_per_window=1,
                          use_linear_regression_model=True,
                          time_fn=lambda: clock["now"])
        mon.task_runner.start(do_sampling=False)
        for _ in range(6):
            mon.task_runner.sample_once()
            sim.advance(5)
            clock["now"] += 5
        g0 = mon.model_generation()
        mon.train()
        assert mon.model_generation() != g0
        # unlogged: the store must rebuild, never fast-forward
        assert mon.deltas_between(g0, mon.model_generation()) is None
        mon.shutdown()

    def test_unknown_ids_are_rejected_or_unsupported(self, rig):
        _sim, mon, store = rig
        with pytest.raises(ModelDeltaError):
            mon.apply_model_delta(ModelDelta(demote_brokers=(99,)))
        with pytest.raises(ModelDeltaError):
            # hypothetical broker rows are shape changes, not deltas
            mon.apply_model_delta(ModelDelta(
                add_brokers=(BrokerAdd(broker_id=1,
                                       rack="somewhere"),)))
        with pytest.raises(ModelDeltaError):
            mon.apply_model_delta(ModelDelta())


# ---------------------------------------------------------------------------
# dirty-region solving
# ---------------------------------------------------------------------------
class TestDirtyRegionSolve:
    @pytest.fixture(scope="class")
    def cluster(self):
        from cruise_control_tpu.testing.random_cluster import (
            RandomClusterSpec, random_cluster)
        return random_cluster(RandomClusterSpec(
            num_brokers=8, num_partitions=60, replication_factor=2,
            num_racks=2, num_topics=4, seed=7, skew_fraction=0.25))

    @pytest.fixture(scope="class")
    def optimizer(self):
        return GoalOptimizer(default_goals(max_rounds=32,
                                           names=INCR_GOALS),
                             pipeline_segment_size=4)

    def test_all_dirty_mask_is_byte_identical_to_full(self, cluster,
                                                      optimizer):
        state, topo = cluster
        full = optimizer.optimizations(state, topo)
        alld = optimizer.optimizations(
            state, topo, dirty_brokers=jnp.ones(state.num_brokers, bool))

        def keys(props):
            return [(str(p.partition),
                     tuple(r.broker_id for r in p.new_replicas))
                    for p in props]
        assert keys(full.proposals) == keys(alld.proposals)
        assert np.array_equal(
            np.asarray(full.final_state.replica_broker),
            np.asarray(alld.final_state.replica_broker))
        assert np.array_equal(
            np.asarray(full.final_state.replica_is_leader),
            np.asarray(alld.final_state.replica_is_leader))

    def test_warm_dirty_subset_feasible_within_full_balancedness(
            self, cluster, optimizer):
        state, topo = cluster
        full = optimizer.optimizations(state, topo)
        # a delta: one broker's capacity moves; solve warm from the
        # converged placement with only that broker dirty
        state2 = state.replace(
            broker_capacity=state.broker_capacity.at[2].set(
                state.broker_capacity[2] * 1.5))
        dirty = jnp.zeros(state.num_brokers, bool).at[2].set(True)
        warm = optimizer.optimizations(state2, topo,
                                       warm_start=full.final_state,
                                       dirty_brokers=dirty)
        ctrl = optimizer.optimizations(state2, topo,
                                       warm_start=full.final_state)
        hard = {g.name for g in optimizer.goals if g.is_hard}
        assert not (set(warm.violated_goals_after) & hard)
        assert warm.balancedness_score() >= \
            ctrl.balancedness_score() - 1e-6
        # the restricted search does no more work than the full sweep
        assert (sum(warm.rounds_by_goal.values())
                <= sum(ctrl.rounds_by_goal.values()))

    def test_restrict_context_all_dirty_is_identity(self, cluster):
        state, topo = cluster
        ctx = make_context(state, BalancingConstraint(),
                           OptimizationOptions(), topo)
        rest = restrict_context_to_dirty(
            state, ctx, jnp.ones(state.num_brokers, bool))
        assert np.array_equal(np.asarray(rest.replica_movable),
                              np.asarray(ctx.replica_movable))
        assert np.array_equal(np.asarray(rest.broker_dest_ok),
                              np.asarray(ctx.broker_dest_ok))

    def test_restrict_context_subset_freezes_clean_sources(self,
                                                           cluster):
        state, topo = cluster
        ctx = make_context(state, BalancingConstraint(),
                           OptimizationOptions(), topo)
        dirty = jnp.zeros(state.num_brokers, bool).at[0].set(True)
        rest = restrict_context_to_dirty(state, ctx, dirty)
        movable = np.asarray(rest.replica_movable)
        rb = np.asarray(state.replica_broker)
        # replicas on clean, non-overloaded brokers are frozen
        load = np.asarray(
            jax.device_get(jnp.asarray(ctx.balance_upper_pct)))
        util = (np.asarray(jax.device_get(
            __import__("cruise_control_tpu.model.state",
                       fromlist=["broker_load"]).broker_load(state)))
            / np.maximum(np.asarray(state.broker_capacity), 1e-9))
        clean_cold = [b for b in range(state.num_brokers)
                      if b != 0 and not (util[b] > load).any()]
        for b in clean_cold:
            assert not movable[(rb == b)
                               & np.asarray(state.replica_valid)].any()


# ---------------------------------------------------------------------------
# facade: store consults, warm-seed tags, fallbacks
# ---------------------------------------------------------------------------
class TestFacadeIncremental:
    def test_interactive_delta_solve_rides_the_store(self):
        _sim, cc, _clock = make_stack()
        try:
            cc.optimizations()                     # cold: install + seed
            store = cc._model_store
            assert store.to_json()["resident"]
            assert cc._warm_seed is not None
            seed_state, seed_gen, seed_scope = cc._warm_seed
            assert seed_gen == cc.load_monitor.model_generation()
            assert seed_scope == cc._coalesce_scope

            cc.load_monitor.apply_model_delta(
                ModelDelta(capacity_overrides={2: {"disk": 9e5}}))
            result = cc.optimizations()            # interactive default
            assert store.delta_applies >= 1
            assert store.hits >= 1
            assert store.last_dirty_brokers == 1
            assert result.proposals is not None
            # the seed advanced to the new generation
            assert cc._warm_seed[1] == cc.load_monitor.model_generation()
        finally:
            cc.shutdown()

    def test_incremental_matches_full_solve_quality(self):
        _sim, cc, _clock = make_stack()
        try:
            cc.optimizations()
            cc.load_monitor.apply_model_delta(
                ModelDelta(capacity_overrides={1: {"disk": 1.2e6}}))
            incr = cc.optimizations()
            # full-sweep control on the SAME model: incremental off
            cc._incremental_enabled = False
            full = cc.optimizations(ignore_proposal_cache=True)
            assert incr.balancedness_score() >= \
                full.balancedness_score() - 1e-6
        finally:
            cc.shutdown()

    def test_generation_gap_falls_back_to_rebuild(self):
        _sim, cc, _clock = make_stack()
        try:
            cc.optimizations()
            store = cc._model_store
            # load generation moves with NO delta: gap
            cc.load_monitor.task_runner.sample_once()
            cc.optimizations()
            assert store.fallbacks >= 1
            assert "generation-gap" in store.last_fallback_reason
            # ... and the rebuild re-installed the store
            assert store.to_json()["resident"]
        finally:
            cc.shutdown()

    def test_stale_seed_dropped_when_generation_moves_unseen(self):
        _sim, cc, _clock = make_stack()
        try:
            cc.optimizations()
            assert cc._warm_seed is not None
            cc.load_monitor.task_runner.sample_once()   # unlogged move
            state, topo, warm, dirty = cc._materialize_solve_inputs(
                True, None, incremental={})
            assert warm is None and dirty is None
            assert cc._warm_seed is None                # dropped for good
        finally:
            cc.shutdown()

    def test_seed_never_crosses_scope(self):
        _sim, cc, _clock = make_stack()
        try:
            cc.optimizations()
            seed_state, seed_gen, _scope = cc._warm_seed
            # a seed tagged for ANOTHER tenant must never warm this one
            cc._warm_seed = (seed_state, seed_gen, "tenant-beta")
            _state, _topo, warm, dirty = cc._materialize_solve_inputs(
                True, None, incremental={})
            assert warm is None and dirty is None
        finally:
            cc.shutdown()

    def test_precompute_class_keeps_the_full_sweep(self):
        _sim, cc, _clock = make_stack()
        try:
            cc.optimizations()
            cc.load_monitor.apply_model_delta(
                ModelDelta(capacity_overrides={3: {"disk": 1.1e6}}))
            before = cc.metrics.meter(
                "incremental-solve-fallbacks").to_json()["count"]
            cc.optimizations(
                _scheduler_class=SchedulerClass.PRECOMPUTE)
            # precompute solves full-sweep: the dirty path never
            # engaged, so no incremental fallback can have fired
            assert cc.metrics.meter(
                "incremental-solve-fallbacks").to_json()["count"] \
                == before
            # the store still served the materialization
            assert cc._model_store.delta_applies >= 1
        finally:
            cc.shutdown()

    def test_state_and_sensors_expose_the_store(self):
        _sim, cc, _clock = make_stack()
        try:
            cc.optimizations()
            out = cc.state()
            block = out["IncrementalStoreState"]
            assert block["enabled"] and block["resident"]
            sensors = cc.state(substates=["sensors"])["Sensors"]
            for name in ("incremental-store-hits",
                         "incremental-store-misses",
                         "incremental-store-fallbacks",
                         "incremental-store-delta-applies",
                         "incremental-store-dirty-brokers"):
                assert name in sensors, name
        finally:
            cc.shutdown()

    def test_disabled_flag_bypasses_the_store(self):
        _sim, cc, _clock = make_stack(incremental_enabled=False)
        try:
            cc.optimizations()
            st = cc._model_store
            assert not st.to_json()["resident"]
            assert st.hits == 0 and st.delta_applies == 0
        finally:
            cc.shutdown()


# ---------------------------------------------------------------------------
# chaos: half-applied deltas, ladder descents
# ---------------------------------------------------------------------------
@pytest.mark.chaos
class TestIncrementalChaos:
    def test_fault_mid_apply_quarantines_and_rebuilds(self):
        _sim, cc, _clock = make_stack()
        try:
            clean = cc.optimizations()
            store = cc._model_store
            cc.load_monitor.apply_model_delta(
                ModelDelta(capacity_overrides={0: {"disk": 1.3e6}}))
            plan = faults.FaultPlan().fail_nth("store.apply_delta", 1)
            with faults.injected(plan):
                result = cc.optimizations()
            # the store quarantined instead of serving half a model...
            assert store.quarantines == 1
            assert "quarantined" in store.last_fallback_reason
            # ...and the solve was served from a full rebuild whose
            # result matches a clean twin's on the same model
            cc2_sim, cc2, _ = make_stack()
            try:
                cc2.load_monitor.apply_model_delta(ModelDelta(
                    capacity_overrides={0: {"disk": 1.3e6}}))
                twin = cc2.optimizations()
                assert ([str(p.partition) for p in result.proposals]
                        == [str(p.partition) for p in twin.proposals])
            finally:
                cc2.shutdown()
            # the rebuild re-installed a fresh resident model
            assert store.to_json()["resident"]
        finally:
            cc.shutdown()

    def test_ladder_descent_below_fused_invalidates_store(self):
        _sim, cc, _clock = make_stack(
            solver_max_retries_per_rung=0,
            solver_retry_backoff_base_s=0.0)
        try:
            cc.optimizations()
            store = cc._model_store
            assert store.to_json()["resident"]
            plan = faults.FaultPlan().fail_nth("optimizer.execute",
                                               (1, 2, 3, 4))
            with faults.injected(plan):
                cc.optimizations(ignore_proposal_cache=True)
            assert store.invalidations >= 1
            assert not store.to_json()["resident"] or \
                store.invalidations >= 1
        finally:
            cc.shutdown()


# ---------------------------------------------------------------------------
# fleet fold: per-lane final states seed warm starts
# ---------------------------------------------------------------------------
@pytest.mark.fleet
class TestFoldedWarmSeeds:
    def test_result_from_outcome_rebuilds_final_state(self):
        from cruise_control_tpu.fleet.router import (FleetRouter,
                                                     FleetSolvePayload)
        from cruise_control_tpu.scenario.engine import ScenarioOutcome
        from cruise_control_tpu.scenario.spec import ScenarioSpec
        from cruise_control_tpu.testing.random_cluster import (
            RandomClusterSpec, random_cluster)
        state, _topo = random_cluster(RandomClusterSpec(
            num_brokers=4, num_partitions=8, replication_factor=2,
            num_racks=2, num_topics=2, seed=3))
        router = FleetRouter()
        payload = FleetSolvePayload(
            tenant_id="alpha", optimizer=GoalOptimizer([]),
            constraint=BalancingConstraint(),
            balancedness_weights=(1.1, 1.5),
            materialize=lambda: None, run_inline=lambda: None,
            commit=lambda r: None)
        fin_b = np.roll(np.asarray(state.replica_broker), 1)
        outcome = ScenarioOutcome(
            spec=ScenarioSpec(name="fleet:alpha"), feasible=True,
            final_placement=dict(
                replica_broker=fin_b,
                replica_is_leader=np.asarray(state.replica_is_leader)))
        result = router._result_from_outcome(payload, outcome, 0.1,
                                             lane_state=state)
        assert result.final_state is not None
        assert np.array_equal(
            np.asarray(result.final_state.replica_broker), fin_b)
        # membership fields come from the lane's own input state
        assert np.array_equal(
            np.asarray(result.final_state.replica_partition),
            np.asarray(state.replica_partition))

    def test_outcome_without_placement_keeps_no_state(self):
        from cruise_control_tpu.fleet.router import (FleetRouter,
                                                     FleetSolvePayload)
        from cruise_control_tpu.scenario.engine import ScenarioOutcome
        from cruise_control_tpu.scenario.spec import ScenarioSpec
        router = FleetRouter()
        payload = FleetSolvePayload(
            tenant_id="alpha", optimizer=GoalOptimizer([]),
            constraint=BalancingConstraint(),
            balancedness_weights=(1.1, 1.5),
            materialize=lambda: None, run_inline=lambda: None,
            commit=lambda r: None)
        outcome = ScenarioOutcome(spec=ScenarioSpec(name="x"),
                                  feasible=True)
        result = router._result_from_outcome(payload, outcome, 0.1,
                                             lane_state=None)
        assert result.final_state is None
