"""Trace-replay load harness + SLO observatory (cruise_control_tpu/
loadgen/, obs/slo.py, detector/slo_burn.py, tools/slo_gate.py).

The PR's acceptance pins:

* identical seed + profile => identical request sequence (the plan is a
  pure function; its sha256 digest is the pin);
* a seeded 2-second mixed-class replay against an IN-PROCESS demo rig
  (real facade, real HTTP server, real retrying client) produces an
  artifact that validates, whose per-class queue-wait vs device-time
  decomposition is non-empty (real span trees, not client clocks);
* the SLO gate passes the clean run against its own baseline and FAILS
  when a `sched.dispatch` latency fault (PR-2 harness) is injected;
* SLO burn state is visible on all three surfaces: STATE `sloStatus`,
  `/metrics` `cc_tpu_slo_*` series, and an SLO_BURN anomaly through
  the notifier.
"""
import importlib.util
import json
import pathlib
import time as _time

import conftest  # noqa: F401

import pytest

from cruise_control_tpu.detector.slo_burn import SloBurnDetector
from cruise_control_tpu.loadgen import (LoadHarness, build_plan,
                                        builtin_profile, parse_profile,
                                        plan_digest, validate_artifact)
from cruise_control_tpu.loadgen.profile import (OP_CLASS, ProfileError,
                                                rate_at)
from cruise_control_tpu.obs import recorder as obs_recorder
from cruise_control_tpu.obs import trace as obs_trace
from cruise_control_tpu.obs.recorder import FlightRecorder
from cruise_control_tpu.obs.slo import (ClassObjective, SloEvaluator,
                                        over_threshold)
from cruise_control_tpu.utils import faults
from cruise_control_tpu.utils.metrics import MetricRegistry

pytestmark = pytest.mark.loadgen


def _load_slo_gate():
    path = (pathlib.Path(conftest.__file__).parent.parent / "tools"
            / "slo_gate.py")
    spec = importlib.util.spec_from_file_location("cc_slo_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# profile + plan units (pure)
# ---------------------------------------------------------------------------
class TestProfile:
    def test_parse_roundtrip_and_validation(self):
        profile = parse_profile({
            "name": "p", "seed": 3, "clients": 2,
            "phases": [{"name": "a", "durationS": 5.0,
                        "rps": [[0.0, 1.0], [1.0, 3.0]],
                        "mix": {"rebalance": 2, "scenarios": 1}}]})
        again = parse_profile(json.dumps(profile.to_json()))
        assert again == profile
        assert profile.duration_s == 5.0

    def test_rejects_garbage(self):
        with pytest.raises(ProfileError, match="unknown op kind"):
            parse_profile({"phases": [{"durationS": 1,
                                       "mix": {"frobnicate": 1}}]})
        with pytest.raises(ProfileError, match="durationS"):
            parse_profile({"phases": [{"durationS": 0,
                                       "mix": {"rebalance": 1}}]})
        with pytest.raises(ProfileError, match="ascending"):
            parse_profile({"phases": [{"durationS": 1,
                                       "rps": [[0.5, 1], [0.2, 2]],
                                       "mix": {"rebalance": 1}}]})

    def test_rate_curve_interpolates(self):
        curve = ((0.0, 2.0), (0.5, 10.0), (1.0, 2.0))
        assert rate_at(curve, 0.0) == 2.0
        assert rate_at(curve, 0.25) == pytest.approx(6.0)
        assert rate_at(curve, 0.5) == 10.0
        assert rate_at(curve, 1.0) == 2.0

    def test_builtins_parse(self):
        for name in ("smoke", "soak-mixed", "fleet-churn"):
            profile = builtin_profile(name, duration_s=10.0)
            assert profile.phases
            assert profile.duration_s >= 3.0


class TestPlan:
    def test_same_seed_identical_sequence(self):
        """THE reproducibility pin: identical seed + profile =>
        byte-identical request sequence (arrivals, kinds, params,
        bodies); a different seed diverges."""
        p1 = builtin_profile("soak-mixed", duration_s=20.0, seed=11)
        p2 = builtin_profile("soak-mixed", duration_s=20.0, seed=11)
        d1, d2 = plan_digest(build_plan(p1)), plan_digest(build_plan(p2))
        assert d1 == d2
        p3 = builtin_profile("soak-mixed", duration_s=20.0, seed=12)
        assert plan_digest(build_plan(p3)) != d1

    def test_plan_shape(self):
        profile = builtin_profile("soak-mixed", duration_s=30.0,
                                  rps=8.0, seed=5)
        plan = build_plan(profile)
        assert plan, "empty plan"
        assert all(0.0 <= r.at_s <= profile.duration_s for r in plan)
        assert [r.at_s for r in plan] == sorted(r.at_s for r in plan)
        kinds = {r.kind for r in plan}
        # the mixed profile exercises every class + the delta stream
        assert {"rebalance", "scenarios", "heal", "precompute",
                "model_delta"} <= kinds
        for r in plan:
            assert r.klass == OP_CLASS[r.kind]
        # per-client sequences are contiguous
        for client in range(profile.clients):
            seqs = [r.seq for r in plan if r.client == client]
            assert sorted(seqs) == list(range(len(seqs)))


# ---------------------------------------------------------------------------
# SLO math units (pure)
# ---------------------------------------------------------------------------
class TestSloEvaluator:
    def hist(self, values, buckets=(0.1, 0.5, 2.0)):
        from cruise_control_tpu.utils.metrics import Histogram
        h = Histogram(buckets)
        for v in values:
            h.observe(v)
        return h.to_json()

    def test_over_threshold_rounds_down_conservatively(self):
        data = self.hist([0.05, 0.3, 0.7, 3.0])
        assert over_threshold(data, 2.0) == (4, 1)     # only the 3.0
        assert over_threshold(data, 0.5) == (4, 2)     # 0.7 + 3.0
        # threshold between boundaries rounds DOWN: 0.3 counts as over
        assert over_threshold(data, 0.4) == (4, 3)
        assert over_threshold(self.hist([]), 1.0) == (0, 0)

    def make_eval(self, registry, **kwargs):
        clock = {"now": 1000.0}
        ev = SloEvaluator(
            registry,
            objectives={"USER_INTERACTIVE": ClassObjective(
                latency_s=0.5, queue_wait_s=0.2, error_budget=0.1)},
            window_s=60.0, alert_threshold=2.0, min_refresh_s=0.0,
            time_fn=lambda: clock["now"], **kwargs)
        return ev, clock

    def test_burn_from_histogram_deltas(self):
        reg = MetricRegistry()
        ev, clock = self.make_eval(reg)
        base = ev.evaluate(force=True)
        assert base["status"] == "ok" and base["worstBurn"] == 0.0
        # 10 solves, 4 over the 0.5s device threshold: bad fraction
        # 0.4 / budget 0.1 = burn 4.0 -> breach (alert at 2.0)
        for v in (0.1, 0.1, 0.2, 0.3, 0.3, 0.4, 0.7, 0.8, 0.9, 1.0):
            reg.update_histogram("sched-device-busy-hist-"
                                 "user-interactive", v)
        clock["now"] += 10.0
        status = ev.evaluate(force=True)
        cls = status["classes"]["USER_INTERACTIVE"]
        assert cls["deviceTimeBurn"] == pytest.approx(4.0)
        assert cls["queueWaitBurn"] == 0.0
        assert cls["status"] == "breach"
        assert status["status"] == "breach"
        assert status["worstClass"] == "USER_INTERACTIVE"
        # queue-wait burn is the separate dimension
        for v in (0.3, 0.4):
            reg.update_histogram("sched-wait-hist-user-interactive", v)
        clock["now"] += 10.0
        status = ev.evaluate(force=True)
        assert status["classes"]["USER_INTERACTIVE"][
            "queueWaitBurn"] > 0.0

    def test_breach_ages_out_of_the_window(self):
        reg = MetricRegistry()
        ev, clock = self.make_eval(reg)
        ev.evaluate(force=True)
        for v in (0.7, 0.8):
            reg.update_histogram("sched-device-busy-hist-"
                                 "user-interactive", v)
        clock["now"] += 10.0
        assert ev.evaluate(force=True)["status"] == "breach"
        # no new observations: once the window rolls past the burst,
        # the delta is empty and the status recovers
        clock["now"] += 120.0
        ev.evaluate(force=True)
        clock["now"] += 1.0
        assert ev.evaluate(force=True)["status"] == "ok"

    def test_slo_burn_detector_fires_once_per_episode(self):
        reg = MetricRegistry()
        ev, clock = self.make_eval(reg)
        reported = []
        det = SloBurnDetector(ev, reported.append,
                              time_fn=lambda: clock["now"])
        det.detect_now()
        assert reported == []
        ev.evaluate(force=True)
        for v in (0.7, 0.8, 0.9):
            reg.update_histogram("sched-device-busy-hist-"
                                 "user-interactive", v)
        clock["now"] += 5.0
        det.detect_now()
        assert len(reported) == 1
        anomaly = reported[0]
        assert anomaly.scheduler_class == "USER_INTERACTIVE"
        assert anomaly.burn >= 2.0
        assert anomaly.device_time_burn >= anomaly.queue_wait_burn
        # still breaching: no duplicate report
        clock["now"] += 5.0
        det.detect_now()
        assert len(reported) == 1
        # recovery re-arms, relapse re-fires
        clock["now"] += 120.0
        det.detect_now()
        clock["now"] += 1.0
        det.detect_now()
        assert det.to_json()["breachedClasses"] == []
        for v in (0.7, 0.8, 0.9):
            reg.update_histogram("sched-device-busy-hist-"
                                 "user-interactive", v)
        clock["now"] += 1.0
        det.detect_now()
        assert len(reported) == 2

    def test_gauges_export_slo_series(self):
        reg = MetricRegistry()
        ev, clock = self.make_eval(reg)
        ev.attach_metrics(reg)
        from cruise_control_tpu.obs import export as obs_export
        text = obs_export.render_openmetrics(reg.to_json())
        assert "cc_tpu_slo_status" in text
        assert "cc_tpu_slo_burn_rate_user_interactive" in text
        assert "cc_tpu_slo_budget_remaining_user_interactive" in text


# ---------------------------------------------------------------------------
# gate units (pure, on synthetic artifacts)
# ---------------------------------------------------------------------------
class TestSloGate:
    def artifact(self, p99_ms=100.0, device_p99_ms=80.0, burn=0.0,
                 errors=0, rejected=0, total=50):
        return {
            "loadgenArtifact": 1,
            "profile": {"name": "t"}, "seed": 1,
            "planDigest": "0" * 64,
            "plannedRequests": total,
            "startedAtMs": 0.0, "wallS": 2.0,
            "requests": {"total": total, "ok": total - errors - rejected,
                         "errors": errors, "rejected": rejected,
                         "skipped": 0, "retries": 0,
                         "rejectedRate": rejected / total,
                         "byKind": {}, "schedulingLagP99Ms": 0.0},
            "latency": {"USER_INTERACTIVE": {
                "count": total, "p50Ms": p99_ms / 2, "p99Ms": p99_ms,
                "p999Ms": p99_ms, "maxMs": p99_ms}},
            "decomposition": {"USER_INTERACTIVE": {
                "traces": total,
                "queueWaitMs": {"p50": 1.0, "p99": 5.0, "mean": 2.0},
                "deviceMs": {"p50": device_p99_ms / 2,
                             "p99": device_p99_ms,
                             "mean": device_p99_ms / 2}}},
            "scheduler": {}, "sensorDeltas": {},
            "slo": {"enabled": True, "status":
                    "breach" if burn >= 2.0 else "ok",
                    "windowS": 300.0, "alertThreshold": 2.0,
                    "worstBurn": burn, "worstClass": None,
                    "classes": {"USER_INTERACTIVE": {
                        "objective": {}, "windowSolves": total,
                        "queueWaitBurn": 0.0, "deviceTimeBurn": burn,
                        "burn": burn,
                        "budgetRemaining": max(0.0, 1 - burn),
                        "status": "ok" if burn < 2.0 else "breach"}}},
            "metricsScrape": {"scraped": True},
            "errors": [],
        }

    def test_clean_passes_and_invalid_refused(self):
        gate = _load_slo_gate()
        art = self.artifact()
        assert validate_artifact(art) == []
        baseline = gate.distill_baseline(art)
        assert gate.gate(art, baseline) == []
        assert gate.gate({"nope": 1}, baseline)      # invalid artifact

    def test_breaches(self):
        gate = _load_slo_gate()
        art = self.artifact()
        baseline = gate.distill_baseline(art)
        # p99 regression
        slow = self.artifact(p99_ms=1000.0)
        assert any("p99 regressed" in b
                   for b in gate.gate(slow, baseline))
        # device-time regression alone (client p99 held flat)
        dev = self.artifact(device_p99_ms=500.0)
        assert any("device-time p99" in b
                   for b in gate.gate(dev, baseline))
        # burn breach needs no baseline at all
        hot = self.artifact(burn=3.0)
        assert any("SLO burn" in b for b in gate.gate(hot, None))
        # error rate
        bad = self.artifact(errors=10)
        assert any("error rate" in b for b in gate.gate(bad, baseline))
        # mismatched plan digest is flagged
        other = dict(baseline, planDigest="f" * 64)
        assert any("DIFFERENT plan" in b for b in gate.gate(art, other))


# ---------------------------------------------------------------------------
# the live smoke: seeded 2s replay against the in-process demo rig
# ---------------------------------------------------------------------------
class TestSmokeReplay:
    @pytest.fixture(scope="class")
    def rig(self):
        from cruise_control_tpu.loadgen.rig import build_demo_rig
        obs_trace.configure(enabled=True, sample_rate=1.0)
        obs_recorder.install(FlightRecorder(capacity=2048))
        # warm=True pre-compiles every program shape the smoke profile
        # touches, so the measured 2s window exercises serving
        demo = build_demo_rig()
        yield demo
        demo.shutdown()
        obs_recorder.install(FlightRecorder())

    def run_profile(self, demo, seed=7):
        profile = builtin_profile("smoke", duration_s=2.0, rps=4.0,
                                  seed=seed)
        harness = LoadHarness(demo.base_url, profile, rig=demo.rig,
                              request_timeout_s=120.0)
        return profile, harness.run()

    def test_smoke_replay_end_to_end(self, rig):
        """Acceptance: artifact validates, per-class decomposition is
        non-empty (REAL span trees), the same seed reproduces the
        request sequence, the gate passes clean and fails under an
        injected sched.dispatch latency fault."""
        gate = _load_slo_gate()
        profile, artifact = self.run_profile(rig)

        # 1. artifact schema validates
        assert validate_artifact(artifact) == [], \
            validate_artifact(artifact)
        requests = artifact["requests"]
        assert requests["total"] > 0 and requests["ok"] > 0
        assert requests["errors"] == 0, artifact["errors"]

        # 2. reproducibility: the artifact's digest IS the plan's, and
        # rebuilding the plan from the same profile reproduces it
        assert artifact["planDigest"] == plan_digest(build_plan(profile))

        # 3. per-class decomposition from real span trees
        decomposition = artifact["decomposition"]
        assert decomposition, "no span trees reached the artifact"
        assert "USER_INTERACTIVE" in decomposition
        ui = decomposition["USER_INTERACTIVE"]
        assert ui["traces"] > 0
        assert ui["deviceMs"]["p99"] > 0.0
        assert ui["queueWaitMs"]["p99"] >= 0.0

        # 4. SLO visible in the artifact + /metrics scrape summary
        assert artifact["slo"].get("enabled") is True
        assert "USER_INTERACTIVE" in artifact["slo"]["classes"]
        assert artifact["metricsScrape"]["scraped"] is True
        assert any("slo" in f for f in
                   artifact["metricsScrape"]["sloSeries"])

        # 5. the gate passes the clean run against its own baseline
        baseline = gate.distill_baseline(artifact)
        clean = gate.gate(artifact, baseline, p99_tolerance=1.2)
        assert clean == [], clean

        # 6. and FAILS when a latency fault inflates every dispatch
        # (PR-2 harness; 2s on a sub-second stack trips the 1.2x
        # tolerance for any clean p99 < 10s)
        plan = faults.FaultPlan()
        plan.hang_always("sched.dispatch", 2.0)
        with faults.injected(plan):
            _, faulted = self.run_profile(rig, seed=7)
        breaches = gate.gate(faulted, baseline, p99_tolerance=1.2)
        assert breaches, "gate passed the faulted run"
        assert any("regressed" in b or "SLO burn" in b
                   for b in breaches), breaches

    def test_slo_surfaces_state_metrics_anomaly(self, rig):
        """Acceptance: burn state visible on all three surfaces —
        STATE sloStatus, /metrics cc_tpu_slo_* series, and an SLO_BURN
        anomaly through the detector once burn crosses the alert
        threshold."""
        from cruise_control_tpu.core.anomaly import AnomalyType
        cc = rig.cc
        state = cc.state(["slo"])
        assert state["sloStatus"]["enabled"] is True
        assert "USER_INTERACTIVE" in state["sloStatus"]["classes"]
        page = __import__(
            "cruise_control_tpu.obs.export",
            fromlist=["render_for"]).render_for(cc)
        assert "cc_tpu_slo_status" in page
        assert "cc_tpu_slo_burn_rate_user_interactive" in page
        # force a breach through the REAL evaluator by tightening the
        # objective below latencies the rig has already recorded
        cc.slo_evaluator.objectives["USER_INTERACTIVE"] = \
            ClassObjective(latency_s=1e-4, queue_wait_s=1e-4,
                           error_budget=1e-3)
        cc.slo_evaluator._snapshots.clear()
        cc.slo_evaluator.evaluate(force=True)
        cc.optimizations(ignore_proposal_cache=True)
        _time.sleep(0.01)
        cc.slo_burn_detector.detect_now()
        assert cc.slo_burn_detector.reported > 0, \
            "SLO_BURN anomaly not reported"
        # it went through the DETECTOR plane (queued for the notifier,
        # nothing else reports on this idle rig) and is on the record
        assert cc.anomaly_detector.num_pending > 0
        assert AnomalyType.SLO_BURN.name in json.dumps(
            cc.anomaly_detector.to_json())
