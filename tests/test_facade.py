"""Facade tests: the wired stack end to end.

Models the reference's service-level tests (KafkaCruiseControl facade usage
in AnomalyDetectorTest/ExecutorTest) against the simulated cluster: model
building through the monitor, cached proposals, rebalance with execution,
add/remove/demote broker flows, and detector wiring.
"""
import conftest  # noqa: F401

import pytest

from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.core.anomaly import AnomalyType
from cruise_control_tpu.detector.notifier import SelfHealingNotifier
from cruise_control_tpu.facade import CruiseControl, OngoingExecutionError
from cruise_control_tpu.monitor.sampling.sampler import (
    SimulatedClusterSampler)


#: facade tests exercise the facade FLOW (model building, caching,
#: execution, detection wiring), not goal breadth — a four-goal stack
#: cuts the ~55 s/test pipeline tracing cost on the 1-core CI host ~4x
#: while test_goal_stack/test_random_goal_order keep the full default
#: stack covered
FACADE_TEST_GOALS = ["RackAwareGoal", "DiskCapacityGoal",
                     "ReplicaDistributionGoal",
                     "DiskUsageDistributionGoal"]


def make_stack(num_brokers=4, partitions=12, rf=2, skewed=True,
               notifier=None, assignment_pool=None, auto_warmup=False,
               goal_names=None, **cc_kwargs):
    """assignment_pool limits which brokers initially host replicas (e.g.
    a freshly added broker starts empty).

    auto_warmup defaults OFF under tests: the facade's production default
    (parallel AOT of every pipeline program before the first solve) made
    every facade/API test pay a full-stack compile — ~60 s each on the
    1-core CI host (round-3 VERDICT weak-5).  Lazily compiling only the
    programs a test actually runs keeps coverage while the dedicated
    warmup tests (test_optimizer warmup/auto-warmup cases) keep the AOT
    path exercised."""
    sim = SimulatedCluster()
    clock = {"now": 10_000.0}
    for b in range(num_brokers):
        sim.add_broker(b, rack=f"rack{b % 2}")
    pool = list(assignment_pool) if assignment_pool is not None \
        else list(range(num_brokers))
    assignments = []
    for p in range(partitions):
        if skewed:
            replicas = [pool[i % 2] for i in range(rf)]  # all on two brokers
        else:
            replicas = [pool[(p + i) % len(pool)] for i in range(rf)]
        assignments.append(replicas)
    sim.create_topic("t0", assignments, size_bytes=1e4)
    for p in range(partitions):
        sim.set_partition_load(TopicPartition("t0", p), leader_cpu=2.0,
                               nw_in=100.0, nw_out=300.0)

    cc = CruiseControl(
        sim, SimulatedClusterSampler(sim),
        anomaly_notifier=notifier,
        time_fn=lambda: clock["now"],
        sleep_fn=lambda s: (sim.advance(s),
                            clock.__setitem__("now", clock["now"] + s)),
        monitor_kwargs=dict(num_windows=3, window_ms=10_000,
                            min_samples_per_window=1,
                            sampling_interval_ms=5_000),
        executor_kwargs=dict(progress_check_interval_s=1.0),
        auto_warmup=auto_warmup,
        goal_names=list(goal_names or FACADE_TEST_GOALS),
        **cc_kwargs)
    return sim, cc, clock


def feed_samples(cc, clock, rounds=8):
    for _ in range(rounds):
        cc.load_monitor.task_runner.sample_once()
        clock["now"] += 10.0


class TestFacade:
    @pytest.mark.slow
    def test_full_default_goal_stack_smoke(self):
        """Facade wired with the PRODUCTION default goal list end to end
        (the other facade tests run the trimmed FACADE_TEST_GOALS stack
        for tracing economics — this one guards facade/goal-list wiring:
        registry instantiation, segment slicing, per-goal stats plumbing
        for the full 15-goal chain).  Marked slow; deselect with
        `-m "not slow"` for quick iterations."""
        from cruise_control_tpu.analyzer.goals.registry import \
            DEFAULT_GOAL_ORDER
        sim, cc, clock = make_stack(goal_names=DEFAULT_GOAL_ORDER)
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        result = cc.optimizations()
        assert [g.name
                for g in cc.goal_optimizer.goals] == DEFAULT_GOAL_ORDER
        assert set(result.stats_by_goal) == set(DEFAULT_GOAL_ORDER)
        assert not result.violated_goals_after
        cc.shutdown()

    def test_cluster_model_and_cached_proposals(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        state, topo = cc.cluster_model()
        assert state.num_brokers == 4
        r1 = cc.optimizations()
        r2 = cc.optimizations()          # same generation: cache hit
        assert r1 is r2
        feed_samples(cc, clock, rounds=1)  # new samples -> new generation
        r3 = cc.optimizations()
        assert r3 is not r1
        cc.shutdown()

    def test_rebalance_executes_and_balances(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        result = cc.rebalance(dryrun=False, wait=True)
        assert not result.dryrun and result.optimizer_result.proposals
        counts = {b: 0 for b in range(4)}
        for p in sim.describe_cluster().partitions:
            for r in p.replicas:
                counts[r] += 1
        assert all(v > 0 for v in counts.values())
        cc.shutdown()

    def test_dryrun_does_not_touch_cluster(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        gen_before = sim.describe_cluster().generation
        result = cc.rebalance(dryrun=True)
        assert result.dryrun and result.optimizer_result.proposals
        assert sim.describe_cluster().generation == gen_before
        cc.shutdown()

    def test_add_brokers_moves_only_onto_new(self):
        # broker 4 just joined: it hosts nothing yet
        sim, cc, clock = make_stack(num_brokers=5, skewed=False,
                                    assignment_pool=[0, 1, 2, 3])
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        # broker 4 treated as new: no old->old movement allowed
        result = cc.add_brokers([4], dryrun=True)
        assert result.optimizer_result.proposals
        for prop in result.optimizer_result.proposals:
            added = set(prop.replicas_to_add)
            assert added <= {4}, f"old->old move in {prop}"
        cc.shutdown()

    def test_remove_brokers_drains_target(self):
        sim, cc, clock = make_stack(num_brokers=4, skewed=False)
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        result = cc.remove_brokers([0], dryrun=False, wait=True)
        assert result.execution_uuid is not None
        snap = sim.describe_cluster()
        on_removed = [p for p in snap.partitions if 0 in p.replicas]
        assert not on_removed
        assert cc.executor.recently_removed_brokers() == {0}
        cc.shutdown()

    def test_demote_brokers_sheds_leadership(self):
        sim, cc, clock = make_stack(num_brokers=4, skewed=False)
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        result = cc.demote_brokers([0], dryrun=False, wait=True)
        snap = sim.describe_cluster()
        leaders = {p.leader for p in snap.partitions}
        assert 0 not in leaders
        # demotion only moves leadership, never replicas
        for prop in result.optimizer_result.proposals:
            assert not prop.replicas_to_add
        cc.shutdown()

    def test_ongoing_execution_rejected(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        # make the move huge & slow so the first execution stays in flight
        for p in range(12):
            sim.set_partition_load(TopicPartition("t0", p),
                                   leader_cpu=2.0, nw_in=100.0,
                                   nw_out=300.0, size_bytes=1e4)
        sim._move_rate = 1.0
        cc.rebalance(dryrun=False, wait=False)
        with pytest.raises(OngoingExecutionError):
            cc.rebalance(dryrun=False)
        cc.stop_execution(force=True)
        assert cc.executor.await_completion(timeout=30.0)
        cc.shutdown()

    def test_state_aggregation(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        st = cc.state()
        assert {"MonitorState", "ExecutorState", "AnalyzerState",
                "AnomalyDetectorState"} <= set(st)
        assert st["MonitorState"]["numValidWindows"] > 0
        assert st["ExecutorState"]["state"] == "NO_TASK_IN_PROGRESS"
        cc.shutdown()

    def test_self_healing_broker_failure_via_facade(self):
        sim2, cc, clock = make_stack(num_brokers=4, skewed=False)
        # swap in a notifier with zero grace periods on the shared clock
        cc.anomaly_detector._notifier = SelfHealingNotifier(
            self_healing_enabled={AnomalyType.BROKER_FAILURE: True},
            broker_failure_alert_threshold_ms=0.0,
            broker_failure_auto_fix_threshold_ms=0.0,
            time_fn=lambda: clock["now"])
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        cc.optimizations()
        sim2.kill_broker(3)
        clock["now"] += 1.0
        statuses = cc.anomaly_detector.process_all()
        assert any(s.name == "FIX_STARTED" for s in statuses), statuses
        cc.executor.await_completion(timeout=60.0)
        snap = sim2.describe_cluster()
        assert not [p for p in snap.partitions if 3 in p.replicas]
        cc.shutdown()


class TestProposalPrecompute:
    def test_precompute_warms_cache_and_expires(self):
        """Background precompute fills the proposal cache (reference
        GoalOptimizer.run loop); a warm cache answers without a new solve;
        expiry (proposal.expiration.ms) forces recompute even at the same
        model generation."""
        sim, cc, clock = make_stack()
        cc._proposal_expiration_s = 100.0
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)

        assert cc.precompute_proposals_once() is True
        with cc._cache_lock:
            cached = cc._cached_result
        assert cached is not None

        # warm cache: second pass is a no-op, optimizations() returns it
        assert cc.precompute_proposals_once() is False
        assert cc.optimizations() is cached

        # expiry: same generation, aged cache -> fresh solve
        clock["now"] += 101.0
        assert cc.precompute_proposals_once() is True
        with cc._cache_lock:
            assert cc._cached_result is not cached
        cc.shutdown()

    def test_precompute_skips_when_not_ready(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        # no samples yet: monitor not ready
        assert cc.precompute_proposals_once() is False
        cc.shutdown()

    def test_invalidation_during_solve_drops_result(self):
        """An execution starting while a (background) solve is in flight
        bumps the cache epoch; the solve must not store its pre-execution
        result afterwards."""
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        orig = cc.goal_optimizer.optimizations

        def hooked(*args, **kwargs):
            result = orig(*args, **kwargs)
            cc._invalidate_proposal_cache()   # execution races the solve
            return result

        cc.goal_optimizer.optimizations = hooked
        result = cc.optimizations()
        assert result.proposals is not None
        with cc._cache_lock:
            assert cc._cached_result is None   # stale result not stored
        cc.shutdown()
