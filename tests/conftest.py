"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on a
virtual 8-device CPU backend instead (same pattern the driver uses for the
multi-chip dry run).  Must run before any jax computation.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent compilation cache: the goal kernels recompile per optimizer
# instance otherwise, dominating test wall-clock.
#
# The cache is SPLIT by compile provenance: with the platform hook
# (sitecustomize from the axon site dir) present, CPU programs may be
# compiled by the remote compile service on a DIFFERENT x86 microarch
# (avx512/+prefer-no-scatter machine flags); a hook-stripped run
# (PYTHONPATH= python -m pytest ...) loading those AOT blobs SIGSEGVs
# (cpu_aot_loader: "Machine type used for XLA:CPU compilation doesn't
# match").  One cache dir per mode keeps both safe.
_suffix = "" if "sitecustomize" in sys.modules else "_localcpu"
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache" + _suffix)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

from cruise_control_tpu.testing.virtual_mesh import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

import warnings  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    # serial-run caveat (ADVICE round 5): one long-lived process
    # accumulating the whole suite's XLA:CPU programs can SEGFAULT on a
    # later big compile.  The per-module cache clearing below relieves
    # the pressure structurally, but distributing files across xdist
    # workers (pytest -n auto --dist loadfile) bounds it harder and is
    # the recommended way to run the full suite — see README "Testing".
    if hasattr(config, "workerinput"):
        return  # xdist worker: parallel run, nothing to warn about
    n = getattr(config.option, "numprocesses", None)
    if not n:
        warnings.warn(
            "running the suite serially (pytest-xdist absent or "
            "disabled): long single-process runs stress XLA:CPU — the "
            "per-module cache clearing in conftest.py mitigates the "
            "known segfault-after-many-compiles failure, but "
            "`pytest -n auto --dist loadfile` is the recommended full- "
            "suite invocation when pytest-xdist is installed",
            pytest.PytestConfigWarning, stacklevel=1)


@pytest.fixture(autouse=True, scope="module")
def _relieve_xla_process_pressure():
    # UNCONDITIONAL per-module cache clearing: after many accumulated
    # compiles in one long process, the next big XLA:CPU compile can
    # SEGFAULT (round 5: reproduced at four different full-stack tests
    # depending on ordering; each passes in a fresh process; ADVICE
    # round 5 reproduced it with three modules NONE of which were on the
    # previous hand-picked heavy-module list — correctness must not
    # depend on the exact file-to-worker assignment).  Dropping every
    # live executable/trace at each module boundary bounds per-process
    # program accumulation for ANY module ordering, serial or xdist; the
    # persistent disk cache keeps re-compiles cheap.
    from cruise_control_tpu.analyzer import optimizer as _opt
    with _opt._SHARED_LOCK:
        _opt._SHARED_PROGRAMS.clear()
        _opt._SHARED_LRU.clear()
        _opt._SHARED_AOT.clear()
    jax.clear_caches()
    # disarm the watched-dispatch watchdog and clear its executable
    # quarantine at each module boundary: a module that armed it
    # (test_meshhealth, chaos drills) must not leave the process-wide
    # switch set for unrelated modules' byte-identical pins
    from cruise_control_tpu.parallel import health as _health
    _health.configure_watchdog(enabled=False, deadline_ms=0.0)
    _health.clear_quarantine()
    yield
