"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on a
virtual 8-device CPU backend instead (same pattern the driver uses for the
multi-chip dry run).  Must run before any jax computation.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent compilation cache: the goal kernels recompile per optimizer
# instance otherwise, dominating test wall-clock.
#
# The cache is SPLIT by compile provenance: with the platform hook
# (sitecustomize from the axon site dir) present, CPU programs may be
# compiled by the remote compile service on a DIFFERENT x86 microarch
# (avx512/+prefer-no-scatter machine flags); a hook-stripped run
# (PYTHONPATH= python -m pytest ...) loading those AOT blobs SIGSEGVs
# (cpu_aot_loader: "Machine type used for XLA:CPU compilation doesn't
# match").  One cache dir per mode keeps both safe.
_suffix = "" if "sitecustomize" in sys.modules else "_localcpu"
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache" + _suffix)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

from cruise_control_tpu.testing.virtual_mesh import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

import pytest  # noqa: E402

#: modules that compile FULL multi-goal pipelines (big XLA:CPU programs):
#: after many accumulated compiles in one long suite process, the next
#: big compile can SEGFAULT inside XLA:CPU (reproduced three times in
#: round 5, each at a different full-stack test depending on ordering —
#: test_goal_stack, test_parallel, test_random_goal_order; each passes
#: solo).  Dropping every live executable/trace before these modules
#: relieves the process pressure; the persistent disk cache keeps the
#: re-compiles cheap.
_HEAVY_PIPELINE_MODULES = {
    "test_goal_stack", "test_parallel", "test_random_goal_order",
    "test_facade", "test_differential_reference",
}


@pytest.fixture(autouse=True, scope="module")
def _relieve_xla_process_pressure(request):
    name = request.module.__name__.rsplit(".", 1)[-1]
    if name in _HEAVY_PIPELINE_MODULES:
        from cruise_control_tpu.analyzer import optimizer as _opt
        _opt._SHARED_PROGRAMS.clear()
        _opt._SHARED_LRU.clear()
        jax.clear_caches()
    yield
