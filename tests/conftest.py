"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on a
virtual 8-device CPU backend instead (same pattern the driver uses for the
multi-chip dry run).  Must run before any jax computation.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent compilation cache: the goal kernels recompile per optimizer
# instance otherwise, dominating test wall-clock
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

from cruise_control_tpu.testing.virtual_mesh import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
