"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on a
virtual 8-device CPU backend instead (same pattern the driver uses for the
multi-chip dry run).  Must run before jax is imported anywhere.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# persistent compilation cache: the goal kernels recompile per optimizer
# instance otherwise, dominating test wall-clock
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# An environment hook (e.g. a TPU-plugin sitecustomize) may import jax at
# interpreter startup, in which case jax has already read JAX_PLATFORMS /
# cache env vars and the assignments above are no-ops.  Force the config
# directly — backends are created lazily, so this still takes effect as
# long as no jax computation ran yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
assert jax.default_backend() == "cpu", jax.default_backend()
