"""Window-rotation salt: int32 safety under large-magnitude loads.

Regression pin for ADVICE round 5 (kernels.py salt_r): the old salt cast
an unreduced float mix straight to int32 — for deployments whose loads
are stored in large absolute units the cast SATURATED to INT32_MAX on
every round, freezing the rotation salt and re-creating the
vetoed-occupant starvation the rotation was added to prevent.
kernels.rotation_salt now reduces modulo 2**31 before the cast and mixes
in an integral leader-count term, so the salt changes on every committed
transfer even when f32 absorption swallows the load delta.
"""
import numpy as np

import conftest  # noqa: F401

import jax.numpy as jnp

from cruise_control_tpu.analyzer.kernels import rotation_salt

INT32_MAX = np.iinfo(np.int32).max


def _transfer(lc, src, dst):
    """Leader counts after one leadership transfer src→dst broker."""
    return lc.at[src].add(-1).at[dst].add(1)


def test_salt_does_not_saturate_on_large_loads():
    # large-magnitude loads (e.g. raw bytes): the old formula's float
    # mix exceeded int32 range and the cast saturated to a constant
    lc = jnp.asarray(np.full(64, 1000, np.int32))
    load = jnp.asarray(np.linspace(1e10, 9e10, 64), dtype=jnp.float32)
    s = int(rotation_salt(lc, load))
    assert s != INT32_MAX and s != -INT32_MAX - 1


def test_salt_changes_per_commit_despite_float_absorption():
    # a single ±1 leader-count commit against a HUGE load sum: the f32
    # term absorbs the delta entirely, so only the integral term can
    # rotate the window — the salt must still change every step
    lc = jnp.asarray(np.full(128, 50_000, np.int32))
    load = jnp.asarray(np.full(128, 7e11), dtype=jnp.float32)
    salts = []
    rng = np.random.RandomState(7)
    for _ in range(6):
        salts.append(int(rotation_salt(lc, load)))
        src, dst = rng.choice(128, size=2, replace=False)
        lc = _transfer(lc, int(src), int(dst))
    assert len(set(salts)) == len(salts), (
        f"rotation salt repeated across distinct states: {salts}")
    assert INT32_MAX not in salts


def test_salt_changes_with_moderate_loads_too():
    # the pre-fix behavior was correct at moderate magnitudes — keep it
    lc = jnp.asarray(np.arange(16, dtype=np.int32))
    load = jnp.asarray(np.linspace(0.0, 40.0, 16), dtype=jnp.float32)
    s1 = int(rotation_salt(lc, load))
    s2 = int(rotation_salt(_transfer(lc, 3, 9), load))
    s3 = int(rotation_salt(lc, load * 1.01))
    assert s1 != s2          # integral term sees the commit
    assert s1 != s3          # float term sees load movement
