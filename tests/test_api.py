"""REST API tests.

Models the reference's servlet tests (parameter validation per
*Parameters class, ResponseTest schema walk, security integration tests,
purgatory/2-step flow) against the transport-free dispatch core, plus one
real HTTP round-trip through the stdlib server.
"""
import json
import time
import urllib.request

import conftest  # noqa: F401
import pytest

from cruise_control_tpu.api import (BasicSecurityProvider, ParameterError,
                                    Purgatory, QueryParams, Role,
                                    TokenSecurityProvider,
                                    USER_TASK_ID_HEADER, UserTaskManager)
from cruise_control_tpu.api.security import (AuthenticationError,
                                             AuthorizationError)
from cruise_control_tpu.api.server import CruiseControlApp

from test_facade import feed_samples, make_stack


class TestQueryParams:
    def test_unknown_param_rejected(self):
        with pytest.raises(ParameterError):
            QueryParams("REBALANCE", {"no_such_param": ["1"]})

    def test_typed_accessors(self):
        p = QueryParams("REBALANCE", {
            "dryrun": ["false"], "goals": ["RackAwareGoal,DiskCapacityGoal"],
            "concurrent_leader_movements": ["12"],
            "replication_throttle": ["1.5e6"]})
        assert p.get_bool("dryrun", default=True) is False
        assert p.get_csv("goals") == ["RackAwareGoal", "DiskCapacityGoal"]
        assert p.get_int("concurrent_leader_movements") == 12
        assert p.get_float("replication_throttle") == 1.5e6

    def test_bad_values(self):
        with pytest.raises(ParameterError):
            QueryParams("REBALANCE", {"dryrun": ["maybe"]}).get_bool("dryrun")
        with pytest.raises(ParameterError):
            QueryParams("ADD_BROKER",
                        {"brokerid": ["x"]}).get_csv_ints("brokerid")


class TestSecurity:
    def test_basic_auth_roles(self):
        import base64
        provider = BasicSecurityProvider({
            "admin": ("secret", Role.ADMIN),
            "viewer": ("pw", Role.VIEWER)})

        def hdr(user, pw):
            tok = base64.b64encode(f"{user}:{pw}".encode()).decode()
            return {"Authorization": f"Basic {tok}"}

        admin = provider.authenticate(hdr("admin", "secret"))
        assert admin.role == Role.ADMIN
        provider.authorize(admin, "REBALANCE")   # no raise
        viewer = provider.authenticate(hdr("viewer", "pw"))
        with pytest.raises(AuthorizationError):
            provider.authorize(viewer, "REBALANCE")
        provider.authorize(viewer, "STATE")
        with pytest.raises(AuthenticationError):
            provider.authenticate(hdr("admin", "wrong"))
        with pytest.raises(AuthenticationError):
            provider.authenticate({})

    def test_token_provider_roundtrip_and_expiry(self):
        clock = {"t": 1000.0}
        provider = TokenSecurityProvider(b"k3y", time_fn=lambda: clock["t"])
        token = provider.issue("alice", Role.USER, ttl_s=60.0)
        p = provider.authenticate({"Authorization": f"Bearer {token}"})
        assert p.name == "alice" and p.role == Role.USER
        clock["t"] += 61.0
        with pytest.raises(AuthenticationError):
            provider.authenticate({"Authorization": f"Bearer {token}"})
        with pytest.raises(AuthenticationError):
            provider.authenticate(
                {"Authorization": f"Bearer {token[:-2]}xx"})


class TestPurgatory:
    def test_review_flow(self):
        purgatory = Purgatory()
        req = purgatory.submit("REBALANCE", "dryrun=false", "alice")
        assert req.status.value == "PENDING_REVIEW"
        purgatory.review([req.review_id], [], reason="lgtm")
        taken = purgatory.take_approved(req.review_id, "REBALANCE",
                                        "dryrun=false")
        assert taken.status.value == "SUBMITTED"
        # one-shot: cannot take again
        with pytest.raises(ValueError):
            purgatory.take_approved(req.review_id, "REBALANCE",
                                    "dryrun=false")

    def test_re_arm_restores_consumed_approval(self):
        """A 429-rejected submission consumed its one-shot approval
        without ever executing — re_arm rolls it back to APPROVED so the
        client's automatic retry is not burned on a dead review."""
        purgatory = Purgatory()
        req = purgatory.submit("REBALANCE", "dryrun=false", "alice")
        purgatory.review([req.review_id], [], reason="lgtm")
        purgatory.take_approved(req.review_id, "REBALANCE", "dryrun=false")
        purgatory.re_arm(req.review_id)
        assert req.status.value == "APPROVED"
        taken = purgatory.take_approved(req.review_id, "REBALANCE",
                                        "dryrun=false")
        assert taken.status.value == "SUBMITTED"
        # no-ops: a not-yet-consumed review and an unknown id
        req2 = purgatory.submit("REBALANCE", "", "bob")
        purgatory.re_arm(req2.review_id)
        assert req2.status.value == "PENDING_REVIEW"
        purgatory.re_arm(999999)

    def test_discard_and_wrong_endpoint(self):
        purgatory = Purgatory()
        req = purgatory.submit("REMOVE_BROKER", "brokerid=1", "bob")
        purgatory.review([], [req.review_id])
        with pytest.raises(ValueError):
            purgatory.take_approved(req.review_id, "REMOVE_BROKER",
                                    "brokerid=1")
        req2 = purgatory.submit("REBALANCE", "", "bob")
        purgatory.review([req2.review_id], [])
        with pytest.raises(ValueError):
            purgatory.take_approved(req2.review_id, "REMOVE_BROKER", "")

    def test_approval_bound_to_parameters(self):
        # an approval for a dry run must not authorize a live run
        purgatory = Purgatory()
        req = purgatory.submit("REBALANCE", "dryrun=true", "mallory")
        purgatory.review([req.review_id], [])
        with pytest.raises(ValueError):
            purgatory.take_approved(req.review_id, "REBALANCE",
                                    "dryrun=false")
        # review_id itself is excluded from the comparison
        taken = purgatory.take_approved(
            req.review_id, "REBALANCE",
            f"dryrun=true&review_id={req.review_id}")
        assert taken.status.value == "SUBMITTED"


class TestUserTaskManager:
    def test_attach_by_same_request(self):
        utm = UserTaskManager()
        calls = []

        def op():
            calls.append(1)
            time.sleep(0.2)
            return {"ok": True}

        a = utm.get_or_create("PROPOSALS", "q=1", "client", op)
        b = utm.get_or_create("PROPOSALS", "q=1", "client", op)
        assert a.task_id == b.task_id
        assert a.future.result(timeout=5.0) == {"ok": True}
        assert calls == [1]
        utm.shutdown()

    def test_lookup_by_task_id(self):
        utm = UserTaskManager()
        info = utm.get_or_create("PROPOSALS", "q=1", "c", lambda: 42)
        same = utm.get_or_create("PROPOSALS", "q=1", "c2", lambda: 0,
                                 task_id=info.task_id)
        assert same.task_id == info.task_id
        with pytest.raises(KeyError):
            utm.get_or_create("PROPOSALS", "q=1", "c", lambda: 0,
                              task_id="nope")
        # a task id is scoped to its request: attaching it to a different
        # endpoint or query must fail rather than return the wrong result
        with pytest.raises(ValueError):
            utm.get_or_create("REBALANCE", "dryrun=false", "c", lambda: 0,
                              task_id=info.task_id)
        utm.shutdown()


def make_app(**kwargs):
    sim, cc, clock = make_stack(num_brokers=4, skewed=True)
    cc.start_up(do_sampling=False, start_detection=False)
    feed_samples(cc, clock)
    app = CruiseControlApp(cc, async_response_timeout_s=30.0, **kwargs)
    return sim, cc, app


class TestDispatch:
    def test_state_endpoint(self):
        sim, cc, app = make_app()
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/state")
        assert status == 200
        assert body["MonitorState"]["numValidWindows"] > 0
        cc.shutdown()

    def test_kafka_cluster_state(self):
        sim, cc, app = make_app()
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/kafka_cluster_state")
        assert status == 200
        counts = body["KafkaBrokerState"]["ReplicaCountByBrokerId"]
        assert sum(counts.values()) == 24    # 12 partitions × rf 2
        cc.shutdown()

    def test_load_and_partition_load(self):
        sim, cc, app = make_app()
        status, _, body = self._poll(
            app, "GET", "/kafkacruisecontrol/load")
        assert status == 200 and len(body["brokers"]) == 4
        status, _, body = self._poll(
            app, "GET", "/kafkacruisecontrol/partition_load",
            "resource=nw_in&entries=5")
        assert status == 200 and len(body["records"]) == 5
        cc.shutdown()

    @staticmethod
    def _poll(app, method, path, query="", deadline_s=600.0):
        """Async client behavior: re-request with the User-Task-ID header
        until the operation completes."""
        headers = {}
        end = time.time() + deadline_s
        while True:
            status, hdrs, body = app.handle_request(method, path, query,
                                                    headers)
            if status != 202:
                return status, hdrs, body
            assert USER_TASK_ID_HEADER in hdrs
            headers = {USER_TASK_ID_HEADER: hdrs[USER_TASK_ID_HEADER]}
            assert time.time() < end, "operation never completed"
            time.sleep(0.2)

    def test_proposals_and_rebalance_roundtrip(self):
        sim, cc, app = make_app()
        status, hdrs, body = self._poll(
            app, "GET", "/kafkacruisecontrol/proposals", "verbose=true")
        assert status == 200, body
        assert body["summary"]["numProposals"] > 0
        status, _, body = self._poll(
            app, "POST", "/kafkacruisecontrol/rebalance", "dryrun=false")
        assert status == 200, body
        assert body["dryRun"] is False and body.get("executionId")
        cc.executor.await_completion(timeout=60.0)
        counts = {}
        for p in sim.describe_cluster().partitions:
            for r in p.replicas:
                counts[r] = counts.get(r, 0) + 1
        assert len(counts) == 4
        cc.shutdown()

    def test_unknown_endpoint_and_params(self):
        sim, cc, app = make_app()
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/nonsense")
        assert status == 404
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "bogus=1")
        assert status == 400 and "bogus" in body["errorMessage"]
        # GET on a POST endpoint
        status, _, _ = app.handle_request(
            "GET", "/kafkacruisecontrol/rebalance")
        assert status == 405
        cc.shutdown()

    def test_admin_self_healing_toggle(self):
        sim, cc, app = make_app()
        status, _, body = app.handle_request(
            "POST", "/kafkacruisecontrol/admin",
            "enable_self_healing_for=broker_failure")
        assert status == 200
        assert body["selfHealing"]["BROKER_FAILURE"]["after"] is True
        cc.shutdown()

    def test_topic_configuration_rf_change(self):
        sim, cc, app = make_app()
        status, _, body = app.handle_request(
            "POST", "/kafkacruisecontrol/topic_configuration",
            "topic=t0&replication_factor=3&dryrun=false&verbose=true")
        assert status == 200, body
        cc.executor.await_completion(timeout=120.0)
        snap = sim.describe_cluster()
        for p in snap.partitions_of("t0"):
            assert len(p.replicas) == 3
            racks = {sim._brokers[b].rack for b in p.replicas}
            assert len(racks) == 2   # both racks covered
        cc.shutdown()

    def test_two_step_verification_flow(self):
        sim, cc, app = make_app(two_step_verification=True)
        # POST without review id parks in purgatory
        status, _, body = app.handle_request(
            "POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
        assert status == 202 and "reviewResult" in body
        rid = body["reviewResult"]["Id"]
        # approve then re-submit with the review id
        status, _, body = app.handle_request(
            "POST", "/kafkacruisecontrol/review", f"approve={rid}")
        assert status == 200
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/review_board")
        assert status == 200 and body["requestInfo"][0]["Status"] \
            == "APPROVED"
        status, _, body = self._poll(
            app, "POST", "/kafkacruisecontrol/rebalance",
            f"dryrun=true&review_id={rid}")
        assert status == 200 and body["summary"]["numProposals"] > 0
        cc.shutdown()

    def test_two_step_gates_sync_posts_and_binds_params(self):
        sim, cc, app = make_app(two_step_verification=True)
        # sync mutating POST parks too
        status, _, body = app.handle_request(
            "POST", "/kafkacruisecontrol/pause_sampling", "reason=x")
        assert status == 202 and "reviewResult" in body
        rid = body["reviewResult"]["Id"]
        app.handle_request("POST", "/kafkacruisecontrol/review",
                           f"approve={rid}")
        # approval is bound to the reviewed parameters
        status, _, body = app.handle_request(
            "POST", "/kafkacruisecontrol/pause_sampling",
            f"reason=other&review_id={rid}")
        assert status == 400
        status, _, body = app.handle_request(
            "POST", "/kafkacruisecontrol/pause_sampling",
            f"reason=x&review_id={rid}")
        assert status == 200
        cc.shutdown()

    def test_security_enforced_in_dispatch(self):
        import base64
        provider = BasicSecurityProvider({"v": ("pw", Role.VIEWER)})
        sim, cc, app = make_app(security=provider)
        tok = base64.b64encode(b"v:pw").decode()
        hdrs = {"Authorization": f"Basic {tok}"}
        status, _, _ = app.handle_request(
            "GET", "/kafkacruisecontrol/state", headers=hdrs)
        assert status == 200
        status, _, _ = app.handle_request(
            "POST", "/kafkacruisecontrol/rebalance", headers=hdrs)
        assert status == 403
        status, _, _ = app.handle_request(
            "GET", "/kafkacruisecontrol/state")
        assert status == 401
        cc.shutdown()

    def test_http_transport_roundtrip(self):
        import logging
        sim, cc, app = make_app()
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = _Capture()
        access = logging.getLogger("accessLogger")
        prior_level = access.level
        try:
            access.addHandler(handler)
            access.setLevel(logging.INFO)
            port = app.start(port=0)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/kafkacruisecontrol/state",
                    timeout=30) as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
                assert "MonitorState" in body
        finally:
            app.stop()
            cc.shutdown()
            access.removeHandler(handler)
            access.setLevel(prior_level)
        # NCSA access line: host - - [time] "GET /path HTTP/1.1" 200 -
        assert any('"GET /kafkacruisecontrol/state' in line
                   and " 200 " in line for line in records), records


class TestSensors:
    def test_sensors_substate_exports_registry(self):
        sim, cc, app = make_app()
        self_poll = TestDispatch._poll
        self_poll(app, "GET", "/kafkacruisecontrol/proposals")
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "substates=sensors")
        assert status == 200
        sensors = body["Sensors"]
        assert sensors["proposal-computation-timer"]["count"] >= 1
        assert sensors["cluster-model-creation-timer"]["count"] >= 1
        assert sensors["PROPOSALS-request-rate"]["count"] >= 1
        assert "balancedness-score" in sensors
        cc.shutdown()
