"""Intra-broker (JBOD) goal tests.

Models the reference's IntraBrokerRebalanceTest.java (151 LoC): replicas
move between a broker's logdirs to satisfy per-disk capacity and to balance
disk usage, never leaving the broker.
"""
import conftest  # noqa: F401

import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.intra_broker import (
    IntraBrokerDiskCapacityGoal, IntraBrokerDiskUsageDistributionGoal)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model import state as S
from cruise_control_tpu.model.builder import ClusterModelBuilder

CAPACITY = {Resource.CPU: 100.0, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
            Resource.DISK: 10_000.0}


def jbod_skewed(disk_caps=(1000.0, 1000.0), sizes=(400.0, 300.0, 200.0)):
    """One broker with two logdirs; everything piled on /d0."""
    b = ClusterModelBuilder()
    disks = {f"/d{i}": c for i, c in enumerate(disk_caps)}
    b.add_broker(0, "A", CAPACITY, disks=disks)
    b.add_broker(1, "B", CAPACITY, disks=disks)
    for p, size in enumerate(sizes):
        load = {Resource.CPU: 1.0, Resource.NW_IN: 10.0,
                Resource.NW_OUT: 10.0, Resource.DISK: size}
        b.add_replica("T", p, 0, True, load, logdir="/d0")
        follower = dict(load)
        follower[Resource.NW_OUT] = 0.0
        b.add_replica("T", p, 1, False, follower, logdir="/d0")
    return b.build()


def _ctx(state, topo):
    return make_context(state, BalancingConstraint(), OptimizationOptions(),
                        topo)


class TestIntraBrokerCapacity:
    def test_overfull_disk_sheds_to_sibling(self):
        state, topo = jbod_skewed(sizes=(400.0, 300.0, 200.0))
        # /d0 on each broker holds 900 > 0.8 * 1000
        goal = IntraBrokerDiskCapacityGoal(capacity_threshold=0.8)
        ctx = _ctx(state, topo)
        cache = make_round_cache(state)
        assert np.asarray(goal.violated_brokers(state, ctx, cache)).all()
        out = goal.optimize(state, ctx, ())
        cache2 = make_round_cache(out)
        assert not np.asarray(goal.violated_brokers(out, ctx, cache2)).any()
        # brokers unchanged: intra-broker only
        assert (np.asarray(out.replica_broker)
                == np.asarray(state.replica_broker)).all()
        dload = np.asarray(S.disk_load(out))
        assert (dload <= 800.0 + 1e-3).all()

    def test_respects_dead_disk(self):
        state, topo = jbod_skewed()
        # kill /d1 everywhere: nothing can move, goal stays violated
        for d in range(state.num_disks):
            if topo.disk_names[d][1] == "/d1":
                state = S.mark_disk_dead(state, d)
        goal = IntraBrokerDiskCapacityGoal(capacity_threshold=0.8)
        ctx = _ctx(state, topo)
        out = goal.optimize(state, ctx, ())
        disk_of = np.asarray(out.replica_disk)
        alive = np.asarray(out.disk_alive)
        valid = np.asarray(out.replica_valid) & (disk_of >= 0)
        # no replica may land on a dead disk
        assert alive[disk_of[valid]].all() or not valid.any()


class TestIntraBrokerDistribution:
    def test_balances_between_logdirs(self):
        state, topo = jbod_skewed(sizes=(300.0, 280.0, 260.0, 240.0))
        goal = IntraBrokerDiskUsageDistributionGoal(balance_margin=0.2)
        ctx = _ctx(state, topo)
        dload0 = np.asarray(S.disk_load(state))
        out = goal.optimize(state, ctx, ())
        dload1 = np.asarray(S.disk_load(out))
        # spread improved on each broker (both started one-sided)
        d0 = dload1.reshape(2, 2)
        assert (abs(d0[:, 0] - d0[:, 1])
                < abs(dload0.reshape(2, 2)[:, 0]
                      - dload0.reshape(2, 2)[:, 1])).all()
        assert (np.asarray(out.replica_broker)
                == np.asarray(state.replica_broker)).all()

    def test_proposals_carry_logdir_moves(self):
        from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
        state, topo = jbod_skewed(sizes=(400.0, 300.0, 200.0))
        opt = GoalOptimizer([IntraBrokerDiskCapacityGoal(
            capacity_threshold=0.8)])
        result = opt.optimizations(state, topo)
        assert result.proposals
        intra = [p for p in result.proposals
                 if not p.has_replica_action
                 and any(o.logdir != n.logdir
                         for o, n in zip(p.old_replicas, p.new_replicas))]
        assert intra, "expected logdir-only proposals"
