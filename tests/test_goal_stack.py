"""Full goal-stack tests: hard goals, rack awareness, count distribution,
priority ordering with acceptance stacking (analogs of the reference's
DeterministicClusterTest / RandomClusterTest / RandomSelfHealingTest)."""
import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.capacity import (DiskCapacityGoal,
                                                        ReplicaCapacityGoal)
from cruise_control_tpu.analyzer.goals.count_distribution import (
    LeaderReplicaDistributionGoal, ReplicaDistributionGoal)
from cruise_control_tpu.analyzer.goals.network import (
    PreferredLeaderElectionGoal)
from cruise_control_tpu.analyzer.goals.rack_aware import RackAwareGoal
from cruise_control_tpu.analyzer.goals.registry import (DEFAULT_GOAL_ORDER,
                                                        default_goals,
                                                        make_goal)
from cruise_control_tpu.analyzer.optimizer import (GoalOptimizer,
                                                   OptimizationFailure)
from cruise_control_tpu.common.resources import Resource as R
from cruise_control_tpu.model import state as S
from cruise_control_tpu.testing import fixtures
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)
from cruise_control_tpu.testing.verifier import run_and_verify


def test_rack_aware_fixes_satisfiable():
    state, topo = fixtures.rack_aware_satisfiable()
    goal = RackAwareGoal()
    assert goal.is_satisfiable(state)
    opt = GoalOptimizer([goal])
    result = run_and_verify(opt, state, topo)
    prc = np.asarray(S.partition_rack_count(result.final_state))
    assert prc.max() == 1, "rack awareness not satisfied"


def test_rack_aware_unsatisfiable_detected():
    state, topo = fixtures.rack_aware_unsatisfiable()
    goal = RackAwareGoal()
    assert not goal.is_satisfiable(state)
    opt = GoalOptimizer([goal])
    with pytest.raises(OptimizationFailure):
        opt.optimizations(state, topo)


def test_replica_capacity_goal():
    spec = RandomClusterSpec(num_brokers=10, num_partitions=100,
                             replication_factor=2, num_racks=5, seed=2,
                             skew_fraction=0.6, skew_brokers=2)
    state, topo = random_cluster(spec)
    counts = np.asarray(S.broker_replica_count(state))
    limit = int(np.ceil(counts.mean())) + 2
    constraint = BalancingConstraint(max_replicas_per_broker=limit)
    opt = GoalOptimizer([ReplicaCapacityGoal()], constraint)
    result = run_and_verify(opt, state, topo)
    after = np.asarray(S.broker_replica_count(result.final_state))
    assert after.max() <= limit


def test_disk_capacity_goal_hard_failure():
    # tiny capacities that cannot fit the load anywhere -> hard failure
    from cruise_control_tpu.model.builder import ClusterModelBuilder
    b = ClusterModelBuilder()
    cap = {R.CPU: 100, R.NW_IN: 1e4, R.NW_OUT: 1e4, R.DISK: 100.0}
    for i in range(3):
        b.add_broker(i, "A", cap)
    for p in range(6):
        b.add_partition("T", p, p % 3, [(p + 1) % 3],
                        {R.CPU: 1, R.NW_IN: 10, R.NW_OUT: 10, R.DISK: 90.0})
    state, topo = b.build()
    opt = GoalOptimizer([DiskCapacityGoal()])
    with pytest.raises(OptimizationFailure):
        opt.optimizations(state, topo)


def test_replica_distribution_goal():
    spec = RandomClusterSpec(num_brokers=12, num_partitions=240,
                             replication_factor=2, num_racks=4, seed=9,
                             skew_fraction=0.5, skew_brokers=3)
    state, topo = random_cluster(spec)
    before = np.asarray(S.broker_replica_count(state))
    opt = GoalOptimizer([ReplicaDistributionGoal(max_rounds=128)])
    result = run_and_verify(opt, state, topo)
    after = np.asarray(S.broker_replica_count(result.final_state))
    assert after.std() <= before.std()
    avg = after.mean()
    assert after.max() <= np.ceil(max(avg * 1.09, avg + 1)) + 1e-6


def test_leader_distribution_goal():
    state, topo = fixtures.unbalanced_cluster()
    opt = GoalOptimizer([LeaderReplicaDistributionGoal()])
    result = run_and_verify(opt, state, topo)
    leaders = np.asarray(S.broker_leader_count(result.final_state))
    assert leaders[0] <= 3, f"leader counts still skewed: {leaders}"
    # leadership-only rebalance: no replica actually moved brokers
    assert result.num_replica_movements == 0


def test_preferred_leader_election():
    state, topo = fixtures.unbalanced_cluster()
    # move some leadership away first
    import jax.numpy as jnp
    part = np.asarray(state.replica_partition)
    lead = np.asarray(state.replica_is_leader)
    src = int(np.nonzero((part == 0) & lead)[0][0])
    dst = int(np.nonzero((part == 0) & ~lead)[0][0])
    state2 = S.transfer_leadership(state, jnp.asarray(src), jnp.asarray(dst))
    opt = GoalOptimizer([PreferredLeaderElectionGoal()])
    result = opt.optimizations(state2, topo)
    # leadership restored to the original (preferred) replica
    final_lead = np.asarray(result.final_state.replica_is_leader)
    assert final_lead[src] and not final_lead[dst]


@pytest.mark.slow
def test_full_default_stack_small():
    spec = RandomClusterSpec(num_brokers=16, num_partitions=200,
                             replication_factor=3, num_racks=4,
                             num_topics=6, seed=21, skew_fraction=0.4)
    state, topo = random_cluster(spec)
    goals = default_goals(max_rounds=48)
    opt = GoalOptimizer(goals)
    result = run_and_verify(opt, state, topo)
    # hard goals all satisfied
    ctx = make_context(result.final_state, opt.constraint,
                       OptimizationOptions(), topo)
    cache = make_round_cache(result.final_state)
    for goal in goals:
        if goal.is_hard:
            v = np.asarray(goal.violated_brokers(result.final_state, ctx,
                                                 cache))
            assert not v.any(), f"{goal.name} violated after full stack"
    # acceptance stacking preserved rack awareness through later goals
    prc = np.asarray(S.partition_rack_count(result.final_state))
    assert prc.max() == 1


@pytest.mark.slow
def test_full_stack_self_healing_random():
    spec = RandomClusterSpec(num_brokers=16, num_partitions=150,
                             replication_factor=3, num_racks=4,
                             num_topics=5, seed=33, dead_brokers=2)
    state, topo = random_cluster(spec)
    goals = default_goals(max_rounds=48)
    opt = GoalOptimizer(goals)
    result = run_and_verify(opt, state, topo)
    broker = np.asarray(result.final_state.replica_broker)
    alive = np.asarray(result.final_state.broker_alive)
    assert alive[broker].all()


def test_add_broker_moves_only_to_new():
    spec = RandomClusterSpec(num_brokers=12, num_partitions=150,
                             replication_factor=2, num_racks=4, seed=40,
                             new_brokers=3)
    state, topo = random_cluster(spec)
    options = OptimizationOptions(only_move_immigrant_replicas=True)
    opt = GoalOptimizer([ReplicaDistributionGoal(max_rounds=96)])
    result = run_and_verify(opt, state, topo, options,
                            check_new_broker_only_moves=False)
    # immigrant-only: originals can only move if offline (none here) or on
    # new brokers; so all moves must target... nothing to move since new
    # brokers are empty -> replicas can't move at all in immigrant mode
    assert result.num_replica_movements == 0


def test_registry_completeness():
    for name in DEFAULT_GOAL_ORDER:
        goal = make_goal(name)
        assert goal.name == name
    with pytest.raises(KeyError):
        make_goal("NoSuchGoal")


@pytest.mark.slow
def test_jbod_random_cluster_self_healing():
    """BASELINE eval config 5 shape: JBOD logdirs with broken disks; the
    stack must bring every offline replica back online within capacity
    (reference: capacityJBOD.json + fix-offline-replicas flow)."""
    spec = RandomClusterSpec(num_brokers=12, num_partitions=120,
                             replication_factor=3, num_racks=4,
                             num_topics=5, seed=13, jbod_disks=3,
                             dead_disks=4)
    state, topo = random_cluster(spec)
    import numpy as np
    from cruise_control_tpu.model import state as S
    assert int(np.asarray(S.self_healing_eligible(state)).sum()) > 0
    opt = GoalOptimizer(default_goals(
        max_rounds=32, names=["DiskCapacityGoal",
                              "DiskUsageDistributionGoal"]))
    result = run_and_verify(opt, state, topo)
    assert result.proposals


class _RegressingGoal(ReplicaDistributionGoal):
    """Test double: optimizes normally but reports its statistic regressed
    (reference AbstractGoal.optimize :92-101 comparator preferring the
    BEFORE state)."""

    name = "RegressingGoal"

    def stats_not_worse(self, before, after) -> bool:
        return False


def test_stats_regression_aborts_optimization():
    state, topo = fixtures.small_cluster()
    opt = GoalOptimizer([_RegressingGoal()])
    with pytest.raises(OptimizationFailure, match="worse than before"):
        opt.optimizations(state, topo)


def test_stats_regression_waived_during_self_healing():
    # reference AbstractGoal.java:92-93: the regression abort applies only
    # when the cluster has no broken brokers
    state, topo = fixtures.dead_broker_cluster()
    opt = GoalOptimizer([_RegressingGoal()])
    result = opt.optimizations(state, topo)
    assert result.regressed_goals == ["RegressingGoal"]
    assert not np.asarray(
        S.broker_replica_count(result.final_state))[
        ~np.asarray(state.broker_alive)].any()


@pytest.mark.slow
def test_warmup_aot_path_serves_optimizations():
    """GoalOptimizer.warmup retains AOT executables and optimizations()
    dispatches through them (the facade's auto_warmup path — its
    production default; tests construct facades with auto_warmup=False
    for wall-clock, so this is the dedicated coverage)."""
    state, topo = fixtures.small_cluster()
    goals = default_goals(max_rounds=16, names=[
        "RackAwareGoal", "DiskCapacityGoal", "ReplicaDistributionGoal"])
    opt = GoalOptimizer(goals, auto_warmup=True)
    assert not opt._aot
    result = opt.optimizations(state, topo)   # triggers the auto-warmup
    assert opt._aot, "auto-warmup retained no AOT executables"
    # every pipeline program was compiled, not only the executed ones
    keys = set(opt._aot)
    assert {"__stats__", "__pre__", "__post__"} <= keys
    assert any(k.startswith("__seg_") for k in keys)
    # the AOT dispatch returns the same result as a fresh jit path
    ref = GoalOptimizer(goals).optimizations(state, topo)
    assert np.array_equal(np.asarray(result.final_state.replica_broker),
                          np.asarray(ref.final_state.replica_broker))
