"""Scripted chaos suite: the solver degradation ladder under injected
faults (utils/faults.py harness + analyzer/degradation.py ladder).

Deterministic scenarios proving the PR-2 robustness contract:

(a) NaN/Inf loads are quarantined at ingest (monitor/sampling/holder.py)
    and flagged device-side with NO extra host syncs (the invalid-input
    verdict rides the single end-of-solve fetch; the transfer-guard pin
    in test_fused_pipeline.py stays green);
(b) the ladder descends fused → eager → CPU on injected compile/runtime
    faults, the breaker pins the degraded rung, and after cooldown the
    probes climb back with the breaker re-closing;
(c) SolverDegraded anomalies reach the notifier and the rung/breaker
    state appears in the STATE endpoint response;
(d) a solve retried after a donated-buffer failure re-materializes its
    inputs and matches the fault-free result bit-for-bit.

Everything runs under JAX_PLATFORMS=cpu with the facade's virtual clock
and the seeded fault plans — reruns reproduce the same faults at the
same calls.
"""
import conftest  # noqa: F401

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.analyzer.degradation import (BackoffPolicy,
                                                     BreakerState,
                                                     CircuitBreaker,
                                                     FailureKind,
                                                     InvalidModelInputError,
                                                     SolverRung,
                                                     classify_failure)
from cruise_control_tpu.analyzer.goals.base import OptimizationFailure
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.detector.anomalies import SolverDegraded
from cruise_control_tpu.detector.notifier import (AnomalyNotifier,
                                                  NotificationAction)
from cruise_control_tpu.testing import fixtures
from cruise_control_tpu.utils import faults

from test_facade import make_stack, feed_samples

pytestmark = pytest.mark.chaos

CHAOS_GOALS = ["RackAwareGoal", "DiskCapacityGoal",
               "ReplicaDistributionGoal"]


class RecordingNotifier(AnomalyNotifier):
    def __init__(self):
        self.anomalies = []

    def on_anomaly(self, anomaly):
        self.anomalies.append(anomaly)
        return NotificationAction.ignore()

    def self_healing_enabled(self):
        return {}


# ---------------------------------------------------------------------------
# harness + classification units
# ---------------------------------------------------------------------------

class TestFaultHarness:
    def test_fail_nth_and_counts(self):
        plan = faults.FaultPlan().fail_nth("site.a", (1, 3))
        with faults.injected(plan) as inj:
            for expected in (True, False, True, False):
                if expected:
                    with pytest.raises(faults.FaultError):
                        faults.inject("site.a")
                else:
                    faults.inject("site.a")
            assert inj.counts() == {"site.a": (4, 2)}
        faults.inject("site.a")   # uninstalled: inert

    def test_fail_probability_is_seeded_deterministic(self):
        def run():
            plan = faults.FaultPlan(seed=42).fail_probability("s", 0.5)
            hits = []
            with faults.injected(plan):
                for _ in range(20):
                    try:
                        faults.inject("s")
                        hits.append(0)
                    except faults.FaultError:
                        hits.append(1)
            return hits
        first = run()
        assert first == run() and 0 < sum(first) < 20

    def test_classification_buckets(self):
        assert classify_failure(
            faults.FaultError("optimizer.compile")) is FailureKind.COMPILE
        assert classify_failure(
            faults.FaultError("optimizer.execute")) is FailureKind.RUNTIME
        assert classify_failure(
            InvalidModelInputError("x")) is FailureKind.INVALID_INPUT
        assert classify_failure(
            RuntimeError("XLA compilation failed")) is FailureKind.COMPILE
        assert classify_failure(
            RuntimeError("device halted")) is FailureKind.RUNTIME

    def test_backoff_is_deterministic_and_capped(self):
        import itertools
        pol = BackoffPolicy(base_s=1.0, max_s=4.0, jitter=0.25, seed=7)
        a = list(itertools.islice(pol.delays(), 6))
        b = list(itertools.islice(pol.delays(), 6))
        assert a == b
        assert all(d <= 4.0 for d in a)   # max_s is a HARD cap
        assert a[0] < a[1] < a[2]   # exponential until the cap

    def test_breaker_transitions(self):
        clock = {"now": 0.0}
        br = CircuitBreaker(failure_threshold=2, cooldown_s=10.0,
                            time_fn=lambda: clock["now"])
        assert br.state is BreakerState.CLOSED
        assert br.record_failure() is False
        assert br.record_failure() is True      # trips exactly once
        assert br.record_failure() is False     # already open
        assert br.state is BreakerState.OPEN
        clock["now"] += 11.0
        assert br.state is BreakerState.HALF_OPEN
        br.record_failure()                     # failed probe re-opens
        assert br.state is BreakerState.OPEN
        clock["now"] += 11.0
        br.record_success()
        assert br.state is BreakerState.CLOSED
        assert br.consecutive_failures == 0


# ---------------------------------------------------------------------------
# (a) invalid inputs: ingest quarantine + device-side flag
# ---------------------------------------------------------------------------

class TestInvalidInputs:
    def test_nan_samples_quarantined_at_ingest(self):
        from cruise_control_tpu.monitor.sampling.holder import (
            BrokerMetricSample, PartitionMetricSample, quarantine_invalid,
            sample_values_valid)
        from cruise_control_tpu.cluster.types import TopicPartition

        good = PartitionMetricSample(0, TopicPartition("t", 0), 1000.0,
                                     {0: 1.0, 1: 2.0})
        for bad_value in (float("nan"), float("inf"), -1.0):
            bad = BrokerMetricSample(1, 1000.0, {0: bad_value})
            assert not sample_values_valid(bad.values)
            valid, dropped = quarantine_invalid([good, bad])
            assert valid == [good] and dropped == 1
        assert sample_values_valid(good.values)

    def test_fetcher_quarantine_counts_and_starves_aggregator(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        fetcher = cc.load_monitor._fetcher
        before = fetcher.num_quarantined_samples

        # corrupt the sampler output: every partition sample carries NaN
        orig = fetcher._sampler.get_samples

        def corrupting(*args, **kwargs):
            out = orig(*args, **kwargs)
            out.partition_samples = [
                type(s)(s.broker_id, s.tp, s.sample_time_ms,
                        {k: float("nan") for k in s.values})
                for s in out.partition_samples]
            return out

        fetcher._sampler.get_samples = corrupting
        try:
            cc.load_monitor.task_runner.sample_once()
        finally:
            fetcher._sampler.get_samples = orig
        assert fetcher.num_quarantined_samples > before
        sensors = cc.metrics.to_json()
        assert sensors["sampler-quarantined-samples"]["value"] \
            == fetcher.num_quarantined_samples
        cc.shutdown()

    def test_device_side_flag_without_extra_syncs(self, monkeypatch):
        """A NaN-bearing model raises InvalidModelInputError from the
        single end-of-solve fetch: exactly the same TWO device_gets as a
        healthy solve (instrument fetch raises before the diff fetch —
        so at MOST two), under a disallow transfer guard."""
        state, topo = fixtures.small_cluster()
        bad = state.replace(
            replica_base_load=state.replica_base_load.at[0, 0].set(
                jnp.nan))
        opt = GoalOptimizer(default_goals(max_rounds=8, names=CHAOS_GOALS),
                            pipeline_segment_size=2)
        calls = []
        real_device_get = jax.device_get

        def counting(x):
            calls.append(1)
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting)
        with jax.transfer_guard_device_to_host("disallow"):
            with pytest.raises(InvalidModelInputError):
                opt.optimizations(bad, topo, OptimizationOptions(),
                                  check_sanity=False)
        assert len(calls) == 1   # the instrument fetch; no diff fetch

    def test_invalid_input_never_retries_or_descends(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)

        orig = cc.cluster_model

        def poisoned(*args, **kwargs):
            state, topo = orig(*args, **kwargs)
            return state.replace(
                replica_base_load=state.replica_base_load.at[0, 0].set(
                    jnp.nan)), topo

        cc.cluster_model = poisoned
        with pytest.raises(InvalidModelInputError):
            cc.optimizations(ignore_proposal_cache=True)
        # the ladder did NOT move: garbage input is not a solver fault
        assert cc.solver_ladder.rung is SolverRung.FUSED
        assert cc.solver_breaker.state is BreakerState.CLOSED
        assert cc.metrics.to_json()["solver-invalid-input"]["count"] == 1
        cc.shutdown()


# ---------------------------------------------------------------------------
# (b) + (c) ladder descent, breaker pin, recovery, anomaly + STATE
# ---------------------------------------------------------------------------

class TestDegradationLadder:
    def test_descends_pins_recovers_and_reports(self):
        notifier = RecordingNotifier()
        sim, cc, clock = make_stack(notifier=notifier)
        cc.solver_breaker.cooldown_s = 50.0
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)

        healthy = cc.optimizations()
        assert healthy.proposals
        assert cc.solver_ladder.rung is SolverRung.FUSED

        # persistent compile+runtime faults: FUSED and EAGER both fail,
        # the CPU rung (no XLA) serves, the breaker trips and pins
        feed_samples(cc, clock, rounds=1)
        plan = faults.FaultPlan() \
            .fail_always("optimizer.compile") \
            .fail_always("optimizer.execute")
        with faults.injected(plan):
            degraded = cc.optimizations(ignore_proposal_cache=True)
        assert degraded is not None   # served, even if with no proposals
        assert cc.solver_ladder.rung is SolverRung.CPU
        assert cc.solver_ladder.total_descents == 2
        assert cc.solver_breaker.state is BreakerState.OPEN

        # while OPEN the rung is pinned: no device dispatch happens even
        # though the faults are gone (the solve runs the CPU rung)
        feed_samples(cc, clock, rounds=1)
        pinned_plan = faults.FaultPlan().fail_always("optimizer.execute")
        with faults.injected(pinned_plan) as inj:
            cc.optimizations(ignore_proposal_cache=True)
            assert inj.call_count("optimizer.execute") == 0
        assert cc.solver_ladder.rung is SolverRung.CPU
        assert cc.solver_breaker.state is BreakerState.OPEN

        # (c) the degradation events reached the notifier
        cc.anomaly_detector.process_all()
        degraded_events = [a for a in notifier.anomalies
                           if isinstance(a, SolverDegraded)]
        assert len(degraded_events) == 3   # 2 descents + 1 breaker trip
        assert any(a.breaker_tripped for a in degraded_events)
        assert {(a.from_rung, a.to_rung) for a in degraded_events} \
            >= {("FUSED", "EAGER"), ("EAGER", "CPU")}

        # recovery: cooldown elapses -> HALF_OPEN probe one rung up,
        # success climbs one rung per solve, breaker re-closes
        clock["now"] += 55.0
        feed_samples(cc, clock, rounds=8)
        assert cc.solver_breaker.state is BreakerState.HALF_OPEN
        cc.optimizations(ignore_proposal_cache=True)
        assert cc.solver_ladder.rung is SolverRung.EAGER
        assert cc.solver_breaker.state is BreakerState.CLOSED
        feed_samples(cc, clock, rounds=1)
        recovered = cc.optimizations(ignore_proposal_cache=True)
        assert cc.solver_ladder.rung is SolverRung.FUSED
        assert recovered.proposals
        cc.shutdown()

    def test_transient_fault_retried_on_same_rung(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        # exactly one mid-solve fault: the retry (same rung) succeeds
        plan = faults.FaultPlan().fail_nth("optimizer.execute", 2)
        with faults.injected(plan):
            result = cc.optimizations()
        assert result.proposals
        assert cc.solver_ladder.rung is SolverRung.FUSED
        assert cc.metrics.to_json()["solver-retries"]["count"] == 1
        cc.shutdown()

    def test_rung_and_breaker_in_state_endpoint(self):
        from cruise_control_tpu.api.server import CruiseControlApp
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        feed_samples(cc, clock, rounds=1)
        plan = faults.FaultPlan() \
            .fail_always("optimizer.compile") \
            .fail_always("optimizer.execute")
        with faults.injected(plan):
            cc.optimizations(ignore_proposal_cache=True)
        app = CruiseControlApp(cc)
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "substates=analyzer",
            {}, client="test")
        assert status == 200
        deg = body["AnalyzerState"]["solverDegradation"]
        assert deg["rung"] == "CPU"
        assert deg["breaker"]["state"] == "OPEN"
        assert deg["totalDescents"] == 2
        assert deg["precomputeWedged"] is False
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "substates=sensors",
            {}, client="test")
        assert body["Sensors"]["solver-rung"]["value"] == 2
        assert body["Sensors"]["solver-breaker-open"]["value"] == 1.0
        cc.shutdown()

    def test_optimization_failure_is_not_ladder_material(self):
        """An unsatisfiable hard goal is a solver VERDICT: it must
        propagate unchanged — no retry, no descent — at every rung."""
        sim, cc, clock = make_stack(
            goal_names=["RackAwareGoal", "DiskCapacityGoal"])
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)

        from cruise_control_tpu.analyzer.goals.base import Goal

        class Unsatisfiable(Goal):
            name = "UnsatisfiableHardGoal"
            is_hard = True

            def optimize_cached(self, state, ctx, prev_goals, cache=None):
                return state, cache

            def violated_brokers(self, state, ctx, cache):
                return state.broker_alive

        cc.goal_optimizer = GoalOptimizer([Unsatisfiable()])
        with pytest.raises(OptimizationFailure):
            cc.optimizations(ignore_proposal_cache=True)
        assert cc.solver_ladder.rung is SolverRung.FUSED
        assert cc.solver_breaker.state is BreakerState.CLOSED
        cc.shutdown()


# ---------------------------------------------------------------------------
# (d) donated-buffer retry: re-materialized inputs, bit-for-bit result
# ---------------------------------------------------------------------------

class TestRetryDeterminism:
    def _result_fingerprint(self, result):
        placements = sorted(
            (p.partition.topic, p.partition.partition,
             tuple(r.broker_id for r in p.old_replicas),
             tuple(r.broker_id for r in p.new_replicas))
            for p in result.proposals)
        return placements, np.asarray(result.final_state.replica_broker)

    def test_retry_after_midsolve_fault_matches_fault_free(self):
        """The goal programs donate their input buffers (non-CPU
        backends), so a fault mid-pipeline leaves the solve's inputs
        consumed; the ladder re-materializes the model per attempt
        (facade._materialize_solve_inputs) — the retried solve must
        reproduce the fault-free solve exactly."""
        fault_free = make_stack()
        sim1, cc1, clock1 = fault_free
        cc1.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc1, clock1)
        baseline = cc1.optimizations()
        cc1.shutdown()

        sim2, cc2, clock2 = make_stack()
        cc2.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc2, clock2)
        # fail the 2nd program dispatch: the pre program already ran, so
        # the threaded state/cache of attempt 1 are poisoned mid-flight
        plan = faults.FaultPlan().fail_nth("optimizer.execute", 2)
        with faults.injected(plan) as inj:
            retried = cc2.optimizations()
            assert inj.failure_count("optimizer.execute") == 1
        assert cc2.metrics.to_json()["solver-retries"]["count"] == 1
        cc2.shutdown()

        base_p, base_state = self._result_fingerprint(baseline)
        retry_p, retry_state = self._result_fingerprint(retried)
        assert retry_p == base_p
        assert np.array_equal(base_state, retry_state)


# ---------------------------------------------------------------------------
# scenario-engine ladder: batched what-if solves degrade independently
# ---------------------------------------------------------------------------

class TestScenarioLadder:
    """Descent through the degradation ladder for the `scenario.*` fault
    sites (PR-3): the batched FUSED path fails -> per-scenario EAGER
    loop; EAGER's device programs fail too -> CPU host fallback; the
    request-path solver ladder never moves; recovery probes climb one
    rung per batch once faults clear."""

    def _specs(self, n=2):
        from cruise_control_tpu.scenario import ScenarioSpec
        return [ScenarioSpec(name=f"s{i}",
                             load_scale={"disk": 1.0 + 0.1 * (i + 1)})
                for i in range(n)]

    def test_scenario_ladder_descends_and_recovers(self):
        sim, cc, clock = make_stack()
        cc.scenario_engine.breaker.cooldown_s = 50.0
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)

        # healthy: batched FUSED
        res = cc.evaluate_scenarios(self._specs(), include_base=False)
        assert all(o.rung == "FUSED" for o in res.outcomes)

        # batched dispatch faulted -> EAGER per-scenario loop serves
        with faults.injected(
                faults.FaultPlan().fail_always("scenario.execute")):
            res = cc.evaluate_scenarios(self._specs(),
                                        include_base=False)
        assert all(o.feasible and o.rung == "EAGER"
                   for o in res.outcomes)
        assert cc.scenario_engine.ladder.rung is SolverRung.EAGER

        # batched AND per-goal device programs faulted -> CPU fallback
        with faults.injected(faults.FaultPlan()
                             .fail_always("scenario.execute")
                             .fail_always("optimizer.execute")):
            res = cc.evaluate_scenarios(self._specs(),
                                        include_base=False)
        assert all(o.rung == "CPU" for o in res.outcomes)
        assert cc.scenario_engine.ladder.rung is SolverRung.CPU
        assert cc.scenario_engine.breaker.state is BreakerState.OPEN
        # isolation: the REQUEST-PATH solver ladder never moved
        assert cc.solver_ladder.rung is SolverRung.FUSED
        assert cc.solver_breaker.state is BreakerState.CLOSED

        # rung + breaker visible in STATE and sensors
        state = cc.state(["scenario", "sensors"])
        eng = state["ScenarioEngineState"]
        assert eng["rung"] == "CPU"
        assert eng["breaker"]["state"] == "OPEN"
        assert state["Sensors"]["scenario-rung"]["value"] == 2
        assert state["Sensors"]["scenario-descents"]["count"] == 2

        # recovery: cooldown elapses, probes climb one rung per batch
        clock["now"] += 55.0
        res = cc.evaluate_scenarios(self._specs(), include_base=False)
        assert cc.scenario_engine.ladder.rung is SolverRung.EAGER
        res = cc.evaluate_scenarios(self._specs(), include_base=False)
        assert cc.scenario_engine.ladder.rung is SolverRung.FUSED
        assert cc.scenario_engine.breaker.state is BreakerState.CLOSED
        assert all(o.rung == "FUSED" for o in res.outcomes)
        cc.shutdown()

    def test_scenario_compile_fault_classifies_compile(self):
        assert classify_failure(
            faults.FaultError("scenario.compile")) is FailureKind.COMPILE
        assert classify_failure(
            faults.FaultError("scenario.execute")) is FailureKind.RUNTIME


# ---------------------------------------------------------------------------
# precompute loop: fault site, backoff, watchdog
# ---------------------------------------------------------------------------

class TestPrecomputeRobustness:
    def test_precompute_survives_injected_faults_and_recovers(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        plan = faults.FaultPlan().fail_nth("facade.precompute", 1)
        with faults.injected(plan):
            assert cc._precompute_once_status() == "failed"
            assert cc._precompute_once_status() == "computed"
        cc.shutdown()

    def test_wedged_precompute_does_not_block_shutdown(self):
        import threading
        import time as _real_time
        sim, cc, clock = make_stack()
        cc._precompute_solve_deadline_s = 10.0
        cc.start_up(do_sampling=False, start_detection=False)
        # simulate a wedged solve: a precompute thread stuck for longer
        # than shutdown would ever wait, started past the deadline
        release = threading.Event()
        wedged = threading.Thread(target=release.wait, daemon=True)
        wedged.start()
        cc._precompute_thread = wedged
        cc._precompute_solve_started_at = clock["now"] - 60.0
        assert cc.precompute_wedged()
        t0 = _real_time.monotonic()
        cc.shutdown()
        assert _real_time.monotonic() - t0 < 4.0   # did not join(5.0)
        release.set()

    def test_precompute_age_within_deadline_is_not_wedged(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        assert not cc.precompute_wedged()
        cc._precompute_solve_started_at = clock["now"] - 1.0
        assert not cc.precompute_wedged()
        cc._precompute_solve_started_at = None
        cc.shutdown()
