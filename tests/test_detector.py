"""Detector plane tests.

Models the reference's AnomalyDetectorTest.java (queue + self-healing flow,
601 LoC, mock-based) and BrokerFailureDetectorTest.java (real ZK watch;
here the SimulatedCluster liveness listener), plus unit tests for the
notifier grace periods, slow-broker scoring, and balancedness score.
"""
import conftest  # noqa: F401

import numpy as np

from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.core.anomaly import AnomalyType
from cruise_control_tpu.detector import (
    AnomalyDetector, AnomalyState, BrokerFailureDetector, BrokerFailures,
    DiskFailureDetector, GoalViolationDetector, NoopNotifier,
    SelfHealingNotifier, SlowBrokerFinder, SlowBrokerFinderConfig,
    TopicReplicationFactorAnomalyFinder, balancedness_score)
from cruise_control_tpu.detector.anomalies import GoalViolations


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _sim(brokers=4):
    sim = SimulatedCluster()
    for b in range(brokers):
        sim.add_broker(b, rack=f"r{b % 2}")
    return sim


class TestBrokerFailureDetector:
    def test_liveness_watch_reports_failures(self):
        sim = _sim()
        clock = FakeClock(100.0)
        reports = []
        det = BrokerFailureDetector(sim, reports.append, time_fn=clock)
        det.start()
        assert reports == []   # all alive at startup
        sim.kill_broker(2)
        assert len(reports) == 1
        assert set(reports[0].failed_brokers_by_time_ms) == {2}
        assert reports[0].failed_brokers_by_time_ms[2] == 100e3
        # failure time sticks across subsequent events
        clock.t = 200.0
        sim.kill_broker(3)
        assert set(reports[-1].failed_brokers_by_time_ms) == {2, 3}
        assert reports[-1].failed_brokers_by_time_ms[2] == 100e3
        # recovery clears the broker
        sim.restart_broker(2)
        assert set(reports[-1].failed_brokers_by_time_ms) == {3}
        det.shutdown()

    def test_persistence_across_restart(self, tmp_path):
        from cruise_control_tpu.detector import FileFailedBrokerStore
        sim = _sim()
        clock = FakeClock(50.0)
        store = FileFailedBrokerStore(str(tmp_path / "failed.json"))
        det = BrokerFailureDetector(sim, lambda a: None, store=store,
                                    time_fn=clock)
        det.start()
        sim.kill_broker(1)
        det.shutdown()
        # new detector instance sees the original failure time
        clock.t = 500.0
        det2 = BrokerFailureDetector(sim, lambda a: None, store=store,
                                     time_fn=clock)
        det2.start()
        assert det2.failed_brokers()[1] == 50e3
        det2.shutdown()

    def test_unfixable_beyond_thresholds(self):
        sim = _sim(4)
        reports = []
        det = BrokerFailureDetector(sim, reports.append,
                                    fix_fn=lambda: True,
                                    fixable_max_ratio=0.25)
        det.start()
        sim.kill_broker(0)
        sim.kill_broker(1)   # 50% failed > 25% threshold
        assert reports[-1].fix_fn is None
        assert not reports[-1].fix()


class TestDiskFailureDetector:
    def test_offline_logdir_detected(self):
        sim = SimulatedCluster()
        for b in range(2):
            sim.add_broker(b, logdirs=("/d0", "/d1"))
        sim.create_topic("t", [[0, 1]])
        reports = []
        det = DiskFailureDetector(sim, reports.append)
        assert det.detect_now() is None
        sim.fail_disk(0, "/d1")
        anomaly = det.detect_now()
        assert anomaly is not None
        assert anomaly.failed_disks_by_broker == {0: ["/d1"]}


class TestSlowBrokerFinder:
    def _history(self, n_brokers=4, n_windows=20, slow_broker=None,
                 factor=10.0):
        rng = np.random.default_rng(0)
        flush = rng.uniform(1.0, 2.0, size=(n_brokers, n_windows))
        bytes_in = np.full((n_brokers, n_windows), 1e6)
        if slow_broker is not None:
            flush[slow_broker, -1] *= factor
        return flush, bytes_in

    def test_detects_and_escalates(self):
        reports = []
        cfg = SlowBrokerFinderConfig(score_per_detection=1.0,
                                     demotion_score=2.0, removal_score=4.0,
                                     log_flush_time_threshold_ms=5.0)
        finder = SlowBrokerFinder(reports.append, cfg,
                                  demote_fix_fn=lambda: True,
                                  remove_fix_fn=lambda: True)
        flush, bytes_in = self._history(slow_broker=1)
        ids = [0, 1, 2, 3]
        finder.detect_now(ids, flush, bytes_in)       # score 1: no anomaly
        assert reports == [] and finder.slowness_scores == {1: 1.0}
        finder.detect_now(ids, flush, bytes_in)       # score 2: demote
        assert reports[-1].remove_slow_brokers is False
        finder.detect_now(ids, flush, bytes_in)
        finder.detect_now(ids, flush, bytes_in)       # score 4: remove
        assert reports[-1].remove_slow_brokers is True

    def test_score_decay_on_recovery(self):
        reports = []
        finder = SlowBrokerFinder(reports.append, SlowBrokerFinderConfig(
            log_flush_time_threshold_ms=5.0))
        flush, bytes_in = self._history(slow_broker=2)
        finder.detect_now([0, 1, 2, 3], flush, bytes_in)
        assert finder.slowness_scores == {2: 1.0}
        healthy_flush, _ = self._history(slow_broker=None)
        finder.detect_now([0, 1, 2, 3], healthy_flush, bytes_in)
        assert finder.slowness_scores == {}

    def test_idle_broker_not_flagged(self):
        reports = []
        finder = SlowBrokerFinder(reports.append)
        flush, bytes_in = self._history(slow_broker=0)
        bytes_in[0, :] = 10.0   # idle: below min_bytes_in_rate
        finder.detect_now([0, 1, 2, 3], flush, bytes_in)
        assert finder.slowness_scores == {}


class TestTopicAnomalyFinder:
    def test_rf_mismatch(self):
        sim = _sim(4)
        sim.create_topic("good", [[0, 1, 2]])
        sim.create_topic("bad", [[0, 1]])
        reports = []
        finder = TopicReplicationFactorAnomalyFinder(
            sim, reports.append, target_replication_factor=3)
        anomaly = finder.detect_now()
        assert anomaly is not None and anomaly.topics == ["bad"]


class TestSelfHealingNotifier:
    def test_broker_failure_grace_periods(self):
        clock = FakeClock(1000.0)
        n = SelfHealingNotifier(
            self_healing_enabled={AnomalyType.BROKER_FAILURE: True},
            broker_failure_alert_threshold_ms=60e3,
            broker_failure_auto_fix_threshold_ms=120e3,
            time_fn=clock)
        failure = BrokerFailures({1: 1000e3}, fix_fn=lambda: True)
        # before alert threshold: CHECK with delay to the alert point
        act = n.on_anomaly(failure)
        assert act.result.value == "CHECK" and act.delay_ms == 60e3
        # between thresholds: CHECK until auto-fix point
        clock.t = 1000.0 + 90.0
        act = n.on_anomaly(failure)
        assert act.result.value == "CHECK"
        # past auto-fix threshold: FIX
        clock.t = 1000.0 + 121.0
        assert n.on_anomaly(failure).result.value == "FIX"

    def test_healing_disabled_ignores(self):
        clock = FakeClock(0.0)
        n = SelfHealingNotifier(time_fn=clock,
                                broker_failure_alert_threshold_ms=0.0,
                                broker_failure_auto_fix_threshold_ms=0.0)
        failure = BrokerFailures({1: 0.0})
        assert n.on_anomaly(failure).result.value == "IGNORE"

    def test_other_anomaly_fixes_when_enabled(self):
        n = SelfHealingNotifier(
            self_healing_enabled={AnomalyType.GOAL_VIOLATION: True})
        gv = GoalViolations(["DiskUsageDistributionGoal"], [],
                            fix_fn=lambda: True)
        assert n.on_anomaly(gv).result.value == "FIX"
        assert n.set_self_healing_for(AnomalyType.GOAL_VIOLATION, False)
        assert n.on_anomaly(gv).result.value == "IGNORE"


class TestAnomalyDetectorQueue:
    def test_priority_and_fix_flow(self):
        clock = FakeClock(0.0)
        notifier = SelfHealingNotifier(
            self_healing_enabled={t: True for t in AnomalyType},
            broker_failure_alert_threshold_ms=0.0,
            broker_failure_auto_fix_threshold_ms=0.0,
            time_fn=clock)
        det = AnomalyDetector(notifier, time_fn=clock)
        fixed = []
        gv = GoalViolations(["g"], [], fix_fn=lambda: fixed.append("gv")
                            or True)
        bf = BrokerFailures({1: 0.0}, fix_fn=lambda: fixed.append("bf")
                            or True)
        det.report(gv)
        det.report(bf)
        statuses = det.process_all()
        # broker failure has higher priority than goal violation
        assert fixed == ["bf", "gv"]
        assert statuses == [AnomalyState.FIX_STARTED] * 2

    def test_check_with_delay_requeues(self):
        clock = FakeClock(0.0)
        notifier = SelfHealingNotifier(
            self_healing_enabled={AnomalyType.BROKER_FAILURE: True},
            broker_failure_alert_threshold_ms=10e3,
            broker_failure_auto_fix_threshold_ms=10e3,
            time_fn=clock)
        det = AnomalyDetector(notifier, time_fn=clock)
        fixed = []
        det.report(BrokerFailures({1: 0.0},
                                  fix_fn=lambda: fixed.append(1) or True))
        assert det.process_once() == AnomalyState.CHECK_WITH_DELAY
        assert det.process_once() is None        # not due yet
        clock.t = 11.0
        assert det.process_once() == AnomalyState.FIX_STARTED
        assert fixed == [1]

    def test_fix_blocked_while_execution_in_progress(self):
        busy = [True]
        det = AnomalyDetector(
            SelfHealingNotifier(
                self_healing_enabled={AnomalyType.GOAL_VIOLATION: True}),
            fix_in_progress_fn=lambda: busy[0])
        det.report(GoalViolations(["g"], [], fix_fn=lambda: True))
        assert det.process_once() == AnomalyState.CHECK_WITH_DELAY

    def test_not_ready_blocks_fix(self):
        det = AnomalyDetector(
            SelfHealingNotifier(
                self_healing_enabled={AnomalyType.GOAL_VIOLATION: True}),
            ready_fn=lambda: False)
        det.report(GoalViolations(["g"], [], fix_fn=lambda: True))
        assert det.process_once() == AnomalyState.LOAD_MONITOR_NOT_READY

    def test_state_json(self):
        det = AnomalyDetector(NoopNotifier())
        det.report(GoalViolations(["g"], []))
        det.process_all()
        js = det.to_json()
        assert js["recentAnomalies"]["GOAL_VIOLATION"][0]["status"] \
            == "IGNORED"


class TestBalancednessScore:
    class _G:
        def __init__(self, name, hard):
            self.name, self.is_hard = name, hard

    def test_score(self):
        goals = [self._G("hard1", True), self._G("soft1", False)]
        assert balancedness_score(goals, []) == 100.0
        assert balancedness_score(goals, ["hard1", "soft1"]) == 0.0
        partial = balancedness_score(goals, ["soft1"])
        # violating only the soft goal costs less than half the score
        assert 50.0 < partial < 100.0
        assert balancedness_score([], []) == 100.0


class TestGoalViolationDetectorEndToEnd:
    def test_detects_on_unbalanced_fixture(self):
        from cruise_control_tpu.analyzer.goals.registry import default_goals
        from cruise_control_tpu.testing.fixtures import unbalanced_cluster

        state, topo = unbalanced_cluster()

        class FakeMonitor:
            def cluster_model(self, **kwargs):
                return state, topo

        reports = []
        det = GoalViolationDetector(FakeMonitor(), default_goals(),
                                    reports.append)
        anomaly = det.detect_now()
        assert anomaly is not None
        assert anomaly.fixable_violated_goals
        assert det.last_balancedness_score < 100.0


class TestReviewRegressions:
    def test_not_ready_requeues_anomaly(self):
        clock = FakeClock(0.0)
        ready = [False]
        det = AnomalyDetector(
            SelfHealingNotifier(
                self_healing_enabled={AnomalyType.GOAL_VIOLATION: True}),
            ready_fn=lambda: ready[0], time_fn=clock)
        fixed = []
        det.report(GoalViolations(["g"], [],
                                  fix_fn=lambda: fixed.append(1) or True))
        assert det.process_once() == AnomalyState.LOAD_MONITOR_NOT_READY
        # once the monitor is ready, the deferred anomaly must still heal
        ready[0] = True
        clock.t = 11.0
        assert det.process_once() == AnomalyState.FIX_STARTED
        assert fixed == [1]

    def test_alert_fires_once_per_anomaly(self):
        alerts = []
        n = SelfHealingNotifier(
            self_healing_enabled={AnomalyType.GOAL_VIOLATION: True},
            alert_fn=lambda a, fix: alerts.append(a.anomaly_id))
        gv = GoalViolations(["g"], [], fix_fn=lambda: True)
        for _ in range(5):   # deferred re-checks must not re-alert
            n.on_anomaly(gv)
        assert alerts == [gv.anomaly_id]
