"""Resident-table path vs table-less fallback path equivalence.

The kernels keep two selection implementations: the resident [B, S]
row planes (production) and the [R]-array fallback (also the starvation-
escalation plane).  Their masks are built from the same sources
(context.replica_static_ok and the goals' dynamic terms), and this test
keeps them from drifting: the same goal run both ways on random clusters
must reach a comparably balanced end state with the same invariants.
"""
import dataclasses

import conftest  # noqa: F401

import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.capacity import DiskCapacityGoal
from cruise_control_tpu.analyzer.goals.count_distribution import (
    LeaderReplicaDistributionGoal, ReplicaDistributionGoal)
from cruise_control_tpu.analyzer.goals.resource_distribution import (
    DiskUsageDistributionGoal, NetworkOutboundUsageDistributionGoal)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.sanity import sanity_check
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)


def _cluster(seed):
    return random_cluster(RandomClusterSpec(
        num_brokers=16, num_partitions=240, replication_factor=3,
        num_racks=4, num_topics=6, seed=seed, skew_fraction=0.4))


from cruise_control_tpu.testing.fixtures import util_spread as _spread


@pytest.mark.parametrize("goal_cls,res", [
    (DiskCapacityGoal, Resource.DISK),
    (DiskUsageDistributionGoal, Resource.DISK),
    (NetworkOutboundUsageDistributionGoal, Resource.NW_OUT),
])
@pytest.mark.parametrize("seed", [3, 11])
@pytest.mark.slow
def test_goal_outcomes_comparable(goal_cls, res, seed):
    state, topo = _cluster(seed)
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    ctx_no_table = dataclasses.replace(ctx, table_slots=0)
    goal = goal_cls(max_rounds=48)

    out_table = goal.optimize(state, ctx, ())
    out_plain = goal.optimize(state, ctx_no_table, ())
    for out in (out_table, out_plain):
        sanity_check(out)
        # no replicas created or destroyed either way
        assert int(np.asarray(out.replica_valid).sum()) \
            == int(np.asarray(state.replica_valid).sum())

    before = _spread(state, res)
    s_table = _spread(out_table, res)
    s_plain = _spread(out_plain, res)
    # both paths must improve, and the production (table) path may not be
    # drastically worse than the fallback — it MAY be much better: the
    # table path runs multi-commit rounds (rank_accept) while the
    # fallback stays single-commit, so a symmetric bound no longer holds
    assert s_table < before and s_plain < before
    assert s_table <= s_plain * 1.5 + 0.05


@pytest.mark.parametrize("seed", [5])
@pytest.mark.slow
def test_count_goals_comparable(seed):
    state, topo = _cluster(seed)
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    ctx_no_table = dataclasses.replace(ctx, table_slots=0)
    for goal in (ReplicaDistributionGoal(max_rounds=48),
                 LeaderReplicaDistributionGoal(max_rounds=48)):
        out_t = goal.optimize(state, ctx, ())
        out_p = goal.optimize(state, ctx_no_table, ())
        for out in (out_t, out_p):
            sanity_check(out)
            assert int(np.asarray(out.replica_valid).sum()) \
                == int(np.asarray(state.replica_valid).sum())
        v_t = int(np.asarray(goal.violated_brokers(
            out_t, ctx, make_round_cache(out_t))).sum())
        v_p = int(np.asarray(goal.violated_brokers(
            out_p, ctx_no_table, make_round_cache(out_p))).sum())
        v_0 = int(np.asarray(goal.violated_brokers(
            state, ctx, make_round_cache(state))).sum())
        assert v_t <= v_0 and v_p <= v_0
        # the multi-commit table path converges at least as well as the
        # single-commit fallback (one-sided: see test above)
        assert v_t <= v_p + max(2, v_0 // 4), (goal.name, v_0, v_t, v_p)
