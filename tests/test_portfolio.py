"""Portfolio-search subsystem tests (ISSUE 19 pins).

Engine-level pins run against the scenario-test rig (small cluster,
three goals, max_rounds=16 — one batched compile serves the module);
facade-level pins share one module-scope stack with
`portfolio_max_programs=1` so every candidate rides the base-order
program and the only portfolio compile is the two-lane batched solve.
"""
import conftest  # noqa: F401

import threading
import time as _real_time

import pytest

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions)
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.portfolio.engine import (PortfolioEngine,
                                                 PortfolioResult,
                                                 portfolio_fitness,
                                                 select_winner)
from cruise_control_tpu.portfolio.mutate import (THRESHOLD_SCALE_RANGE,
                                                 SolverCandidate,
                                                 crossover_orders,
                                                 make_portfolio,
                                                 mutate_candidate,
                                                 split_tiers)
from cruise_control_tpu.scenario import ScenarioEngine
from cruise_control_tpu.sched import runtime as sched_runtime
from cruise_control_tpu.sched.policy import SchedulerClass
from cruise_control_tpu.sched.runtime import SolvePreempted
from cruise_control_tpu.testing import fixtures
from cruise_control_tpu.utils import faults

from test_facade import feed_samples, make_stack

pytestmark = pytest.mark.portfolio

PORTFOLIO_GOALS = ["RackAwareGoal", "DiskCapacityGoal",
                   "ReplicaDistributionGoal"]


# ---------------------------------------------------------------------------
# mutate: the declarative perturbation vocabulary (pure, no device work)
# ---------------------------------------------------------------------------

class TestMutate:
    def test_candidates_are_pure_functions_of_seed_and_index(self):
        a = make_portfolio(PORTFOLIO_GOALS, seed=7, width=6, max_programs=3)
        b = make_portfolio(PORTFOLIO_GOALS, seed=7, width=6, max_programs=3)
        assert a == b
        assert a[0].is_identity and a[0].index == 0
        # a different seed perturbs differently (beyond the identity)
        c = make_portfolio(PORTFOLIO_GOALS, seed=8, width=6, max_programs=3)
        assert a[1:] != c[1:]

    def test_dropping_identity_keeps_indices_stable(self):
        with_id = make_portfolio(PORTFOLIO_GOALS, seed=7, width=5,
                                 max_programs=3)
        without = make_portfolio(PORTFOLIO_GOALS, seed=7, width=5,
                                 max_programs=3, include_identity=False)
        assert [c.index for c in without] == [1, 2, 3, 4]
        assert with_id[1:] == without

    def test_perturbations_respect_bounds_and_hard_precedence(self):
        cands = make_portfolio(PORTFOLIO_GOALS, seed=3, width=16,
                               max_programs=4)
        lo, hi = THRESHOLD_SCALE_RANGE
        hard_base, soft_base = split_tiers(PORTFOLIO_GOALS)
        trace_keys = set()
        for c in cands:
            assert sorted(c.goal_order) == sorted(PORTFOLIO_GOALS)
            hard, soft = split_tiers(c.goal_order)
            # hard tier always precedes the soft tier, whatever the draw
            assert list(c.goal_order[:len(hard)]) == hard
            assert sorted(hard) == sorted(hard_base)
            assert lo <= c.threshold_scale <= hi
            trace_keys.add(c.trace_key())
        # trace-time knobs capped: width 16 never compiles >4 programs
        assert len(trace_keys) <= 4

    def test_mutation_and_crossover_respect_tiers(self):
        import random
        base = make_portfolio(PORTFOLIO_GOALS, seed=5, width=4,
                              max_programs=4)
        for parent in base:
            for i in (7, 8, 9):
                child = mutate_candidate(parent, seed=5, index=i)
                assert child == mutate_candidate(parent, seed=5, index=i)
                assert sorted(child.goal_order) == sorted(PORTFOLIO_GOALS)
                hard, _ = split_tiers(child.goal_order)
                assert list(child.goal_order[:len(hard)]) == hard
                lo, hi = THRESHOLD_SCALE_RANGE
                assert lo <= child.threshold_scale <= hi
        rng = random.Random(1)
        for _ in range(8):
            child = crossover_orders(base[1].goal_order,
                                     base[2].goal_order, rng)
            assert sorted(child) == sorted(PORTFOLIO_GOALS)
            hard, _ = split_tiers(child)
            assert list(child[:len(hard)]) == hard

    def test_select_winner_prefers_low_index_on_ties(self):
        def out(i, fit):
            return type("O", (), {
                "candidate": SolverCandidate(index=i,
                                             goal_order=("RackAwareGoal",)),
                "fitness": fit, "feasible": fit != float("-inf")})()
        assert select_winner([]) is None
        picked = select_winner([out(2, 5.0), out(0, 5.0),
                                out(1, float("-inf"))])
        assert picked.candidate.index == 0
        assert select_winner([out(0, 1.0), out(3, 2.0)]).candidate.index == 3


# ---------------------------------------------------------------------------
# engine: batched search, determinism, chaos descent, preemption
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def rig():
    """Shared (state, topo, scenario engine, factory): one batched
    compile serves the engine-level tests."""
    state, topo = fixtures.small_cluster()
    constraint = BalancingConstraint()
    base_opt = GoalOptimizer(
        default_goals(max_rounds=16, names=PORTFOLIO_GOALS), constraint,
        pipeline_segment_size=2)

    def factory(names):
        if names is None or list(names) == PORTFOLIO_GOALS:
            return base_opt
        return GoalOptimizer(default_goals(max_rounds=16, names=names),
                             constraint)

    scenario = ScenarioEngine(factory, constraint)
    return state, topo, scenario, factory, constraint


def _make_engine(rig, **kwargs):
    state, topo, scenario, factory, constraint = rig
    return PortfolioEngine(scenario, factory, constraint=constraint,
                           **kwargs)


class TestEngine:
    def test_same_seed_same_portfolio_bit_for_bit(self, rig):
        """Same-seed determinism pin: two searches over the same model
        score every candidate identically and pick the same winner."""
        state, topo, scenario, factory, constraint = rig
        engine = _make_engine(rig)
        cands = make_portfolio(PORTFOLIO_GOALS, seed=7, width=4,
                               max_programs=2)

        def run():
            res = engine.search(state, topo, cands, seed=7,
                                options=OptimizationOptions())
            return res

        r1, r2 = run(), run()
        assert r1.rung == r2.rung == "FUSED"
        key1 = [(c.candidate.index, c.feasible, round(c.fitness, 6))
                for c in r1.candidates]
        key2 = [(c.candidate.index, c.feasible, round(c.fitness, 6))
                for c in r2.candidates]
        assert key1 == key2
        assert r1.winner is not None and r2.winner is not None
        assert r1.winner.candidate == r2.winner.candidate
        assert engine.total_searches == 2
        assert engine.total_candidates == 8
        assert engine.last_width == 4

    def test_chaos_descends_to_eager_with_isolated_ladder(self, rig):
        """Chaos pin: an armed `portfolio.search` fault fails the fused
        batch; the search descends to the bounded EAGER loop and still
        returns a feasible winner.  The portfolio's degradation ladder
        is its OWN — the scenario engine's request-path ladder must not
        move."""
        state, topo, scenario, factory, constraint = rig
        engine = _make_engine(rig, max_eager_candidates=2)
        scenario_rung_before = scenario.ladder.rung
        cands = make_portfolio(PORTFOLIO_GOALS, seed=7, width=3,
                               max_programs=1)
        plan = faults.FaultPlan().fail_nth("portfolio.search", (1,))
        with faults.injected(plan) as inj:
            res = engine.search(state, topo, cands, seed=7,
                                options=OptimizationOptions())
        assert inj.counts().get("portfolio.search") == (1, 1)
        assert res.rung == "EAGER"
        assert res.winner is not None and res.winner.feasible
        assert res.winner.result is not None      # eager lanes carry full
        # results so the facade can serve them without a rebuild
        # bounded budget: only the first 2 candidates solved eagerly
        solved = [c for c in res.candidates if c.feasible]
        assert len(solved) == 2
        assert engine.total_descents == 1
        # ladder isolation: the portfolio's failure never touches the
        # request path's ladder
        assert scenario.ladder.rung == scenario_rung_before

    def test_preemption_propagates_without_descending(self, rig, monkeypatch):
        """SolvePreempted is NOT a solver failure: it must propagate to
        the scheduler (which requeues the sweep) without burning a
        ladder descent or a breaker failure."""
        state, topo, scenario, factory, constraint = rig
        engine = _make_engine(rig)

        def boom(*a, **k):
            raise SolvePreempted("preempted by ANOMALY_HEAL")

        monkeypatch.setattr(engine, "_search_fused", boom)
        cands = make_portfolio(PORTFOLIO_GOALS, seed=7, width=3,
                               max_programs=1)
        with pytest.raises(SolvePreempted):
            engine.search(state, topo, cands, seed=7,
                          options=OptimizationOptions())
        assert engine.total_descents == 0
        assert engine.ladder.rung.name == "FUSED"

    def test_fitness_formula_penalizes_movement(self):
        free = portfolio_fitness(90.0, 0, 0, 24, movement_cost_weight=4.0)
        costly = portfolio_fitness(90.0, 12, 4, 24,
                                   movement_cost_weight=4.0)
        assert free == 90.0
        assert costly == pytest.approx(90.0 - 4.0 * (12 + 2.0) / 24)
        assert costly < free


# ---------------------------------------------------------------------------
# facade: K=1 identity, winner-never-worse, CAS install, refinement job
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def stack():
    """One facade stack for every facade-level pin.
    `portfolio_max_programs=1` keeps every candidate on the base goal
    order (perturbations are lane-batchable knobs only), so the module
    compiles exactly one extra (two-lane) program."""
    sim, cc, clock = make_stack(
        portfolio_seed=11, portfolio_max_programs=1,
        portfolio_background_width=2, portfolio_background_generations=1)
    cc.start_up(do_sampling=False, start_detection=False)
    feed_samples(cc, clock)
    yield sim, cc, clock
    cc.shutdown()


def _num_replicas(cc):
    """The same replica count the facade's fitness comparisons use."""
    state, _ = cc._model_for_solve()
    return cc._num_replicas(cc._fleet_pad(state))


class TestFacadePortfolio:
    def test_k1_identity_never_consults_the_engine(self, stack,
                                                   monkeypatch):
        """K=1 identity pin: at the default width the portfolio engine
        is never invoked and the response carries NO solverProvenance
        block — byte-identical to the pre-portfolio greedy path."""
        from cruise_control_tpu.api.responses import optimization_result
        sim, cc, clock = stack

        def boom(*a, **k):
            raise AssertionError("portfolio engine consulted at K=1")

        monkeypatch.setattr(cc.portfolio_engine, "search", boom)
        r = cc.optimizations(ignore_proposal_cache=True)
        assert r.solver_provenance is None
        body = optimization_result(r)
        assert "solverProvenance" not in body
        assert not r.violated_goals_after
        # cache hit still served engine-free
        assert cc.optimizations() is r

    def test_width3_winner_never_worse_with_provenance(self, stack):
        """Winner-never-worse pin: a width-3 sync search serves a result
        whose fitness is >= greedy's, and the response says which solver
        produced it (and why)."""
        sim, cc, clock = stack
        num = _num_replicas(cc)
        greedy = cc.optimizations(ignore_proposal_cache=True)
        wide = cc.optimizations(ignore_proposal_cache=True,
                                portfolio_width=3)
        prov = wide.solver_provenance
        assert prov is not None
        assert prov["solver"] in ("greedy", "portfolio")
        assert prov["portfolioWidth"] == 3
        assert prov["portfolioSeed"] == 11
        assert prov["rung"] in ("FUSED", "EAGER", "CPU")
        assert "error" not in prov
        fit_greedy = cc.portfolio_engine.greedy_fitness(greedy, num)
        fit_wide = cc.portfolio_engine.greedy_fitness(wide, num)
        assert fit_wide >= fit_greedy - 1e-9
        if prov["solver"] == "portfolio":
            assert prov["bestCandidateFitness"] > prov["greedyFitness"]
            assert "candidateIndex" in prov and "perturbation" in prov
        assert not wide.violated_goals_after
        # provenance must survive JSON encoding (REST responses)
        import json
        from cruise_control_tpu.api.responses import optimization_result
        json.dumps(optimization_result(wide))

    def test_state_block_and_sensors(self, stack):
        sim, cc, clock = stack
        block = cc.state(substates=["portfolio"])["PortfolioState"]
        assert block["width"] == 1          # config default: disabled
        assert block["seed"] == 11
        assert block["backgroundEnabled"] is False
        assert block["rung"] in ("FUSED", "EAGER", "CPU")
        assert block["totalSearches"] >= 1  # the width-3 request above
        for key in ("improvements", "staleDrops", "fitnessBest",
                    "fitnessGreedy", "backgroundSweeps", "breaker"):
            assert key in block
        # portfolio sensors registered on the shared registry
        sensors = cc.metrics.to_json()
        for name in ("portfolio-candidates", "portfolio-rung",
                     "portfolio-fitness-best", "portfolio-improvements",
                     "portfolio-stale-drops"):
            assert name in sensors, name

    def test_install_winner_cas_gate(self, stack):
        """Stale-generation drop pin: the CAS install drops winners from
        a moved generation or a bumped cache epoch, refuses not-better
        winners without counting them stale, and lands strictly-better
        ones."""
        sim, cc, clock = stack
        num = _num_replicas(cc)
        baseline = cc.optimizations(ignore_proposal_cache=True)
        gen = cc.load_monitor.model_generation()
        base_fit = cc.portfolio_engine.greedy_fitness(baseline, num)
        stale0, imp0 = cc._portfolio_stale_drops, cc._portfolio_improvements

        import dataclasses as _dc
        wrong_gen = _dc.replace(gen, load_generation=gen.load_generation + 1)
        assert cc.install_portfolio_winner(baseline, wrong_gen,
                                           base_fit + 5, num) is False
        assert cc._portfolio_stale_drops == stale0 + 1
        # bumped epoch (an execution started mid-search) also drops
        assert cc.install_portfolio_winner(baseline, gen, base_fit + 5,
                                           num,
                                           epoch=cc._cache_epoch + 1) is False
        assert cc._portfolio_stale_drops == stale0 + 2
        # not-better: refused silently (no stale count)
        assert cc.install_portfolio_winner(baseline, gen, base_fit - 1.0,
                                           num) is False
        assert cc._portfolio_stale_drops == stale0 + 2
        assert cc._portfolio_improvements == imp0
        # strictly better: lands, becomes the served cache entry
        assert cc.install_portfolio_winner(baseline, gen, base_fit + 1.0,
                                           num) is True
        assert cc._portfolio_improvements == imp0 + 1
        assert cc.optimizations() is baseline

    def test_background_refinement_statuses(self, stack):
        """The SCENARIO_SWEEP refinement pass: 'skipped' without a warm
        baseline, then a real evolve pass that either improves the cache
        or confirms greedy."""
        sim, cc, clock = stack
        cc._invalidate_proposal_cache()
        assert cc.portfolio_refine_once() == "skipped"
        baseline = cc.optimizations()      # warm the cache baseline
        status = cc.portfolio_refine_once()
        assert status in ("improved", "computed", "stale")
        served = cc.optimizations()        # same generation: cache serve
        if status == "improved":
            assert served is not baseline
            assert served.solver_provenance["solver"] == "portfolio"
            num = _num_replicas(cc)
            assert (cc.portfolio_engine.greedy_fitness(served, num)
                    > cc.portfolio_engine.greedy_fitness(baseline, num))
        else:
            assert served is baseline

    def test_refinement_yields_to_anomaly_heal(self, stack, monkeypatch):
        """Background-job preemption pin: an ANOMALY_HEAL submitted while
        the SCENARIO_SWEEP refinement runs preempts it at the next
        segment checkpoint; the scheduler runs the heal first, requeues
        the sweep, and the refine pass still completes."""
        import importlib
        # the package __init__ re-exports the evolve FUNCTION under the
        # same name, so a plain `import ... as` binds the function —
        # import_module returns the real submodule to patch
        evolve_mod = importlib.import_module(
            "cruise_control_tpu.portfolio.evolve")
        sim, cc, clock = stack
        cc.optimizations()                 # warm baseline (else skipped)

        order, order_lock = [], threading.Lock()
        entered, release = threading.Event(), threading.Event()
        calls = {"n": 0}

        def note(tag):
            with order_lock:
                order.append(tag)

        def fake_evolve(engine, base_state, topology, base_order, seed,
                        width, generations, max_programs=4, options=None,
                        include_proposals=True, on_generation=None):
            calls["n"] += 1
            if calls["n"] == 1:
                entered.set()
                assert release.wait(30.0)
                sched_runtime.segment_checkpoint()  # raises SolvePreempted
            note("sweep")
            return PortfolioResult(seed=seed, width=width, candidates=[])

        monkeypatch.setattr(evolve_mod, "evolve", fake_evolve)
        preempt0 = cc.solve_scheduler.stats.preemptions

        refine_out = {}
        t = threading.Thread(
            target=lambda: refine_out.update(
                status=cc.portfolio_refine_once()), daemon=True)
        t.start()
        assert entered.wait(30.0)

        heal_out = {}

        def heal():
            heal_out["v"] = cc._scheduled_solve(
                SchedulerClass.ANOMALY_HEAL,
                lambda: (note("heal"), "healed")[1], label="heal-stub")

        ht = threading.Thread(target=heal, daemon=True)
        ht.start()
        deadline = _real_time.monotonic() + 10.0
        while (cc.solve_scheduler.queue.depth() < 1
               and _real_time.monotonic() < deadline):
            _real_time.sleep(0.01)
        release.set()
        ht.join(timeout=60.0)
        t.join(timeout=60.0)
        assert heal_out.get("v") == "healed"
        # the sweep was preempted, the heal ran first, the sweep re-ran
        assert order == ["heal", "sweep"]
        assert calls["n"] == 2
        assert refine_out["status"] == "computed"  # empty fake portfolio
        assert cc.solve_scheduler.stats.preemptions >= preempt0 + 1
        # preemption is not failure: the portfolio ladder never moved
        assert cc.portfolio_engine.ladder.rung.name == "FUSED"
