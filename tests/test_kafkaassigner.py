"""Kafka-assigner mode tests.

Models the reference's KafkaAssignerDiskUsageDistributionGoalTest.java (306
LoC, swap-based balancing cases) and KafkaAssignerEvenRackAwareGoal usage:
rack spreading with count-even destinations and swap-based disk balancing
that preserves per-broker replica counts.
"""
import conftest  # noqa: F401

import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.kafkaassigner import (
    KafkaAssignerDiskUsageDistributionGoal, KafkaAssignerEvenRackAwareGoal)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model import state as S
from cruise_control_tpu.model.builder import ClusterModelBuilder
from cruise_control_tpu.testing.fixtures import rack_aware_satisfiable
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)


def skewed_disk_cluster(num_brokers=6, partitions=24):
    """rf=1 partitions with varied sizes, all piled onto brokers 0/1."""
    b = ClusterModelBuilder()
    for i in range(num_brokers):
        b.add_broker(i, rack_id=f"r{i % 3}",
                     capacity={Resource.CPU: 100.0, Resource.NW_IN: 1e6,
                               Resource.NW_OUT: 1e6, Resource.DISK: 1e6})
    for p in range(partitions):
        size = 1000.0 * (1 + p % 4)
        b.add_replica("t", p, p % 2, True,
                      {Resource.DISK: size, Resource.NW_IN: 10.0,
                       Resource.NW_OUT: 20.0, Resource.CPU: 1.0})
    return b.build()


class TestSwapDiskGoal:
    def test_swaps_preserve_replica_counts(self):
        state, topo = skewed_disk_cluster()
        # give brokers 2-5 some replicas so swaps are possible
        b = ClusterModelBuilder()
        for i in range(6):
            b.add_broker(i, rack_id=f"r{i % 3}",
                         capacity={Resource.CPU: 100.0,
                                   Resource.NW_IN: 1e6,
                                   Resource.NW_OUT: 1e6,
                                   Resource.DISK: 1e6})
        rng = np.random.default_rng(7)
        for p in range(48):
            # big partitions on brokers 0-1, small on 2-5
            if p < 16:
                broker, size = p % 2, 5000.0
            else:
                broker, size = 2 + p % 4, 100.0
            b.add_replica("t", p, broker, True,
                          {Resource.DISK: size, Resource.NW_IN: 1.0,
                           Resource.NW_OUT: 1.0, Resource.CPU: 0.1})
        state, topo = b.build()
        counts_before = np.bincount(
            np.asarray(state.replica_broker)[np.asarray(state.replica_valid)],
            minlength=6)
        util_before = np.asarray(S.broker_load(state))[:, Resource.DISK]

        goal = KafkaAssignerDiskUsageDistributionGoal(max_rounds=32)
        ctx = make_context(state, BalancingConstraint(),
                           OptimizationOptions(), topo)
        out = goal.optimize(state, ctx, ())
        counts_after = np.bincount(
            np.asarray(out.replica_broker)[np.asarray(out.replica_valid)],
            minlength=6)
        util_after = np.asarray(S.broker_load(out))[:, Resource.DISK]
        # swap-only: per-broker replica counts unchanged
        assert (counts_before == counts_after).all()
        # disk spread improved
        assert util_after.std() < util_before.std() * 0.5
        S.sanity_check(out) if hasattr(S, "sanity_check") else None

    def test_violated_brokers_surface(self):
        state, topo = skewed_disk_cluster()
        goal = KafkaAssignerDiskUsageDistributionGoal()
        ctx = make_context(state, BalancingConstraint(),
                           OptimizationOptions(), topo)
        cache = make_round_cache(state)
        violated = np.asarray(goal.violated_brokers(state, ctx, cache))
        assert violated.any()


class TestEvenRackAwareGoal:
    def test_fixes_rack_violations_with_count_preference(self):
        state, topo = rack_aware_satisfiable()
        goal = KafkaAssignerEvenRackAwareGoal(max_rounds=64)
        ctx = make_context(state, BalancingConstraint(),
                           OptimizationOptions(), topo)
        out = goal.optimize(state, ctx, ())
        cache = make_round_cache(out)
        assert not np.asarray(
            goal.violated_brokers(out, ctx, cache)).any()


class TestKafkaAssignerStack:
    def test_full_mode_via_optimizer(self):
        state, topo = random_cluster(RandomClusterSpec(
            num_brokers=8, num_partitions=64, replication_factor=2,
            num_racks=4, num_topics=4, seed=11, skew_fraction=0.5))
        opt = GoalOptimizer([KafkaAssignerEvenRackAwareGoal(max_rounds=64),
                             KafkaAssignerDiskUsageDistributionGoal(
                                 max_rounds=32)])
        result = opt.optimizations(state, topo)
        assert "KafkaAssignerEvenRackAwareGoal" \
            not in result.violated_goals_after
