"""Live-response conformance against the published JSON Schemas.

Analog of the reference's ResponseTest (cruise-control/src/test/java/.../
ResponseTest.java:1-227 walking @JsonResponseClass against OpenAPI YAML):
every endpoint's real response body must validate against
cruise_control_tpu.api.schema.ENDPOINT_SCHEMAS, and the artifact itself
must be valid JSON Schema.
"""
import json

import conftest  # noqa: F401
import jsonschema
import pytest

from cruise_control_tpu.api.schema import (AUX_SCHEMAS, ENDPOINT_SCHEMAS,
                                           document)
from test_api import make_app


@pytest.fixture(scope="module")
def app():
    sim, cc, app = make_app()
    yield app
    app.stop()
    cc.shutdown()


def _request(app, method, endpoint, query="", deadline_s=300.0):
    """Issue a request, long-polling 202 async-progress responses via the
    User-Task-ID header (the reference client protocol); every 202 body
    must itself conform to the async-progress schema."""
    import time

    from cruise_control_tpu.api.user_tasks import USER_TASK_ID_HEADER
    headers = {}
    end = time.time() + deadline_s
    while True:
        status, hdrs, body = app.handle_request(
            method, f"/kafkacruisecontrol/{endpoint.lower()}", query,
            headers, client="127.0.0.1")
        if status != 202 or time.time() > end:
            return status, body
        jsonschema.validate(body, AUX_SCHEMAS["async_progress_202"])
        headers = {USER_TASK_ID_HEADER: hdrs[USER_TASK_ID_HEADER]}
        time.sleep(0.2)


def _get(app, endpoint, query=""):
    return _request(app, "GET", endpoint, query)


def _post(app, endpoint, query=""):
    return _request(app, "POST", endpoint, query)


def _validate(endpoint, body):
    jsonschema.validate(body, ENDPOINT_SCHEMAS[endpoint])


def test_schemas_are_valid_jsonschema():
    for name, schema in {**ENDPOINT_SCHEMAS, **AUX_SCHEMAS}.items():
        jsonschema.Draft202012Validator.check_schema(schema)


def test_document_is_json_serializable():
    json.dumps(document())


@pytest.mark.parametrize("endpoint,query", [
    ("STATE", ""),
    ("KAFKA_CLUSTER_STATE", ""),
    ("LOAD", ""),
    ("PARTITION_LOAD", ""),
    ("USER_TASKS", ""),
    ("PROPOSALS", ""),
    ("BOOTSTRAP", ""),
])
def test_get_endpoints_conform(app, endpoint, query):
    status, body = _get(app, endpoint, query)
    assert status == 200, body
    _validate(endpoint, body)


@pytest.mark.parametrize("endpoint,query", [
    ("REBALANCE", "dryrun=true"),
    ("PAUSE_SAMPLING", ""),
    ("RESUME_SAMPLING", ""),
    ("ADMIN", "enable_self_healing_for=broker_failure"),
])
def test_post_endpoints_conform(app, endpoint, query):
    status, body = _post(app, endpoint, query)
    assert status == 200, body
    _validate(endpoint, body)


def test_error_body_conforms(app):
    status, body = _get(app, "LOAD", "bogus_param=1")
    assert status == 400
    jsonschema.validate(body, AUX_SCHEMAS["error"])


def test_artifact_matches_committed_file():
    """docs/RESPONSE_SCHEMAS.json is generated from this module — fail if
    it drifts (regenerate with
    `python -m cruise_control_tpu.api.schema > docs/RESPONSE_SCHEMAS.json`)."""
    import pathlib
    path = (pathlib.Path(__file__).resolve().parent.parent
            / "docs" / "RESPONSE_SCHEMAS.json")
    committed = json.loads(path.read_text())
    assert committed == json.loads(json.dumps(document(), sort_keys=True))
