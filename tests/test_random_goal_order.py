"""Shuffled-goal-order property tests (reference RandomGoalTest.java:1-190:
a fixed cluster optimized under randomly shuffled goal priority orders must
always satisfy the invariant oracle — hard goals hold, nothing regresses,
self-healing completes — regardless of order).
"""
import conftest  # noqa: F401

import random

import pytest

from cruise_control_tpu.analyzer.goals.registry import (DEFAULT_GOAL_ORDER,
                                                        default_goals)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)
from cruise_control_tpu.testing.verifier import run_and_verify

#: trimmed goal subset: full 15-goal stacks per order would dominate suite
#: wall-clock; the subset keeps one goal of each family (hard capacity,
#: rack, count, resource, leadership) so order interactions stay covered
GOAL_SUBSET = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "ReplicaDistributionGoal",
    "DiskUsageDistributionGoal",
    "LeaderReplicaDistributionGoal",
]


@pytest.fixture(scope="module")
def fixed_cluster():
    return random_cluster(RandomClusterSpec(
        num_brokers=10, num_partitions=120, replication_factor=3,
        num_racks=5, num_topics=6, seed=21, skew_fraction=0.4))


HARD = ["RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal"]
SOFT = [n for n in GOAL_SUBSET if n not in HARD]


def _shuffled_order(seed: int):
    """Shuffle within the hard and soft tiers, hard first — the priority
    contract the reference's goal sorting guarantees (hard goals always
    precede soft goals; a soft goal optimized first could legitimately
    veto mandatory hard-goal fixes through acceptance stacking)."""
    rng = random.Random(seed)
    hard = list(HARD)
    soft = list(SOFT)
    rng.shuffle(hard)
    rng.shuffle(soft)
    return hard + soft


@pytest.mark.parametrize("order_seed", [0, 1, 2])
@pytest.mark.slow
def test_shuffled_goal_orders_hold_invariants(fixed_cluster, order_seed):
    state, topo = fixed_cluster
    names = _shuffled_order(order_seed)
    opt = GoalOptimizer(default_goals(max_rounds=32, names=names))
    result = run_and_verify(opt, state, topo)
    # hard goals hold under every ordering
    assert not (set(HARD) & set(result.violated_goals_after)), (
        names, result.violated_goals_after)


@pytest.mark.slow
def test_shuffled_order_with_dead_broker():
    """Self-healing must complete under a non-default goal order too
    (reference RandomSelfHealingTest shuffles goals over dead-broker
    clusters)."""
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=10, num_partitions=100, replication_factor=3,
        num_racks=5, num_topics=5, seed=22, dead_brokers=1))
    opt = GoalOptimizer(default_goals(max_rounds=32,
                                      names=_shuffled_order(7)))
    result = run_and_verify(opt, state, topo)
    assert result.proposals


def test_default_order_matches_reference_priorities():
    """The default priority order is the reference's `default.goals` list
    (config/constants/AnalyzerConfig.java) — hard goals first."""
    hard_prefix = DEFAULT_GOAL_ORDER[:6]
    assert hard_prefix == ["RackAwareGoal", "ReplicaCapacityGoal",
                           "DiskCapacityGoal",
                           "NetworkInboundCapacityGoal",
                           "NetworkOutboundCapacityGoal", "CpuCapacityGoal"]
    goals = default_goals()
    assert [g.name for g in goals] == DEFAULT_GOAL_ORDER
