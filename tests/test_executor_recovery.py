"""Crash-safe execution: durable journal + reconcile-and-resume.

The PR-13 contract (docs/EXECUTOR.md): a process bounce mid-rebalance
never leaves the cluster half-moved.  Pinned here with a
kill-at-every-point crash/restart matrix on the virtual-time simulated
cluster — crash at every executor sleep AND around every admin call —
asserting for every crash point: no inter-broker move submitted twice,
no replication throttle leaked, and the resumed execution (SAME uuid)
ends byte-equal to an uncrashed twin.  Plus torn-tail/corrupt journal
replay, abort-and-clean mode, per-tenant journal isolation, journal
fault degradation (disk-full/EIO must never fail the rebalance), the
poll-failure config satellite, sample-store compaction, and the
durable-write lint rule.
"""
import os
import struct
import sys

import conftest  # noqa: F401
import pytest

from cruise_control_tpu.analyzer.proposals import (ExecutionProposal,
                                                   ReplicaPlacement)
from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.executor import Executor, ExecutionJournal
from cruise_control_tpu.model.builder import PartitionId
from cruise_control_tpu.utils import faults, persist

pytestmark = [pytest.mark.recovery, pytest.mark.chaos]


# ---------------------------------------------------------------------------
# rig
# ---------------------------------------------------------------------------
def _proposal(topic, part, old, new, old_leader=None, size=0.0,
              logdirs_old=None, logdirs_new=None):
    olds = tuple(ReplicaPlacement(b, (logdirs_old or {}).get(b))
                 for b in old)
    news = tuple(ReplicaPlacement(b, (logdirs_new or {}).get(b))
                 for b in new)
    return ExecutionProposal(
        partition=PartitionId(topic, part),
        old_leader=old_leader if old_leader is not None else old[0],
        old_replicas=olds, new_replicas=news, partition_size=size)


def _sim(logdirs=("/d0", "/d1")):
    sim = SimulatedCluster()  # virtual clock
    sim._move_rate = 20e6     # several poll intervals per move
    for b in range(4):
        sim.add_broker(b, rack=f"r{b % 2}", logdirs=logdirs)
    sim.create_topic("t", [[0, 1], [1, 2], [2, 3]], size_bytes=40e6)
    return sim


def _proposals():
    """Replica moves + a logdir move + leader moves: all three phases."""
    return [
        _proposal("t", 0, [0, 1], [2, 1], old_leader=0, size=40e6),
        _proposal("t", 1, [1, 2], [3, 2], old_leader=1, size=40e6),
        _proposal("t", 2, [2, 3], [2, 3], old_leader=2, size=40e6,
                  logdirs_old={2: "/d0"}, logdirs_new={2: "/d1"}),
    ]


def _placement(sim):
    snap = sim.describe_cluster()
    out = {}
    for p in range(3):
        info = snap.partition(TopicPartition("t", p))
        out[p] = (list(info.replicas), info.leader,
                  dict(sorted(info.logdir_by_broker.items())))
    return out


def _twin_placement():
    """Final placement of an uncrashed run over an identical cluster."""
    sim = SimulatedCluster()
    sim._move_rate = 1e12     # twin speed is irrelevant to placement
    for b in range(4):
        sim.add_broker(b, rack=f"r{b % 2}", logdirs=("/d0", "/d1"))
    sim.create_topic("t", [[0, 1], [1, 2], [2, 3]], size_bytes=40e6)
    ex = Executor(sim, progress_check_interval_s=1.0,
                  time_fn=lambda: sim.now_ms() / 1000.0,
                  sleep_fn=sim.advance)
    ex.execute_proposals(_proposals(), reason="twin", wait=True)
    return _placement(sim)


class _Killed(RuntimeError):
    """The simulated SIGKILL."""


class CrashyAdmin:
    """Admin proxy with a power switch + duplicate-submission ledger.

    While ON it forwards to the simulated cluster, counting every
    alter_partition_reassignments target that ADDS brokers a partition
    does not currently host (a growth submission — the thing that must
    never happen twice per partition across crash + recovery).  It can
    kill the "process" before or after the nth admin call.  While OFF
    every call raises — the dead process cannot touch the cluster."""

    def __init__(self, sim, growth_counts, journal=None,
                 kill_before_call=None, kill_after_call=None):
        self._sim = sim
        self._growth = growth_counts
        self._journal = journal
        self._kill_before = kill_before_call
        self._kill_after = kill_after_call
        self.calls = 0
        self.on = True

    def _die(self):
        self.on = False
        if self._journal is not None:
            # the dead process writes nothing more
            self._journal.broken = True
        raise _Killed("simulated process kill")

    def __getattr__(self, name):
        real = getattr(self._sim, name)
        if not callable(real):
            return real

        def call(*args, **kwargs):
            if not self.on:
                raise _Killed("process is dead")
            self.calls += 1
            if self._kill_before is not None \
                    and self.calls == self._kill_before:
                self._die()
            if name == "alter_partition_reassignments":
                for tp, target in args[0].items():
                    if target is None:
                        continue
                    current = set(
                        self._sim._partitions[tp].replicas)
                    if set(target) - current:
                        self._growth[tp] = self._growth.get(tp, 0) + 1
            out = real(*args, **kwargs)
            if self._kill_after is not None \
                    and self.calls == self._kill_after:
                self._die()
            return out
        return call


def _crashed_run(tmp_path, kill_sleep=None, kill_before_call=None,
                 kill_after_call=None, throttle=None, removed=(),
                 name="run"):
    """One 'process': start the execution and crash it at the chosen
    point.  Returns (sim, journal_dir, growth_counts, uuid_or_None)."""
    sim = _sim()
    jdir = str(tmp_path / name)
    growth = {}
    journal = ExecutionJournal(jdir,
                               time_fn=lambda: sim.now_ms() / 1000.0)
    proxy = CrashyAdmin(sim, growth, journal=journal,
                        kill_before_call=kill_before_call,
                        kill_after_call=kill_after_call)
    ex = Executor(proxy, progress_check_interval_s=1.0, journal=journal,
                  replication_throttle_bytes_per_s=throttle,
                  time_fn=lambda: sim.now_ms() / 1000.0)
    sleeps = {"n": 0}

    def sleep(s):
        sleeps["n"] += 1
        if kill_sleep is not None and sleeps["n"] == kill_sleep:
            proxy.on = False
            journal.broken = True
            raise _Killed("simulated process kill during sleep")
        sim.advance(s)
    ex._sleep = sleep
    uuid = None
    try:
        uuid = ex.execute_proposals(_proposals(), reason="prod",
                                    removed_brokers=list(removed),
                                    wait=True)
    except _Killed:
        pass          # died before the runnable even started
    return sim, jdir, growth, uuid


def _recover(sim, jdir, growth, mode="resume"):
    """The 'restarted process': fresh executor over the same journal
    dir and the (powered-back-on) cluster."""
    journal = ExecutionJournal(jdir,
                               time_fn=lambda: sim.now_ms() / 1000.0)
    proxy = CrashyAdmin(sim, growth)
    ex = Executor(proxy, progress_check_interval_s=1.0, journal=journal,
                  time_fn=lambda: sim.now_ms() / 1000.0,
                  sleep_fn=sim.advance)
    report = ex.recover(mode=mode, wait=True)
    return ex, report


# ---------------------------------------------------------------------------
# THE acceptance pin: kill at every point, resume, byte-equal twin
# ---------------------------------------------------------------------------
class TestCrashResumeMatrix:
    def _assert_recovered(self, sim, jdir, growth, uuid, initial, twin,
                          point):
        ex2, report = _recover(sim, jdir, growth, mode="resume")
        final = _placement(sim)
        if report is None:
            # crashed before the start record committed (nothing to
            # recover) or after the finish record (nothing left): the
            # cluster must be all-or-nothing, never half-moved
            assert final in (initial, twin), point
        else:
            # the SAME execution resumed and completed
            assert report["uuid"] == uuid, point
            assert final == twin, point
        # no inter-broker move was ever submitted twice
        for tp, n in growth.items():
            assert n <= 1, f"{point}: {tp} submitted {n} times"
        # no replication throttle left behind
        assert all(b.throttle is None
                   for b in sim._brokers.values()), point
        assert not ex2.has_ongoing_execution

    def test_kill_at_every_sleep(self, tmp_path):
        twin = _twin_placement()
        initial = _placement(_sim())
        # discover the clean run's sleep count
        sim_c = _sim()
        ex_c = Executor(sim_c, progress_check_interval_s=1.0,
                        time_fn=lambda: sim_c.now_ms() / 1000.0)
        count = {"n": 0}

        def counting_sleep(s):
            count["n"] += 1
            sim_c.advance(s)
        ex_c._sleep = counting_sleep
        ex_c.execute_proposals(_proposals(), reason="count", wait=True)
        clean_sleeps = count["n"]
        assert clean_sleeps >= 4, "rig too fast to crash mid-flight"
        for k in range(1, clean_sleeps + 1):
            sim, jdir, growth, uuid = _crashed_run(
                tmp_path, kill_sleep=k,
                throttle=100e6, name=f"sleep{k}")
            self._assert_recovered(sim, jdir, growth, uuid, initial,
                                   twin, point=f"kill at sleep {k}")

    def test_kill_around_every_admin_call(self, tmp_path):
        twin = _twin_placement()
        initial = _placement(_sim())
        # clean call count
        sim_c = _sim()
        growth_c = {}
        proxy_c = CrashyAdmin(sim_c, growth_c)
        ex_c = Executor(proxy_c, progress_check_interval_s=1.0,
                        time_fn=lambda: sim_c.now_ms() / 1000.0,
                        sleep_fn=sim_c.advance)
        ex_c.execute_proposals(_proposals(), reason="count", wait=True)
        total = proxy_c.calls
        assert total >= 8
        for k in range(1, total + 1):
            for where, kwargs in (("before", {"kill_before_call": k}),
                                  ("after", {"kill_after_call": k})):
                sim, jdir, growth, uuid = _crashed_run(
                    tmp_path, name=f"call{k}{where}", **kwargs)
                self._assert_recovered(
                    sim, jdir, growth, uuid, initial, twin,
                    point=f"kill {where} admin call {k}")

    def test_mid_inter_phase_sigkill_resumes_same_uuid(self, tmp_path):
        """The headline pin spelled out: SIGKILL mid-inter-broker phase
        with a throttle applied -> restart -> the SAME uuid resumes,
        adopted moves are polled (not re-submitted), final placement is
        byte-equal to the uncrashed twin, zero throttles remain."""
        twin = _twin_placement()
        sim, jdir, growth, uuid = _crashed_run(
            tmp_path, kill_sleep=2, throttle=100e6, name="headline")
        # the crash left the cluster mid-move with throttles applied
        assert any(b.throttle is not None
                   for b in sim._brokers.values())
        assert sim.list_partition_reassignments()
        ex2, report = _recover(sim, jdir, growth, mode="resume")
        assert report is not None and report["uuid"] == uuid
        assert report["resumed"] is True
        assert report["tasksAdopted"] >= 1
        assert _placement(sim) == twin
        assert all(n <= 1 for n in growth.values())
        assert all(b.throttle is None for b in sim._brokers.values())
        # the resumed run settled its journal: a SECOND restart finds
        # nothing to recover
        ex3, report3 = _recover(sim, jdir, growth)
        assert report3 is None


class TestDoubleCrash:
    def test_crash_during_resume_recovers_again(self, tmp_path):
        """A SECOND crash mid-resume must replay the re-journaled
        segment correctly: sealed terminal states stay sealed (review
        finding: the resume used to re-journal tasks as PENDING) and
        the third process still converges to the twin."""
        twin = _twin_placement()
        sim, jdir, growth, uuid = _crashed_run(
            tmp_path, kill_sleep=3, name="double")
        # process 2: resume, but crash again on its first sleep
        journal2 = ExecutionJournal(
            jdir, time_fn=lambda: sim.now_ms() / 1000.0)
        proxy2 = CrashyAdmin(sim, growth, journal=journal2)
        ex2 = Executor(proxy2, progress_check_interval_s=1.0,
                       journal=journal2,
                       time_fn=lambda: sim.now_ms() / 1000.0)
        sleeps = {"n": 0}

        def crashing_sleep(s):
            sleeps["n"] += 1
            if sleeps["n"] == 1:
                proxy2.on = False
                journal2.broken = True
                raise _Killed("second kill")
            sim.advance(s)
        ex2._sleep = crashing_sleep
        report2 = ex2.recover(mode="resume", wait=True)
        assert report2 is not None and report2["uuid"] == uuid
        # process 3: recover again and finish
        ex3, report3 = _recover(sim, jdir, growth)
        if report3 is not None:
            assert report3["uuid"] == uuid
        assert _placement(sim) == twin
        for tp, n in growth.items():
            assert n <= 1, f"{tp} submitted {n} times across 3 processes"
        assert all(b.throttle is None for b in sim._brokers.values())

    def test_orphan_throttle_clear_is_attributed(self, tmp_path):
        """The recovery-time throttle clear is journaled under the
        replayed execution's uuid (review finding: uuid=None records
        were dropped by replay, so every restart re-cleared)."""
        sim, jdir, growth, uuid = _crashed_run(
            tmp_path, kill_sleep=2, throttle=100e6, name="attrib")
        ex2, report = _recover(sim, jdir, growth, mode="abort")
        assert report is not None
        assert report["clearedThrottleBrokers"]
        # a later restart replays NO outstanding throttle
        journal3 = ExecutionJournal(
            jdir, time_fn=lambda: sim.now_ms() / 1000.0)
        replay = journal3.replay()
        assert replay.throttle_brokers == []


class TestAbortAndClean:
    def test_abort_cancels_clears_and_restores_history(self, tmp_path):
        sim, jdir, growth, uuid = _crashed_run(
            tmp_path, kill_sleep=2, throttle=100e6,
            removed=[3], name="abort")
        assert sim.list_partition_reassignments()
        ex2, report = _recover(sim, jdir, growth, mode="abort")
        assert report is not None and report["uuid"] == uuid
        assert report["resumed"] is False
        assert report["cancelledReassignments"] >= 1
        # abort-and-clean: nothing in flight, nothing leaked
        assert sim.list_partition_reassignments() == []
        assert all(b.throttle is None for b in sim._brokers.values())
        assert not ex2.has_ongoing_execution
        # removal history survived the bounce (exclusion windows hold)
        assert 3 in ex2.recently_removed_brokers()
        # the journal is settled: a restart finds nothing to recover
        ex3, report3 = _recover(sim, jdir, growth, mode="abort")
        assert report3 is None


class TestJournalReplay:
    def _segments(self, jdir):
        return sorted(p for p in os.listdir(jdir)
                      if p.startswith("journal-"))

    def test_torn_tail_truncated_at_first_bad_record(self, tmp_path):
        twin = _twin_placement()
        sim, jdir, growth, uuid = _crashed_run(tmp_path, kill_sleep=2,
                                               name="torn")
        seg = os.path.join(jdir, self._segments(jdir)[-1])
        with open(seg, "ab") as fh:
            fh.write(b"deadbeef {\"t\":\"garbage")   # torn tail
        ex2, report = _recover(sim, jdir, growth)
        assert report is not None
        assert report["journalTruncated"] is True
        assert report["uuid"] == uuid
        assert _placement(sim) == twin

    def test_corrupt_mid_record_stops_replay_there(self, tmp_path):
        twin = _twin_placement()
        sim, jdir, growth, uuid = _crashed_run(tmp_path, kill_sleep=3,
                                               name="corrupt")
        seg = os.path.join(jdir, self._segments(jdir)[-1])
        with open(seg, "rb") as fh:
            lines = fh.readlines()
        assert len(lines) >= 3
        # flip one byte inside a middle record's payload
        mid = len(lines) // 2
        corrupted = bytearray(lines[mid])
        corrupted[12] ^= 0xFF
        lines[mid] = bytes(corrupted)
        with open(seg, "wb") as fh:     # test-only surgery
            fh.writelines(lines)
        # replay stops at the corrupt record; metadata reconciliation
        # still recovers the execution to the twin placement
        ex2, report = _recover(sim, jdir, growth)
        assert report is not None
        assert report["journalTruncated"] is True
        assert _placement(sim) == twin
        assert all(n <= 1 for n in growth.values())

    def test_crc_framing_units(self, tmp_path):
        path = str(tmp_path / "frames.jsonl")
        with open(path, "ab") as fh:
            fh.write(persist.json_frame({"a": 1}))
            fh.write(persist.json_frame({"b": 2}))
        records, truncated = persist.read_crc_json(path)
        assert records == [{"a": 1}, {"b": 2}] and not truncated
        with open(path, "ab") as fh:
            fh.write(b"0000000 not-a-frame\n")
            fh.write(persist.json_frame({"c": 3}))
        records, truncated = persist.read_crc_json(path)
        # truncation at the FIRST bad record: the valid frame after the
        # garbage is NOT trusted
        assert records == [{"a": 1}, {"b": 2}] and truncated

    def test_per_tenant_journal_isolation(self, tmp_path):
        """Two tenants, two journal dirs: tenant A's crash never leaks
        into tenant B's recovery and vice versa."""
        twin = _twin_placement()
        sim_a, jdir_a, growth_a, uuid_a = _crashed_run(
            tmp_path, kill_sleep=2, name="tenantA")
        # tenant B: own dir, clean run to completion
        sim_b = _sim()
        jdir_b = str(tmp_path / "tenantB")
        jb = ExecutionJournal(jdir_b,
                              time_fn=lambda: sim_b.now_ms() / 1000.0)
        ex_b = Executor(sim_b, progress_check_interval_s=1.0,
                        journal=jb,
                        time_fn=lambda: sim_b.now_ms() / 1000.0,
                        sleep_fn=sim_b.advance)
        ex_b.execute_proposals(_proposals(), reason="b", wait=True)
        # B's recovery: nothing in flight (its journal is settled)
        ex_b2, report_b = _recover(sim_b, jdir_b, {})
        assert report_b is None
        # A's recovery: resumes only its own execution
        ex_a2, report_a = _recover(sim_a, jdir_a, growth_a)
        assert report_a is not None and report_a["uuid"] == uuid_a
        assert _placement(sim_a) == twin

    def test_history_survives_restart(self, tmp_path):
        sim = _sim()
        jdir = str(tmp_path / "hist")
        j = ExecutionJournal(jdir,
                             time_fn=lambda: sim.now_ms() / 1000.0)
        ex = Executor(sim, progress_check_interval_s=1.0, journal=j,
                      time_fn=lambda: sim.now_ms() / 1000.0,
                      sleep_fn=sim.advance)
        ex.execute_proposals(_proposals(), reason="hist", wait=True,
                             removed_brokers=[0], demoted_brokers=[1])
        j2 = ExecutionJournal(jdir,
                              time_fn=lambda: sim.now_ms() / 1000.0)
        ex2 = Executor(sim, journal=j2,
                       time_fn=lambda: sim.now_ms() / 1000.0)
        assert ex2.recently_removed_brokers() == {0}
        assert ex2.recently_demoted_brokers() == {1}
        ex2.drop_recently_removed_brokers([0])
        j3 = ExecutionJournal(jdir,
                              time_fn=lambda: sim.now_ms() / 1000.0)
        ex3 = Executor(sim, journal=j3,
                       time_fn=lambda: sim.now_ms() / 1000.0)
        assert ex3.recently_removed_brokers() == set()
        assert ex3.recently_demoted_brokers() == {1}


class TestJournalDegradation:
    """Journal failure must degrade to journal-less execution — never
    fail the rebalance (sites executor.journal.write/fsync)."""

    def test_write_failure_degrades_not_fails(self, tmp_path):
        sim = _sim()
        jdir = str(tmp_path / "sick")
        j = ExecutionJournal(jdir,
                             time_fn=lambda: sim.now_ms() / 1000.0)
        degraded = []
        j.on_error = degraded.append
        ex = Executor(sim, progress_check_interval_s=1.0, journal=j,
                      time_fn=lambda: sim.now_ms() / 1000.0,
                      sleep_fn=sim.advance)
        plan = faults.FaultPlan().fail_always("executor.journal.write")
        with faults.injected(plan):
            ex.execute_proposals(_proposals(), reason="sick", wait=True)
        # the rebalance completed despite the dead journal
        assert _placement(sim) == _twin_placement()
        assert j.broken and j.errors >= 1
        assert len(degraded) == 1     # anomaly hook fired exactly once
        assert not ex.has_ongoing_execution

    def test_fsync_failure_degrades_not_fails(self, tmp_path):
        sim = _sim()
        j = ExecutionJournal(str(tmp_path / "fsync"),
                             time_fn=lambda: sim.now_ms() / 1000.0)
        ex = Executor(sim, progress_check_interval_s=1.0, journal=j,
                      time_fn=lambda: sim.now_ms() / 1000.0,
                      sleep_fn=sim.advance)
        plan = faults.FaultPlan().fail_nth("executor.journal.fsync", 1)
        with faults.injected(plan):
            ex.execute_proposals(_proposals(), reason="eio", wait=True)
        assert _placement(sim) == _twin_placement()
        assert j.broken and j.errors >= 1


class TestPollFailureConfig:
    """Satellite: the hardcoded _max_consecutive_poll_failures=10 is
    now executor.max.consecutive.poll.failures, with the =1 fail-fast
    edge covered."""

    def test_fail_fast_edge(self):
        sim = _sim()
        ex = Executor(sim, progress_check_interval_s=1.0,
                      max_consecutive_poll_failures=1,
                      time_fn=lambda: sim.now_ms() / 1000.0,
                      sleep_fn=sim.advance)
        finished = []

        class Notifier:
            def on_execution_finished(self, uuid, ok, msg):
                finished.append((ok, msg))
        ex._notifier = Notifier()
        # two consecutive poll failures: the first is tolerated
        # (1 allowed), the second fails the execution
        plan = faults.FaultPlan().fail_nth(
            "executor.admin.describe_cluster", (3, 4, 5, 6))
        with faults.injected(plan):
            ex.execute_proposals(
                [_proposal("t", 0, [0, 1], [2, 1], size=40e6)],
                wait=True)
        assert finished and finished[0][0] is False
        assert not ex.has_ongoing_execution

    def test_single_blip_still_tolerated_at_one(self):
        sim = _sim()
        ex = Executor(sim, progress_check_interval_s=1.0,
                      max_consecutive_poll_failures=1,
                      time_fn=lambda: sim.now_ms() / 1000.0,
                      sleep_fn=sim.advance)
        plan = faults.FaultPlan().fail_nth(
            "executor.admin.describe_cluster", 3)
        with faults.injected(plan):
            ex.execute_proposals(
                [_proposal("t", 0, [0, 1], [2, 1], size=40e6)],
                wait=True)
        snap = sim.describe_cluster()
        assert set(snap.partition(
            TopicPartition("t", 0)).replicas) == {1, 2}
        assert ex.num_poll_failures_tolerated == 1

    def test_config_key_wiring(self, tmp_path):
        from cruise_control_tpu.common.config import load_properties
        from cruise_control_tpu.config.main_config import (
            CruiseControlConfig)
        from cruise_control_tpu.main import build_cruise_control
        props = tmp_path / "cc.properties"
        props.write_text(
            "capacity.config.file=\n"
            "sample.store.directory=" + str(tmp_path / "s") + "\n"
            "executor.max.consecutive.poll.failures=3\n"
            "executor.journal.dir=" + str(tmp_path / "jrn") + "\n"
            "executor.recovery.mode=abort\n")
        config = CruiseControlConfig(load_properties(str(props)))
        sim = _sim()
        cc = build_cruise_control(config, sim)
        try:
            assert cc.executor._max_consecutive_poll_failures == 3
            assert cc.executor_journal is not None
            assert cc.executor_journal.directory == str(tmp_path / "jrn")
            assert cc._executor_recovery_mode == "abort"
        finally:
            cc.shutdown()


class TestFacadeRecovery:
    """The facade surface: EXECUTION_RECOVERY anomaly, STATE recovery
    block, recovery sensors, and the detector's fix-in-progress gate."""

    def _facade(self, sim, jdir, notifier=None):
        from cruise_control_tpu.facade import CruiseControl
        from cruise_control_tpu.monitor.sampling.sampler import (
            SimulatedClusterSampler)
        return CruiseControl(
            sim, SimulatedClusterSampler(sim),
            anomaly_notifier=notifier,
            time_fn=lambda: sim.now_ms() / 1000.0,
            sleep_fn=sim.advance,
            executor_kwargs=dict(progress_check_interval_s=1.0),
            executor_journal_dir=jdir,
            auto_warmup=False, scheduler_enabled=False)

    def test_recovery_surfaces_everywhere(self, tmp_path):
        from cruise_control_tpu.detector.anomalies import (
            ExecutionRecovery)
        from cruise_control_tpu.detector.notifier import (
            AnomalyNotifier, NotificationAction)

        class Recorder(AnomalyNotifier):
            def __init__(self):
                self.anomalies = []

            def on_anomaly(self, anomaly):
                self.anomalies.append(anomaly)
                return NotificationAction.ignore()

            def self_healing_enabled(self):
                return {}

        twin = _twin_placement()
        sim, jdir, growth, uuid = _crashed_run(tmp_path, kill_sleep=2,
                                               name="facade")
        rec = Recorder()
        cc = self._facade(sim, jdir, notifier=rec)
        try:
            report = cc.recover_interrupted_execution()
            assert report is not None and report["uuid"] == uuid
            cc.executor.await_completion(timeout=60.0)
            assert _placement(sim) == twin
            # idempotent: the second call (start_up would make one)
            # does nothing
            assert cc.recover_interrupted_execution() is None
            # anomaly routed through the notifier plane
            cc.anomaly_detector.process_all()
            recovered = [a for a in rec.anomalies
                         if isinstance(a, ExecutionRecovery)]
            assert recovered and recovered[0].uuid == uuid
            # STATE recovery block
            state = cc.state(substates=["executor"])["ExecutorState"]
            assert state["recovery"]["journalEnabled"] is True
            assert state["recovery"]["lastRecovery"]["uuid"] == uuid
            # sensors
            sensors = cc.metrics.to_json()
            assert sensors["executor-recoveries"]["count"] == 1
            assert sensors["executor-journal-writes"]["value"] > 0
        finally:
            cc.shutdown()

    def test_detector_blocked_while_reconciling(self, tmp_path):
        sim = _sim()
        cc = self._facade(sim, str(tmp_path / "gate"))
        try:
            gate = cc.anomaly_detector._fix_in_progress
            assert gate() is False
            cc.executor._recovery_in_progress = True
            assert gate() is True      # self-heal blocked mid-recovery
            cc.executor._recovery_in_progress = False
            assert gate() is False
        finally:
            cc.shutdown()


class TestSampleStoreDurability:
    """Satellite: retention compaction on the store cadence (the files
    no longer grow unbounded) + the fsync-on-store option."""

    def _samples(self, t_ms, n=4):
        from cruise_control_tpu.monitor.sampling.holder import (
            PartitionMetricSample)
        from cruise_control_tpu.monitor.sampling.sampler import Samples
        s = Samples()
        for i in range(n):
            s.partition_samples.append(PartitionMetricSample(
                broker_id=0, tp=TopicPartition("t", i),
                sample_time_ms=t_ms, values={0: 1.0}))
        return s

    def test_compaction_bounds_file_growth(self, tmp_path):
        from cruise_control_tpu.monitor.sampling.sample_store import (
            FileSampleStore)
        clock = {"now": 1_000.0}
        store = FileSampleStore(
            str(tmp_path), partition_retention_ms=10_000.0,
            compaction_interval_ms=1.0,
            time_fn=lambda: clock["now"])
        path = os.path.join(str(tmp_path),
                            FileSampleStore.PARTITION_FILE)
        store.store_samples(self._samples(clock["now"] * 1000.0))
        size_1 = os.path.getsize(path)
        # a long retention-window's worth of stores: without
        # compaction the file would grow linearly forever
        for _ in range(30):
            clock["now"] += 5.0
            store.store_samples(self._samples(clock["now"] * 1000.0))
        assert store.compactions > 0
        assert store.evicted_samples > 0
        # bounded: at most ~ the retention window of samples remains
        assert os.path.getsize(path) <= size_1 * 4
        # survivors still load
        loaded = []

        class Loader:
            def load_samples(self, samples):
                loaded.append(samples)
        store.load_samples(Loader())
        assert loaded[0].partition_samples
        assert all(s.sample_time_ms >= clock["now"] * 1000.0 - 10_000.0
                   for s in loaded[0].partition_samples)
        store.close()

    def test_evict_samples_before_hook(self, tmp_path):
        from cruise_control_tpu.monitor.sampling.sample_store import (
            FileSampleStore)
        store = FileSampleStore(str(tmp_path), fsync=True,
                                time_fn=lambda: 100.0)
        store.store_samples(self._samples(1_000.0))
        store.store_samples(self._samples(50_000.0))
        store.evict_samples_before(10_000.0)
        loaded = []

        class Loader:
            def load_samples(self, samples):
                loaded.append(samples)
        store.load_samples(Loader())
        times = {s.sample_time_ms
                 for s in loaded[0].partition_samples}
        assert times == {50_000.0}
        store.close()

    def test_compaction_drops_unreadable_records(self, tmp_path):
        from cruise_control_tpu.monitor.sampling.sample_store import (
            FileSampleStore)
        store = FileSampleStore(str(tmp_path), time_fn=lambda: 100.0)
        store.store_samples(self._samples(90_000.0))
        path = os.path.join(str(tmp_path),
                            FileSampleStore.PARTITION_FILE)
        with open(path, "ab") as fh:   # a corrupt length-prefixed rec
            fh.write(struct.pack("<I", 4) + b"\xff\xff\xff\xff")
        store.store_samples(self._samples(95_000.0))
        store.evict_samples_before(0.0)
        assert store.evicted_samples >= 1   # the corrupt record
        loaded = []

        class Loader:
            def load_samples(self, samples):
                loaded.append(samples)
        store.load_samples(Loader())
        assert len(loaded[0].partition_samples) == 8
        store.close()


class TestDurableWriteLintRule:
    def _lint(self, tmp_path, body, relpath="cruise_control_tpu/mod.py"):
        """Per-file G105 findings from the whole-program analyzer
        (tools/analysis/ — the ISSUE-15 successor of the flat lint;
        single-file parse set = the old per-file semantics)."""
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        try:
            from analysis import cli
        finally:
            sys.path.pop(0)
        mod = tmp_path / relpath
        mod.parent.mkdir(parents=True, exist_ok=True)
        mod.write_text(body)
        return [f.render() for f in cli.analyze([mod], tmp_path)
                if "durable-write" in f.message]

    def test_flags_truncating_open_and_rename(self, tmp_path):
        findings = self._lint(tmp_path, (
            "import os\n\n\n"
            "def f(p):\n"
            "    with open(p, \"w\") as fh:\n"
            "        fh.write(\"x\")\n"
            "    os.replace(p, p + \".bak\")\n"))
        assert len(findings) == 2

    def test_allows_append_and_reads(self, tmp_path):
        findings = self._lint(tmp_path, (
            "def f(p):\n"
            "    with open(p) as fh:\n"
            "        fh.read()\n"
            "    with open(p, \"ab\") as fh:\n"
            "        fh.write(b\"x\")\n"))
        assert findings == []

    def test_persist_module_is_exempt(self, tmp_path):
        body = "import os\n\n\ndef f(a, b):\n    os.replace(a, b)\n"
        assert self._lint(
            tmp_path, body,
            relpath="cruise_control_tpu/utils/persist.py") == []
