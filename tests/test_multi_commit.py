"""Multi-commit round semantics: cumulative gating and the refuel escape.

The round kernels commit several actions against one broker per round
(rank_accept + headroom terms).  These tests pin the two contracts that
make that safe: (a) a committed batch never exceeds any prior goal's
strict headroom at a destination beyond the single boolean-validated
first arrival, and (b) the leader-count goal's refuel phase escapes the
band-floor deadlock that single-direction shedding cannot.
"""
import conftest  # noqa: F401

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import kernels


def test_rank_accept_respects_cumulative_headroom():
    num_b = 10
    C = 12
    # all candidates target broker 3, weights 2.0 each, headroom 5.0:
    # the first arrival is boolean-validated (exempt), then the
    # cumulative gate admits ranks while cum <= hr: 2, 4 -> next would
    # be 6 > 5, so exactly 2 term-gated arrivals + nothing more
    dest = jnp.full((C,), 3, jnp.int32)
    gain = jnp.arange(C, 0, -1).astype(jnp.float32)
    has = jnp.ones((C,), bool)
    keep = kernels.rank_accept(
        dest, gain, has, num_b,
        taken_cnt=jnp.zeros((num_b,), jnp.int32),
        cap=jnp.full((num_b,), 64, jnp.int32),
        cum_d=[jnp.zeros((num_b,))],
        d_w=[jnp.full((C,), 2.0)],
        hr_d=[jnp.full((num_b,), 5.0)])
    assert int(np.asarray(keep).sum()) == 2
    # the accepted ones are the highest-gain candidates
    assert np.asarray(keep)[:2].all()


def test_rank_accept_first_arrival_exempt_only_when_virgin():
    num_b = 4
    dest = jnp.zeros((3,), jnp.int32)
    gain = jnp.asarray([3.0, 2.0, 1.0])
    has = jnp.ones((3,), bool)
    # headroom 0: only the virgin-destination exemption admits anyone
    keep = kernels.rank_accept(
        dest, gain, has, num_b,
        taken_cnt=jnp.zeros((num_b,), jnp.int32),
        cap=jnp.full((num_b,), 64, jnp.int32),
        cum_d=[jnp.zeros((num_b,))],
        d_w=[jnp.ones((3,))], hr_d=[jnp.zeros((num_b,))])
    assert int(np.asarray(keep).sum()) == 1
    # already-taken destination: no exemption, headroom 0 blocks all
    keep2 = kernels.rank_accept(
        dest, gain, has, num_b,
        taken_cnt=jnp.asarray([1, 0, 0, 0], jnp.int32),
        cap=jnp.full((num_b,), 64, jnp.int32),
        cum_d=[jnp.zeros((num_b,))],
        d_w=[jnp.ones((3,))], hr_d=[jnp.zeros((num_b,))])
    assert int(np.asarray(keep2).sum()) == 0


def test_segment_rank_matches_table_append_contract():
    seg = jnp.asarray([2, 0, 2, 2, 1, 0], jnp.int32)
    order, seg_s, start, pos = kernels.segment_rank(seg, 4)
    # ranks within each segment are 0..k-1 and stable by index
    got = {}
    o = np.asarray(order)
    p = np.asarray(pos)
    for i in range(len(o)):
        got.setdefault(int(np.asarray(seg_s)[i]), []).append(int(p[i]))
    assert got[0] == [0, 1] and got[1] == [0] and got[2] == [0, 1, 2]


@pytest.mark.parametrize("seed", [4, 9])
@pytest.mark.slow
def test_leader_goal_escapes_band_floor(seed):
    """End-to-end: after the full stack, leader-count violations shrink
    to a small residual — the refuel phase must break the measured
    deadlock where every shed off an over-count broker is vetoed by a
    prior goal's band floor (see PARITY.md round 3)."""
    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.testing.random_cluster import (
        RandomClusterSpec, random_cluster)

    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=64, num_partitions=4000, replication_factor=3,
        num_racks=8, num_topics=10, seed=seed, skew_fraction=0.2))
    res = GoalOptimizer(default_goals(max_rounds=96),
                        pipeline_segment_size=5).optimizations(
        state, topo, OptimizationOptions())
    before, _, after = res.violated_broker_counts[
        "LeaderReplicaDistributionGoal"]
    assert after <= max(3, before // 5), (before, after)
