"""Differential test against hand-derived reference behavior on the
reference's own deterministic fixture.

The reference's DeterministicClusterTest runs its GoalOptimizer over
DeterministicCluster.smallClusterModel (cruise-control/src/test/java/.../
common/DeterministicCluster.java:307-344) and verifies via
OptimizationVerifier.  No JVM exists in this environment, so the expected
outcome is DERIVED BY HAND from the fixture and the reference's goal
semantics (AbstractGoal.java:179-221 maybeApplyBalancingAction,
RackAwareGoal.java:43) and pinned here:

Fixture (brokers 0,1 in rack "0"; broker 2 in rack "1"; RF=2):

    partition  leader  follower   racks      rack-aware?
    T1-0       b0      b2         {0, 1}     yes
    T1-1       b1      b0         {0, 0}     NO
    T2-0       b1      b2         {0, 1}     yes
    T2-1       b0      b2         {0, 1}     yes
    T2-2       b0      b1         {0, 0}     NO

Derivation: rack 1 contains exactly one broker (2), so rack awareness
FORCES one replica each of T1-1 and T2-2 onto broker 2 — the destination
is unique, not a heuristic choice; which of the two replicas moves is
implementation-defined (the reference walks its sorted replica list; any
choice is equally valid and the reference itself accepts either via
OptimizationVerifier).  Broker capacities (CPU=100, NW_IN=300K,
NW_OUT=200K, DISK=300K per TestConstants.BROKER_CAPACITY) exceed every
post-move load by construction, so capacity goals force nothing and the
two rack moves are the only REQUIRED actions of the hard-goal phase.
"""
import numpy as np

import conftest  # noqa: F401

from cruise_control_tpu.analyzer.goals.registry import (DEFAULT_HARD_GOALS,
                                                        default_goals)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.model import state as S
from cruise_control_tpu.testing.fixtures import reference_small_cluster
from cruise_control_tpu.testing.verifier import run_and_verify


def _placements(state, topo):
    """{(topic, partition): frozenset(broker_ids)} + leader map."""
    part = np.asarray(state.replica_partition)
    broker = np.asarray(state.replica_broker)
    valid = np.asarray(state.replica_valid)
    leader = np.asarray(state.replica_is_leader)
    out, leaders = {}, {}
    for r in np.nonzero(valid)[0]:
        pid = topo.partitions[part[r]]
        key = (pid.topic, pid.partition)
        out.setdefault(key, set()).add(int(topo.broker_ids[broker[r]]))
        if leader[r]:
            leaders[key] = int(topo.broker_ids[broker[r]])
    return {k: frozenset(v) for k, v in out.items()}, leaders


def test_initial_fixture_matches_reference_exactly():
    state, topo = reference_small_cluster()
    load = np.asarray(S.broker_load(state))
    # hand-computed initial broker loads (CPU, NW_IN, NW_OUT, DISK)
    np.testing.assert_allclose(load[0], [69.5, 260.0, 295.0, 280.0],
                               rtol=1e-6)
    np.testing.assert_allclose(load[1], [28.0, 140.0, 116.0, 155.0],
                               rtol=1e-6)
    np.testing.assert_allclose(load[2], [19.5, 130.0, 0.0, 135.0],
                               rtol=1e-6)


def test_hard_goals_reproduce_derived_reference_outcome():
    state, topo = reference_small_cluster()
    before, _ = _placements(state, topo)
    opt = GoalOptimizer(default_goals(names=DEFAULT_HARD_GOALS))
    result = run_and_verify(opt, state, topo)   # shared oracle invariants
    after, leaders = _placements(result.final_state, topo)

    # the two forced rack moves: T1-1 and T2-2 each gain broker 2 and
    # keep exactly one of their original rack-0 brokers
    for key, original in ((("T1", 1), {1, 0}), (("T2", 2), {0, 1})):
        placed = set(after[key])
        assert 2 in placed, f"{key} must reach rack 1 (broker 2): {placed}"
        assert len(placed) == 2
        assert placed - {2} <= original, (
            f"{key} rack-0 replica must be one of the originals: {placed}")

    # rack-aware partitions with satisfied capacity stay untouched —
    # adding brokers 0..2 changes nothing for them, and the reference's
    # verifier rejects gratuitous movement of already-satisfied partitions
    for key in (("T1", 0), ("T2", 0), ("T2", 1)):
        assert after[key] == before[key], (
            f"already rack-aware {key} moved: {before[key]} -> {after[key]}")

    # every partition ends rack-aware (the hard-goal contract)
    rack_of = {0: 0, 1: 0, 2: 1}
    for key, placed in after.items():
        racks = {rack_of[b] for b in placed}
        assert len(racks) == 2, f"{key} not rack aware: {placed}"

    assert not result.violated_goals_after
