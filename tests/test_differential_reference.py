"""Differential test against hand-derived reference behavior on the
reference's own deterministic fixture.

The reference's DeterministicClusterTest runs its GoalOptimizer over
DeterministicCluster.smallClusterModel (cruise-control/src/test/java/.../
common/DeterministicCluster.java:307-344) and verifies via
OptimizationVerifier.  No JVM exists in this environment, so the expected
outcome is DERIVED BY HAND from the fixture and the reference's goal
semantics (AbstractGoal.java:179-221 maybeApplyBalancingAction,
RackAwareGoal.java:43) and pinned here:

Fixture (brokers 0,1 in rack "0"; broker 2 in rack "1"; RF=2):

    partition  leader  follower   racks      rack-aware?
    T1-0       b0      b2         {0, 1}     yes
    T1-1       b1      b0         {0, 0}     NO
    T2-0       b1      b2         {0, 1}     yes
    T2-1       b0      b2         {0, 1}     yes
    T2-2       b0      b1         {0, 0}     NO

Derivation: rack 1 contains exactly one broker (2), so rack awareness
FORCES one replica each of T1-1 and T2-2 onto broker 2 — the destination
is unique, not a heuristic choice; which of the two replicas moves is
implementation-defined (the reference walks its sorted replica list; any
choice is equally valid and the reference itself accepts either via
OptimizationVerifier).  Broker capacities (CPU=100, NW_IN=300K,
NW_OUT=200K, DISK=300K per TestConstants.BROKER_CAPACITY) exceed every
post-move load by construction, so capacity goals force nothing and the
two rack moves are the only REQUIRED actions of the hard-goal phase.
"""
import numpy as np

import conftest  # noqa: F401

import pytest

from cruise_control_tpu.analyzer.goals.registry import (DEFAULT_HARD_GOALS,
                                                        default_goals)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.model import state as S
from cruise_control_tpu.testing.fixtures import reference_small_cluster
from cruise_control_tpu.testing.verifier import run_and_verify


def _placements(state, topo):
    """{(topic, partition): frozenset(broker_ids)} + leader map."""
    part = np.asarray(state.replica_partition)
    broker = np.asarray(state.replica_broker)
    valid = np.asarray(state.replica_valid)
    leader = np.asarray(state.replica_is_leader)
    out, leaders = {}, {}
    for r in np.nonzero(valid)[0]:
        pid = topo.partitions[part[r]]
        key = (pid.topic, pid.partition)
        out.setdefault(key, set()).add(int(topo.broker_ids[broker[r]]))
        if leader[r]:
            leaders[key] = int(topo.broker_ids[broker[r]])
    return {k: frozenset(v) for k, v in out.items()}, leaders


def test_initial_fixture_matches_reference_exactly():
    state, topo = reference_small_cluster()
    load = np.asarray(S.broker_load(state))
    # hand-computed initial broker loads (CPU, NW_IN, NW_OUT, DISK)
    np.testing.assert_allclose(load[0], [69.5, 260.0, 295.0, 280.0],
                               rtol=1e-6)
    np.testing.assert_allclose(load[1], [28.0, 140.0, 116.0, 155.0],
                               rtol=1e-6)
    np.testing.assert_allclose(load[2], [19.5, 130.0, 0.0, 135.0],
                               rtol=1e-6)


def test_hard_goals_reproduce_derived_reference_outcome():
    state, topo = reference_small_cluster()
    before, _ = _placements(state, topo)
    opt = GoalOptimizer(default_goals(names=DEFAULT_HARD_GOALS))
    result = run_and_verify(opt, state, topo)   # shared oracle invariants
    after, leaders = _placements(result.final_state, topo)

    # the two forced rack moves: T1-1 and T2-2 each gain broker 2 and
    # keep exactly one of their original rack-0 brokers
    for key, original in ((("T1", 1), {1, 0}), (("T2", 2), {0, 1})):
        placed = set(after[key])
        assert 2 in placed, f"{key} must reach rack 1 (broker 2): {placed}"
        assert len(placed) == 2
        assert placed - {2} <= original, (
            f"{key} rack-0 replica must be one of the originals: {placed}")

    # rack-aware partitions with satisfied capacity stay untouched —
    # adding brokers 0..2 changes nothing for them, and the reference's
    # verifier rejects gratuitous movement of already-satisfied partitions
    for key in (("T1", 0), ("T2", 0), ("T2", 1)):
        assert after[key] == before[key], (
            f"already rack-aware {key} moved: {before[key]} -> {after[key]}")

    # every partition ends rack-aware (the hard-goal contract)
    rack_of = {0: 0, 1: 0, 2: 1}
    for key, placed in after.items():
        racks = {rack_of[b] for b in placed}
        assert len(racks) == 2, f"{key} not rack aware: {placed}"

    assert not result.violated_goals_after


@pytest.mark.slow
def test_full_pipeline_pins_config1_outcome():
    """BENCH config 1 (the 3-broker deterministic fixture, full default
    goal stack) end-state pin, derived by hand — the full-pipeline analog
    of DeterministicClusterTest (reference cruise-control/src/test/java/
    .../common/DeterministicCluster.java:307 + DeterministicClusterTest).

    Fixture bands (margin = (1.1-1)*0.9 = 9% around the alive average):

      DISK  loads (120, 130, 100), avg 116.67, band [106.17, 127.17]
      NW_IN loads (160, 190, 150), avg 166.67, band [151.67, 181.67]
      NW_OUT loads (130, 110, 80), avg 106.67, band [ 97.07, 116.27]

    Derivation:

    1. Only T1-0 violates rack awareness (leader b0 + follower b1, both
       rack A); broker 2 is the only rack-B broker, so exactly ONE forced
       move exists: a T1-0 replica -> b2.  Which of the two replicas
       moves is implementation-defined (the reference walks its sorted
       list; OptimizationVerifier accepts either) — this solver
       deterministically moves the b1 follower.
    2. After that move (b1 -= [100 NW_IN, 75 DISK, ~3.35 CPU];
       b2 += same), every usage band holds exactly 2 violated brokers
       and NO further action is acceptable:
       * every replica move crosses a band limit on one end or is
         vetoed by RackAwareGoal / the strict branch of
         ResourceDistributionGoal.actionAcceptance (e.g. refilling b1
         with T2-0's leader re-violates rack awareness; T1-1's follower
         would duplicate the partition on b1);
       * the one deviation-improving SWAP (T1-0 leader on b0 for T1-1
         leader on b1, DISK delta 20) drops b0 from 120 to 100 against
         the DISK lower limit 106.17 — the reference REJECTS it twice
         over: the optimizing goal's own selfSatisfied
         (isSwapViolatingLimit, ResourceDistributionGoal.java:864-920)
         and, at later goals, the strict acceptance branch ("never make
         a balanced broker unbalanced", :98-123).  Until round 5 this
         framework's swap kernel lacked the band gate and COMMITTED the
         swap, ending DiskUsage/NetworkInbound at 3 violated brokers —
         worse than the initial 2 (the round-4 BENCH config-1 artifact
         this test pins against regressing).
       * the LeaderBytesIn residual (b0's leader carries 100 of NW_IN
         base against an upper bound of ~90.8) has one candidate
         transfer (to the T1-0 follower now on b2), which lands 100 on
         the already-highest-NW_IN broker — rejected by the goal's own
         strict-then-relaxed acceptance.
    """
    from cruise_control_tpu.analyzer.context import (OptimizationOptions,
                                                     make_context,
                                                     make_round_cache)
    from cruise_control_tpu.analyzer.goals.resource_distribution import \
        DiskUsageDistributionGoal
    from cruise_control_tpu.testing.fixtures import small_cluster

    state, topo = small_cluster()
    load0 = np.asarray(S.broker_load(state))
    # hand-computed initial loads (NW_IN, NW_OUT, DISK columns)
    np.testing.assert_allclose(load0[:, 1:], [[160.0, 130.0, 120.0],
                                              [190.0, 110.0, 130.0],
                                              [150.0, 80.0, 100.0]],
                               rtol=1e-6)

    opt = GoalOptimizer(default_goals(max_rounds=192),
                        pipeline_segment_size=2)
    result = opt.optimizations(state, topo, OptimizationOptions(),
                               check_sanity=False)

    # exactly the one forced rack move, nothing else
    assert len(result.proposals) == 1
    p = result.proposals[0]
    assert (p.partition.topic, p.partition.partition) == ("T1", 0)
    new_brokers = {r.broker_id for r in p.new_replicas}
    assert 2 in new_brokers and len(new_brokers) == 2
    assert not result.regressed_goals

    # pinned violated-broker counts (before -> after-own -> after-all):
    # the 2 -> 2 usage-goal end state is the reference-consistent fixed
    # point; 2 -> 3 (the round-4 artifact) is the swap-gate regression
    expected = {
        "RackAwareGoal": (2, 0, 0),
        "DiskUsageDistributionGoal": (2, 2, 2),
        "NetworkInboundUsageDistributionGoal": (2, 2, 2),
        "NetworkOutboundUsageDistributionGoal": (2, 2, 2),
        "CpuUsageDistributionGoal": (2, 2, 2),
        "LeaderBytesInDistributionGoal": (1, 1, 1),
    }
    nonzero = {g: c for g, c in result.violated_broker_counts.items()
               if any(c)}
    assert nonzero == expected, nonzero

    # final loads follow from the single move (CPU column is the
    # follower-CPU estimate, asserted via the run itself)
    load1 = np.asarray(S.broker_load(result.final_state))
    np.testing.assert_allclose(load1[:, 1:], [[160.0, 130.0, 120.0],
                                              [90.0, 110.0, 55.0],
                                              [250.0, 80.0, 175.0]],
                               rtol=1e-6)

    # the blocked swap, pinned explicitly: exchanging T1-0's leader (b0,
    # DISK 75) for T1-1's leader (b1, DISK 55) improves the DISK spread
    # but drops b0 below the lower limit — the goal's own acceptance
    # must reject it (reference isSwapViolatingLimit)
    fs = result.final_state
    ctx = make_context(fs, opt.constraint, OptimizationOptions(), topo)
    cache = make_round_cache(fs, 0, ctx)
    disk_goal = DiskUsageDistributionGoal()
    r_t10_leader = 0   # builder order: first replica of T1-0
    r_t11_leader = 2   # first replica of T1-1
    ok = np.asarray(disk_goal.accept_swap(
        fs, ctx, cache, np.asarray([r_t10_leader]),
        np.asarray([r_t11_leader])))
    assert not ok.any(), "band-crossing swap must be rejected"

    # fixed point: a second full optimization finds nothing to do
    again = opt.optimizations(fs, topo, OptimizationOptions(),
                              check_sanity=False)
    assert not again.proposals
    nonzero2 = {g: c for g, c in again.violated_broker_counts.items()
                if any(c)}
    assert {g: (b, a) for g, (b, o, a) in nonzero2.items()} == {
        g: (a, a) for g, (b, o, a) in expected.items() if a}
