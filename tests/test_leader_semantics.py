"""Hand-derived differential fixture: the leader-goal residual is
strict-priority SEMANTICS, not a search failure.

Round-3/4 VERDICT ask: LeaderReplicaDistribution leaves a violated
residual at 2.6K-broker scale whose transfers are vetoed by the
higher-priority CPU/NW_OUT usage goals' acceptance.  This fixture pins
the mechanism at hand-checkable size against the reference's acceptance
rules (reference ResourceDistributionGoal.actionAcceptance,
cruise-control/src/main/java/com/linkedin/kafka/cruisecontrol/analyzer/
goals/ResourceDistributionGoal.java:93-140):

  * LEADERSHIP_MOVEMENT src->dst is ACCEPTed only if, when both ends
    start inside the balance band, the destination stays under the upper
    bound AND the source stays over the lower bound after the bonus
    moves (the strict branch); when an end starts outside the band, the
    destination must not end up more loaded than the source (the
    relaxed branch).

Fixture: broker 0 leads six tiny-CPU partitions (leader-count 6 vs a
count band upper of 4 — violated); brokers 1-3 each lead one 40-CPU
partition, so broker 0 sits far BELOW the CPU balance band while every
possible receiver sits at/above its upper edge.  Every action that could
fix broker 0's leader count is then vetoed by the reference's own rules:

  * shedding leadership 0->k: broker 0 is under the CPU band, so the
    relaxed branch applies, and every receiver is already MORE
    CPU-loaded than broker 0 — rejected;
  * moving a leader replica 0->k: the receiver is above the CPU band
    upper, so the relaxed branch applies and fails the same way;
  * refueling broker 0 with a big-CPU leadership (to lift it toward the
    band): the 40-CPU bonus overshoots the band upper at broker 0 and
    drops the donor below its lower bound — the strict branch rejects.

The TPU pipeline must therefore leave broker 0 over the leader-count
band — matching what the reference's greedy would do — and that is
asserted here, together with the per-action vetoes."""
import conftest  # noqa: F401

import pytest

import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.count_distribution import (
    LeaderReplicaDistributionGoal, _count_bounds)
from cruise_control_tpu.analyzer.goals.resource_distribution import (
    CpuUsageDistributionGoal)
from cruise_control_tpu.common.resources import Resource as R
from cruise_control_tpu.model import state as S
from cruise_control_tpu.model.builder import ClusterModelBuilder

CAPACITY = {R.CPU: 100.0, R.NW_IN: 1000.0, R.NW_OUT: 1000.0,
            R.DISK: 2000.0}


def _fixture():
    b = ClusterModelBuilder()
    for broker, rack in ((0, "A"), (1, "A"), (2, "B"), (3, "B")):
        b.add_broker(broker, rack, CAPACITY)
    # six tiny-CPU partitions led by broker 0, followers spread on 1-3
    for p in range(6):
        b.add_partition("small", p, 0, [1 + p % 3],
                        {R.CPU: 3.0, R.NW_IN: 10.0, R.NW_OUT: 10.0,
                         R.DISK: 10.0})
    # one heavy-CPU partition led by each of brokers 1-3; the first one
    # keeps its follower on broker 0 (the refuel candidate whose veto is
    # asserted below — its follower base CPU is small, so broker 0 stays
    # far below the band), the rest chain among 1-3
    for i, leader in enumerate((1, 2, 3)):
        chain = 1 + (i + 1) % 3
        followers = [0, chain] if i == 0 else [chain]
        b.add_partition("big", i, leader, followers,
                        {R.CPU: 40.0, R.NW_IN: 10.0, R.NW_OUT: 10.0,
                         R.DISK: 10.0})
    return b.build()


def test_fixture_shape_matches_derivation():
    state, topo = _fixture()
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    cache = make_round_cache(state)
    counts = np.asarray(cache.leader_count, dtype=float)
    avg = counts.mean()
    lo, up = _count_bounds(jnp.asarray(avg), 0.09)
    assert counts[0] > float(up), (counts, float(up))

    cpu = np.asarray(cache.broker_load)[:, R.CPU]
    lower = float(np.asarray(ctx.balance_lower_pct)[R.CPU]) * 100.0
    upper = float(np.asarray(ctx.balance_upper_pct)[R.CPU]) * 100.0
    # broker 0 far below the CPU band; every receiver at/above its upper
    assert cpu[0] < lower, (cpu, lower)
    assert (cpu[1:] > upper).all(), (cpu, upper)


def test_every_fixing_action_is_vetoed_by_cpu_goal():
    state, topo = _fixture()
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    cache = make_round_cache(state)
    cpu_goal = CpuUsageDistributionGoal()
    rows = np.asarray(ctx.partition_replicas)
    cur = np.asarray(S.partition_leader_replica(state))
    broker_of = np.asarray(state.replica_broker)

    shed_vetoed = refuel_vetoed = 0
    for p in range(state.num_partitions):
        leader = cur[p]
        for r in rows[p]:
            if r < 0 or r == leader:
                continue
            ok = bool(np.asarray(cpu_goal.accept_leadership(
                state, ctx, cache, jnp.asarray(leader), jnp.asarray(r))))
            if broker_of[leader] == 0:
                # shedding broker 0's leadership: relaxed branch (source
                # below band) requires the receiver to end up no more
                # loaded than broker 0 — impossible here
                assert not ok, (p, leader, r)
                shed_vetoed += 1
            elif broker_of[r] == 0:
                # refueling broker 0 with a 40-CPU leadership: strict
                # branch fails both ends
                assert not ok, (p, leader, r)
                refuel_vetoed += 1
    assert shed_vetoed >= 6 and refuel_vetoed >= 1

    # the replica-move fallback is vetoed the same way: receivers are
    # above the CPU band upper, so the relaxed branch compares loads
    for r_id in np.nonzero(broker_of == 0)[0]:
        for dest in (1, 2, 3):
            ok = bool(np.asarray(cpu_goal.accept_move(
                state, ctx, cache, jnp.asarray(int(r_id)),
                jnp.asarray(dest))))
            assert not ok, (int(r_id), dest)


@pytest.mark.slow
def test_pipeline_leaves_the_semantic_residual():
    state, topo = _fixture()
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    cpu_goal = CpuUsageDistributionGoal()
    leader_goal = LeaderReplicaDistributionGoal(max_rounds=32)
    out = leader_goal.optimize(state, ctx, (cpu_goal,))
    counts = np.asarray(S.broker_leader_count(out), dtype=float)
    # broker 0 remains over the count band — the same residual the
    # reference's greedy leaves, because every fixing action fails its
    # acceptance rules (asserted action-by-action above)
    avg = counts.mean()
    _, up = _count_bounds(jnp.asarray(avg), 0.09)
    assert counts[0] > float(up), counts
    # and leadership never left broker 0's partitions' original owners
    # in a way that violates the CPU goal's band
    cache = make_round_cache(out)
    cpu = np.asarray(cache.broker_load)[:, R.CPU]
    upper = float(np.asarray(ctx.balance_upper_pct)[R.CPU]) * 100.0
    assert (cpu[1:] <= upper * 1.5).all()
