"""Tests for the core windowed aggregation engine.

Modeled on the reference's core test strategy (reference:
cruise-control-core/src/test/java/.../MetricSampleAggregatorTest.java:1-484
and RawMetricValuesTest.java:1-379) with an IntegerEntity-style fake entity.
"""
import dataclasses

import numpy as np
import pytest

from cruise_control_tpu.core.aggregator import (AggregationOptions,
                                                Extrapolation, Granularity,
                                                MetricSample,
                                                MetricSampleAggregator,
                                                NotEnoughValidWindowsError)
from cruise_control_tpu.core.anomaly import PercentileMetricAnomalyFinder
from cruise_control_tpu.core.metricdef import AggregationFunction, MetricDef


@dataclasses.dataclass(frozen=True)
class IntegerEntity:
    """reference CORE test IntegerEntity: entity with a named group."""
    group: str
    idx: int


WINDOW_MS = 1000
MIN_SAMPLES = 4


def make_metric_def():
    md = MetricDef()
    md.define("m_avg", AggregationFunction.AVG)
    md.define("m_max", AggregationFunction.MAX)
    md.define("m_latest", AggregationFunction.LATEST)
    return md.freeze()


def make_aggregator(num_windows=8):
    return MetricSampleAggregator(num_windows=num_windows, window_ms=WINDOW_MS,
                                  min_samples_per_window=MIN_SAMPLES,
                                  metric_def=make_metric_def())


def fill_window(agg, entity, window, num_samples=MIN_SAMPLES, value=10.0):
    """Put `num_samples` samples into the window covering
    ((window-1)*W, window*W]."""
    for i in range(num_samples):
        t = (window - 1) * WINDOW_MS + (i + 1) * WINDOW_MS // (num_samples + 1)
        agg.add_sample(MetricSample(
            entity, t, {0: value, 1: value * 2, 2: value * 3}))


def test_avg_max_latest_aggregation():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    # window 1: values 1..4 → avg 2.5, max 8, latest 12
    for i, v in enumerate([1.0, 2.0, 3.0, 4.0]):
        agg.add_sample(MetricSample(e, 100 + i * 100, {0: v, 1: v * 2, 2: v * 3}))
    # roll to make window 1 stable
    fill_window(agg, e, 2)
    result = agg.aggregate(0, 10_000, AggregationOptions())
    vae = result.entity_values[e]
    assert vae.window_times_ms[0] == WINDOW_MS
    np.testing.assert_allclose(vae.values[0], [2.5, 8.0, 12.0])
    assert not vae.extrapolations.get(0)


def test_avg_available_extrapolation():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    # half-min (2) samples in window 1 → AVG_AVAILABLE
    for i, v in enumerate([2.0, 4.0]):
        agg.add_sample(MetricSample(e, 100 + i * 100, {0: v, 1: v, 2: v}))
    fill_window(agg, e, 2)
    result = agg.aggregate(0, 10_000)
    vae = result.entity_values[e]
    assert vae.extrapolations[0] == Extrapolation.AVG_AVAILABLE
    np.testing.assert_allclose(vae.values[0, 0], 3.0)


def test_avg_adjacent_extrapolation():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    fill_window(agg, e, 1, value=10.0)
    # window 2 left empty
    fill_window(agg, e, 3, value=20.0)
    fill_window(agg, e, 4)  # roll so 3 is stable and has a right neighbour
    fill_window(agg, e, 5)
    result = agg.aggregate(0, 100_000)
    vae = result.entity_values[e]
    pos = vae.window_times_ms.index(2 * WINDOW_MS)
    assert vae.extrapolations[pos] == Extrapolation.AVG_ADJACENT
    # AVG metric: (4*10 + 4*20) / 8 = 15
    np.testing.assert_allclose(vae.values[pos, 0], 15.0)
    # MAX metric: (20 + 40) / 2 = 30 (counts==0 → divide by 2)
    np.testing.assert_allclose(vae.values[pos, 1], 30.0)


def test_forced_insufficient_extrapolation():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    agg.add_sample(MetricSample(e, 500, {0: 7.0, 1: 7.0, 2: 7.0}))
    fill_window(agg, e, 2)
    result = agg.aggregate(0, 10_000)
    vae = result.entity_values[e]
    assert vae.extrapolations[0] == Extrapolation.FORCED_INSUFFICIENT
    np.testing.assert_allclose(vae.values[0, 0], 7.0)


def test_window_rolling_evicts_old_windows():
    agg = make_aggregator(num_windows=4)
    e = IntegerEntity("g", 0)
    for w in range(1, 10):
        fill_window(agg, e, w)
    windows = agg.all_windows()
    assert len(windows) == 4
    assert windows[-1] == 8 * WINDOW_MS  # window 9 is current, 5..8 stable
    assert agg.num_abandoned_samples > 0


def test_too_old_sample_rejected():
    agg = make_aggregator(num_windows=2)
    e = IntegerEntity("g", 0)
    for w in range(5, 9):
        fill_window(agg, e, w)
    assert not agg.add_sample(MetricSample(e, 100, {0: 1.0, 1: 1.0, 2: 1.0}))


def test_partial_sample_rejected():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    with pytest.raises(ValueError, match="missing"):
        agg.add_sample(MetricSample(e, 100, {0: 1.0}))


def test_sparse_window_skipped_without_invalidating_entities():
    """A window failing min_valid_entity_ratio is excluded; entities with
    full data in the included windows stay valid (reference
    WindowState.maybeInclude / retainAllValidEntities)."""
    agg = make_aggregator()
    entities = [IntegerEntity("g", i) for i in range(10)]
    for w in [1, 5, 6]:
        for e in entities:
            fill_window(agg, e, w)
    # windows 2-4: samples for only 2 of 10 entities (a 3-wide gap defeats
    # AVG_ADJACENT, which needs both direct neighbours sufficient)
    for e in entities[:2]:
        for w in [2, 3, 4]:
            fill_window(agg, e, w)
    opts = AggregationOptions(min_valid_entity_ratio=0.5,
                              interested_entities=set(entities))
    result = agg.aggregate(0, 100_000, opts)
    comp = result.completeness
    for w in [2, 3, 4]:
        assert w * WINDOW_MS not in comp.valid_window_indices
    assert len(comp.valid_entities) == 10
    assert len(result.entity_values) == 10
    # the sparse windows must not appear in any entity's value windows
    assert all(3 * WINDOW_MS not in vae.window_times_ms
               for vae in result.entity_values.values())


def test_completeness_cache_hit():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    for w in range(1, 5):
        fill_window(agg, e, w)
    opts = AggregationOptions()
    c1 = agg.completeness(0, 100_000, opts)
    c2 = agg.completeness(0, 100_000, opts)
    assert c2 is c1  # served from cache at same generation
    fill_window(agg, e, 5)  # generation bump invalidates
    assert agg.completeness(0, 100_000, opts) is not c1


def test_completeness_entity_and_group_granularity():
    agg = make_aggregator()
    complete = IntegerEntity("topicA", 0)
    partial = IntegerEntity("topicA", 1)
    other = IntegerEntity("topicB", 2)
    for w in range(1, 6):
        fill_window(agg, complete, w)
        fill_window(agg, other, w)
        if w >= 3:  # `partial` misses windows 1-2 entirely
            fill_window(agg, partial, w)

    opts = AggregationOptions(interested_entities={complete, partial, other})
    comp = agg.completeness(0, 100_000, opts)
    assert complete in comp.valid_entities
    assert other in comp.valid_entities
    assert partial not in comp.valid_entities
    assert comp.valid_entity_ratio == pytest.approx(2 / 3)
    # topicA has an invalid member → group invalid
    assert comp.valid_entity_groups == {"topicB"}

    group_opts = dataclasses.replace(opts, granularity=Granularity.ENTITY_GROUP)
    comp2 = agg.completeness(0, 100_000, group_opts)
    assert comp2.valid_entities == {other}


def test_aggregate_raises_without_enough_windows():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    fill_window(agg, e, 1)  # only the current window exists: no stable ones
    with pytest.raises(NotEnoughValidWindowsError):
        agg.aggregate(0, 10_000, AggregationOptions(min_valid_windows=1))


def test_min_valid_entity_ratio_enforced():
    agg = make_aggregator()
    good = IntegerEntity("g", 0)
    bad = IntegerEntity("g", 1)
    for w in range(1, 4):
        fill_window(agg, good, w)
    opts = AggregationOptions(min_valid_entity_ratio=0.9,
                              interested_entities={good, bad})
    with pytest.raises(NotEnoughValidWindowsError):
        agg.aggregate(0, 100_000, opts)


def test_peek_current_window():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    fill_window(agg, e, 1)
    agg.add_sample(MetricSample(e, 1500, {0: 42.0, 1: 42.0, 2: 42.0}))
    peek = agg.peek_current_window()
    np.testing.assert_allclose(peek[e].values[0, 0], 42.0)


def test_retain_and_remove_entities():
    agg = make_aggregator()
    a, b = IntegerEntity("ga", 0), IntegerEntity("gb", 1)
    for w in range(1, 4):
        fill_window(agg, a, w)
        fill_window(agg, b, w)
    gen = agg.generation
    agg.retain_entities({a})
    assert agg.generation > gen
    result = agg.aggregate(0, 100_000)
    assert a in result.entity_values and b not in result.entity_values

    agg2 = make_aggregator()
    for w in range(1, 4):
        fill_window(agg2, a, w)
        fill_window(agg2, b, w)
    agg2.remove_entity_group({"gb"})
    result = agg2.aggregate(0, 100_000)
    assert a in result.entity_values and b not in result.entity_values


def test_generation_bumps_on_new_window():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    fill_window(agg, e, 1)
    g0 = agg.generation
    fill_window(agg, e, 2)
    assert agg.generation > g0


def test_percentile_anomaly_finder():
    agg = make_aggregator()
    e = IntegerEntity("g", 0)
    for w in range(1, 9):
        fill_window(agg, e, w, value=10.0)
    history = agg.aggregate(0, 1_000_000).entity_values
    # current window has a big spike
    agg.add_sample(MetricSample(e, agg.all_windows()[-1] + 10,
                                {0: 500.0, 1: 500.0, 2: 500.0}))
    current = agg.peek_current_window()
    finder = PercentileMetricAnomalyFinder(interested_metrics=[0])
    anomalies = finder.metric_anomalies(history, current)
    assert len(anomalies) == 1
    assert anomalies[0].metric_id == 0

    # normal value → no anomaly
    finder2 = PercentileMetricAnomalyFinder(interested_metrics=[0])
    normal_current = {ent: vae for ent, vae in history.items()}
    assert finder2.metric_anomalies(history, normal_current) == []
