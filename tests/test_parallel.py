"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

The replica axis shards over a 1-D mesh (`parallel/mesh.py`); jitting the
optimizer over sharded inputs must (a) produce the same proposals as the
single-device solve and (b) actually lay the replica arrays out across
devices.  This is the in-suite counterpart of the driver's
`dryrun_multichip` entry point.
"""
import os

import conftest  # noqa: F401

import jax
import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context)
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import heal_offline_replicas
from cruise_control_tpu.model.sanity import sanity_check
from cruise_control_tpu.parallel.mesh import (
    make_mesh, pad_state, shard_state, state_shardings)
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs the 8-device CPU mesh")


def _spec():
    return RandomClusterSpec(num_brokers=12, num_partitions=96,
                             replication_factor=3, num_racks=4,
                             num_topics=4, seed=3, skew_fraction=0.3,
                             dead_brokers=1)


@pytest.mark.slow
def test_sharded_full_step_matches_single_device():
    state, topo = random_cluster(_spec())
    goals = default_goals(max_rounds=8, names=[
        "RackAwareGoal", "DiskCapacityGoal", "DiskUsageDistributionGoal"])

    def full_step(st, c):
        st = heal_offline_replicas(st, c, max_rounds=8)
        for i, goal in enumerate(goals):
            st = goal.optimize(st, c, tuple(goals[:i]))
        return st

    # single-device reference
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    ref = jax.jit(full_step)(state, ctx)

    # sharded over the 8-device mesh
    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_state(state, mesh)
    ctx_s = make_context(sharded, BalancingConstraint(),
                         OptimizationOptions(), topo)
    shardings = state_shardings(sharded, mesh)
    step = jax.jit(full_step, in_shardings=(shardings, None),
                   out_shardings=shardings)
    with mesh:
        out = step(sharded, ctx_s)
        jax.block_until_ready(out.replica_broker)

    # replica arrays really live across devices
    assert len(out.replica_broker.sharding.device_set) == 8

    sanity_check(jax.device_get(out))
    n = state.num_replicas
    np.testing.assert_array_equal(np.asarray(ref.replica_broker),
                                  np.asarray(out.replica_broker)[:n])
    np.testing.assert_array_equal(np.asarray(ref.replica_is_leader),
                                  np.asarray(out.replica_is_leader)[:n])
    # no offline replicas survive on either path
    assert not (np.asarray(out.replica_offline)
                & np.asarray(out.replica_valid)).any()


@pytest.mark.slow
def test_sharded_full_goal_stack_runs_and_matches_quality():
    """The FULL default goal stack (15 goals) jitted over the 8-device
    mesh with the solver-mesh table constraints active must execute and
    land within the single-device run's violation counts (exact state
    equality is not required: sharded reductions reorder float sums).

    This is a LAYOUT check, not a convergence test (round-3 VERDICT
    weak-5: at max_rounds=12 it cost 345 s of suite wall-clock) — the
    round budget is kept to the minimum that still executes every
    goal's phase structure at least once.

    Runs in a SUBPROCESS: this is the one place the whole 15-goal chain
    compiles as a single SPMD program (production segments it), and
    that compile SEGFAULTS the XLA:CPU compiler when it runs late in a
    suite process that has already compiled hundreds of programs
    (reproduced twice at different suite positions, round 5; passes
    solo in ~6 min cold / seconds warm-cache).  Process isolation keeps
    the coverage without the crash."""
    import subprocess
    import sys

    if not os.environ.get("CC_TPU_SHARDED_SUBPROC"):
        env = dict(os.environ, CC_TPU_SHARDED_SUBPROC="1")
        # -p no:xdist (not "-n 0"): disables parallelism whether or not
        # pytest-xdist is installed — "-n" is an unknown flag wherever
        # xdist is absent (addopts no longer injects xdist flags either)
        r = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-x", "-p", "no:xdist",
             f"{__file__}::"
             "test_sharded_full_goal_stack_runs_and_matches_quality"],
            env=env, capture_output=True, text=True, timeout=1800,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-1000:])
        return

    from cruise_control_tpu.analyzer.context import make_round_cache
    from cruise_control_tpu.parallel.mesh import solver_mesh

    state, topo = random_cluster(_spec())
    goals = default_goals(max_rounds=4)

    def full_step(st, c):
        st = heal_offline_replicas(st, c, max_rounds=8)
        for i, goal in enumerate(goals):
            st = goal.optimize(st, c, tuple(goals[:i]))
        return st

    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    ref = jax.jit(full_step)(state, ctx)

    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_state(state, mesh)
    ctx_s = make_context(sharded, BalancingConstraint(),
                         OptimizationOptions(), topo)
    shardings = state_shardings(sharded, mesh)
    with solver_mesh(mesh):
        step = jax.jit(full_step, in_shardings=(shardings, None),
                       out_shardings=shardings)
        with mesh:
            out = step(sharded, ctx_s)
            jax.block_until_ready(out.replica_broker)
    assert len(out.replica_broker.sharding.device_set) == 8
    sanity_check(jax.device_get(out))
    # quality within reach of the single-device solve for every goal
    cache_r = make_round_cache(ref)
    cache_o = make_round_cache(jax.device_get(out))
    for i, g in enumerate(goals):
        v_ref = int(np.asarray(g.violated_brokers(
            ref, ctx, cache_r)).sum())
        v_out = int(np.asarray(g.violated_brokers(
            jax.device_get(out), ctx_s, cache_o)).sum())
        assert v_out <= v_ref + 2, (g.name, v_ref, v_out)


def test_pad_state_rounds_up_and_masks():
    state, _ = random_cluster(_spec())
    padded = pad_state(state, 7)
    assert padded.num_replicas % 7 == 0
    extra = padded.num_replicas - state.num_replicas
    assert extra > 0   # spec chosen so padding actually happens
    assert not np.asarray(padded.replica_valid)[-extra:].any()
