"""Persistent compiled-program cache (parallel/progcache.py).

Tier-1 `progcache` marker coverage per the ISSUE-8 acceptance criteria:

* cached-vs-fresh byte equality: a solve served by hydrated cache
  entries returns proposals IDENTICAL to the fresh-compile run, and the
  cache-enabled path is byte-identical to the cache-disabled path;
* warm "cold start" performs ZERO source-program compiles (pinned via
  the gateway compile-count instrumentation AND the empty shared
  jit-program dict);
* stale-fingerprint rejection: a bumped fingerprint term makes every
  old entry a miss (recompile), never a wrong answer;
* corrupt-entry quarantine: a truncated blob falls back to the compile
  path, increments progcache-corrupt-entries, moves the entry aside,
  and never crashes;
* concurrent-writer safety: two writers storing the same key through
  the atomic write-temp-then-rename leave exactly one valid entry.

The pipeline rig runs ONCE per module (module fixture) on a tiny
skewed 6-broker cluster with a 2-goal stack so the compile cost stays
inside the tier-1 smoke budget.
"""
import os
import threading

import conftest  # noqa: F401  (forces the CPU platform before jax loads)
import jax
import jax.numpy as jnp
import pytest

from cruise_control_tpu.analyzer import optimizer as opt_mod
from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.parallel import mesh as mesh_mod
from cruise_control_tpu.parallel import progcache
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)

pytestmark = pytest.mark.progcache

GOALS = ["ReplicaCapacityGoal", "ReplicaDistributionGoal"]


def _proposal_key(result):
    return sorted(
        (p.partition.topic, p.partition.partition,
         tuple((r.broker_id, r.logdir) for r in p.new_replicas))
        for p in result.proposals)


def _make_optimizer():
    return GoalOptimizer(default_goals(max_rounds=8, names=GOALS),
                         pipeline_segment_size=4)


def _simulate_restart():
    """Drop every in-process compiled artifact, keeping only the disk
    cache — the closest a test can get to a process bounce."""
    with opt_mod._SHARED_LOCK:
        opt_mod._SHARED_PROGRAMS.clear()
        opt_mod._SHARED_LRU.clear()
        opt_mod._SHARED_AOT.clear()
    jax.clear_caches()
    progcache.get_cache().reset_counters()


@pytest.fixture()
def cache_tmp(tmp_path):
    """Configure the process-wide cache onto a fresh temp dir; restore
    the disabled default afterwards so no other test sees it."""
    cache = progcache.get_cache()
    prev = (cache.enabled, cache.cache_dir, cache.max_bytes,
            cache.fingerprint_override)
    cache.configure(enabled=True, cache_dir=str(tmp_path))
    cache.reset_counters()
    yield cache
    cache.enabled, cache.cache_dir, cache.max_bytes, \
        cache.fingerprint_override = prev
    cache.reset_counters()


# ---------------------------------------------------------------------------
# key / fingerprint helpers (parallel/mesh.py — the shared keyspace)
# ---------------------------------------------------------------------------

class TestKeyHelpers:
    def test_program_key_mesh_suffix(self):
        assert mesh_mod.program_key("__pre__") == "__pre__"
        assert mesh_mod.program_key("__pre__", 1) == "__pre__"
        assert mesh_mod.program_key("__pre__", 8) == "__pre__@mesh8"

    def test_goal_list_signature(self):
        assert mesh_mod.goal_list_signature(None) is None
        a = mesh_mod.goal_list_signature((("m", "G", (("k", 1),)),))
        b = mesh_mod.goal_list_signature((("m", "G", (("k", 1),)),))
        c = mesh_mod.goal_list_signature((("m", "G", (("k", 2),)),))
        assert a == b and a != c and len(a) == 16

    def test_tree_signature_shapes_and_statics(self):
        x = jnp.ones((4, 2))
        assert (mesh_mod.tree_signature((x, 3))
                == mesh_mod.tree_signature((jnp.zeros((4, 2)), 3)))
        assert (mesh_mod.tree_signature((x, 3))
                != mesh_mod.tree_signature((x, 4)))
        assert (mesh_mod.tree_signature((x,))
                != mesh_mod.tree_signature((jnp.ones((5, 2)),)))

    def test_fingerprint_override_changes_one_term(self):
        base = mesh_mod.program_fingerprint()
        a = mesh_mod.program_fingerprint("vA")
        assert mesh_mod.program_fingerprint("vA") == a
        assert a != base != mesh_mod.program_fingerprint("vB")


# ---------------------------------------------------------------------------
# cache store/load mechanics (trivial exports; no pipeline compiles)
# ---------------------------------------------------------------------------

def _trivial_blob(scale=2.0):
    from jax import export as jexport
    progcache.ensure_export_registrations()
    exported = jexport.export(jax.jit(lambda x: x * scale))(
        jnp.ones((4,), jnp.float32))
    return bytes(exported.serialize())


class TestCacheMechanics:
    def test_roundtrip_and_hit_accounting(self, cache_tmp):
        blob = _trivial_blob()
        path = cache_tmp.store("__t__", "g" * 16, "s" * 16, blob)
        assert path is not None and os.path.exists(path)
        exported = cache_tmp.load_exported("__t__", "g" * 16, "s" * 16)
        assert exported is not None
        out = jax.jit(exported.call)(jnp.full((4,), 3.0))
        assert float(out[0]) == 6.0
        assert cache_tmp.stats()["hits"] == 1
        assert cache_tmp.stats()["stores"] == 1
        [entry] = cache_tmp.entries()
        assert entry.program == "__t__" and entry.hits == 1

    def test_unshareable_goal_list_never_touches_disk(self, cache_tmp):
        assert cache_tmp.store("__t__", None, "s" * 16,
                               b"ignored") is None
        assert cache_tmp.load_exported("__t__", None, "s" * 16) is None
        assert cache_tmp.entries() == []

    def test_disabled_is_inert(self, cache_tmp, tmp_path):
        cache_tmp.configure(enabled=False)
        assert cache_tmp.store("__t__", "g" * 16, "s" * 16,
                               _trivial_blob()) is None
        assert cache_tmp.load_exported("__t__", "g" * 16,
                                       "s" * 16) is None
        assert os.listdir(tmp_path) == []

    def test_corrupt_entry_quarantined(self, cache_tmp, tmp_path):
        path = cache_tmp.store("__t__", "g" * 16, "s" * 16,
                               _trivial_blob())
        with open(path, "wb") as fh:       # truncate to garbage
            fh.write(b"not stablehlo")
        assert cache_tmp.load_exported("__t__", "g" * 16,
                                       "s" * 16) is None
        assert cache_tmp.stats()["corruptEntries"] == 1
        assert not os.path.exists(path)    # moved aside, not served
        qdir = tmp_path / "quarantine"
        assert qdir.is_dir() and len(list(qdir.iterdir())) >= 1
        # second lookup: plain miss, no double-count
        assert cache_tmp.load_exported("__t__", "g" * 16,
                                       "s" * 16) is None
        assert cache_tmp.stats()["corruptEntries"] == 1

    def test_stale_fingerprint_is_a_miss(self, cache_tmp):
        cache_tmp.configure(fingerprint_override="vA")
        cache_tmp.store("__t__", "g" * 16, "s" * 16, _trivial_blob())
        assert cache_tmp.load_exported("__t__", "g" * 16,
                                       "s" * 16) is not None
        # bumped source hash (simulated via the override term) => miss
        cache_tmp.configure(fingerprint_override="vB")
        assert cache_tmp.load_exported("__t__", "g" * 16,
                                       "s" * 16) is None
        assert cache_tmp.entries() == []   # current generation is empty
        assert len(cache_tmp.entries(all_fingerprints=True)) == 1
        # rolling back re-addresses the old generation losslessly
        cache_tmp.configure(fingerprint_override="vA")
        assert cache_tmp.load_exported("__t__", "g" * 16,
                                       "s" * 16) is not None

    def test_concurrent_writers_one_valid_entry(self, cache_tmp):
        blob = _trivial_blob()
        barrier = threading.Barrier(2)
        errors = []

        def writer():
            try:
                barrier.wait(timeout=10)
                cache_tmp.store("__race__", "g" * 16, "s" * 16, blob)
            except Exception as exc:  # noqa: BLE001 - the test fails on
                # ANY writer error
                errors.append(exc)
        threads = [threading.Thread(target=writer) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        assert cache_tmp.stats()["stores"] == 2
        entries = [e for e in cache_tmp.entries()
                   if e.program == "__race__"]
        assert len(entries) == 1           # one key, one file
        assert cache_tmp.load_exported("__race__", "g" * 16,
                                       "s" * 16) is not None

    def test_size_cap_evicts_oldest(self, cache_tmp):
        blob = _trivial_blob()
        for i in range(3):
            path = cache_tmp.store(f"__e{i}__", "g" * 16, "s" * 16,
                                   blob)
            os.utime(path, (i + 1, i + 1))      # deterministic ages
        cache_tmp.configure(max_bytes=2 * len(blob) + 1)
        cache_tmp._enforce_size_cap()
        kept = {e.program for e in cache_tmp.entries()}
        assert "__e0__" not in kept            # oldest went first
        assert cache_tmp.stats()["evictions"] >= 1


# ---------------------------------------------------------------------------
# the pipeline rig: cold store -> restart -> hydrated warm solve
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pipeline_rig(tmp_path_factory):
    cache = progcache.get_cache()
    prev = (cache.enabled, cache.cache_dir, cache.max_bytes,
            cache.fingerprint_override)
    cache_dir = str(tmp_path_factory.mktemp("progcache"))
    # skewed leaders so the distribution goal actually proposes moves
    # (the equality pins must compare real placements)
    state, topo = random_cluster(RandomClusterSpec(
        seed=3, num_brokers=6, num_partitions=40, replication_factor=2,
        num_racks=3, num_topics=4, skew_fraction=0.5))
    options = OptimizationOptions()
    try:
        # baseline: cache DISABLED — the exact pre-cache compile path
        cache.configure(enabled=False)
        baseline = _make_optimizer().optimizations(state, topo, options)
        # the equality pins below must compare real placements, not
        # empty lists — the skewed fixture must produce moves
        assert baseline.proposals, "fixture produced no proposals"

        # cold pass: cache enabled + empty — compiles, stores exports
        _simulate_restart()
        cache.configure(enabled=True, cache_dir=cache_dir)
        cold_opt = _make_optimizer()
        cold_opt.warmup(state, topo, options)
        cold_stats = cache.stats()
        cold = cold_opt.optimizations(state, topo, options)

        # warm pass: fresh process state, hydrate from disk, solve
        _simulate_restart()
        warm_opt = _make_optimizer()
        hydrated = warm_opt.hydrate_from_cache()
        warm = warm_opt.optimizations(state, topo, options)
        warm_stats = cache.stats()
        warm_shared_programs = len(opt_mod._SHARED_PROGRAMS)

        # corrupt pass: truncate one entry, hydrate again, solve — the
        # bad program falls back to the compile path, nothing crashes
        _simulate_restart()
        victim = cache.entries()[0]
        with open(victim.path, "r+b") as fh:
            fh.truncate(16)
        corrupt_opt = _make_optimizer()
        corrupt_hydrated = corrupt_opt.hydrate_from_cache()
        corrupt = corrupt_opt.optimizations(state, topo, options)
        corrupt_stats = cache.stats()
        yield {
            "baseline": _proposal_key(baseline),
            "cold": _proposal_key(cold), "cold_stats": cold_stats,
            "warm": _proposal_key(warm), "warm_stats": warm_stats,
            "hydrated": hydrated,
            "warm_shared_programs": warm_shared_programs,
            "corrupt": _proposal_key(corrupt),
            "corrupt_hydrated": corrupt_hydrated,
            "corrupt_stats": corrupt_stats,
        }
    finally:
        cache.enabled, cache.cache_dir, cache.max_bytes, \
            cache.fingerprint_override = prev
        cache.reset_counters()
        _simulate_restart()


class TestPipelineColdWarm:
    def test_cold_pass_stores_entries(self, pipeline_rig):
        s = pipeline_rig["cold_stats"]
        assert s["stores"] > 0 and s["freshCompiles"] > 0

    def test_enabled_path_byte_identical_to_disabled(self, pipeline_rig):
        assert pipeline_rig["cold"] == pipeline_rig["baseline"]

    def test_warm_solve_byte_identical_and_zero_compiles(
            self, pipeline_rig):
        assert pipeline_rig["hydrated"] > 0
        assert pipeline_rig["warm"] == pipeline_rig["cold"]
        s = pipeline_rig["warm_stats"]
        # THE acceptance pin: a warm cold-start traces/compiles no
        # source program (gateway counter) and never even builds a
        # shared jit wrapper (every dispatch served by hydrated AOTs)
        assert s["freshCompiles"] == 0, s
        assert s["hits"] >= pipeline_rig["hydrated"]
        assert pipeline_rig["warm_shared_programs"] == 0

    def test_corrupt_entry_falls_back_without_crash(self, pipeline_rig):
        s = pipeline_rig["corrupt_stats"]
        assert s["corruptEntries"] >= 1
        assert pipeline_rig["corrupt"] == pipeline_rig["cold"]
        # the surviving entries still hydrated
        assert pipeline_rig["corrupt_hydrated"] >= 1


# ---------------------------------------------------------------------------
# fleet onboarding warms from the cache (registry hook)
# ---------------------------------------------------------------------------

class TestFleetRegisterWarm:
    def _registry(self):
        from cruise_control_tpu.fleet import FleetRegistry
        from cruise_control_tpu.sched.policy import SchedulerPolicy
        from cruise_control_tpu.sched.scheduler import DeviceTimeScheduler
        return FleetRegistry(DeviceTimeScheduler(SchedulerPolicy.default()))

    def test_register_calls_warm_hook(self):
        calls = []

        class _Facade:
            def warm_programs_from_cache(self):
                calls.append(1)
                return 3

            def shutdown(self):
                pass
        fleet = self._registry()
        fleet.register("a", _Facade(), default=True)
        assert calls == [1]
        fleet.shutdown()

    def test_register_tolerates_stub_without_hook(self):
        class _Stub:
            def shutdown(self):
                pass
        fleet = self._registry()
        fleet.register("a", _Stub(), default=True)   # must not raise
        fleet.shutdown()
