"""Fleet serving (cruise_control_tpu/fleet/): multi-cluster tenancy on
one device.

Pins the PR-5 tentpole contract:

* single-tenant byte-identical pin — a facade built WITHOUT a fleet
  binding never touches fleet code (engine-free: bucket padding and the
  router are monkeypatched to explode) and produces proposals identical
  to a fleet tenant serving the same cluster;
* bucket-padding no-leak pin — a tenant's model padded to the fleet
  shape bucket (dead brokers / invalid replicas / empty partitions)
  solves to the same proposals as the unpadded model, and padded rows
  stay dead end to end;
* cross-tenant fold split-back — two tenants' queued solves batch into
  ONE vmapped dispatch and each tenant gets back exactly the result its
  isolated solve produces;
* tenant isolation — persistent faults injected while one tenant solves
  degrade only that tenant's ladder rung; its neighbors stay FUSED, and
  the degraded tenant is excluded from fused folds;
* register/drain/unregister lifecycle, the FLEET endpoint, `?cluster=`
  routing with 404/503, and fleet sensors.
"""
import threading
import time

import conftest  # noqa: F401

import numpy as np
import pytest

from cruise_control_tpu.analyzer.degradation import SolverRung
from cruise_control_tpu.api.server import CruiseControlApp
from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.fleet import (BucketIndex, FleetRegistry,
                                      TenantDrainingError, TenantStatus,
                                      UnknownTenantError, bucket_of,
                                      next_pow2, pad_state_to_bucket)
from cruise_control_tpu.fleet import buckets as buckets_mod
from cruise_control_tpu.fleet.router import FleetRouter
from cruise_control_tpu.monitor.sampling.sampler import (
    SimulatedClusterSampler)
from cruise_control_tpu.sched.policy import SchedulerClass, SchedulerPolicy
from cruise_control_tpu.sched.scheduler import (DeviceTimeScheduler,
                                                SolveJob)
from cruise_control_tpu.testing import fixtures
from cruise_control_tpu.utils import faults

from test_facade import feed_samples

pytestmark = pytest.mark.fleet

#: trimmed stack (same tracing-economics rationale as FACADE_TEST_GOALS)
FLEET_GOALS = ["RackAwareGoal", "DiskCapacityGoal",
               "ReplicaDistributionGoal"]


def proposal_keys(proposals):
    return sorted((p.partition.topic, p.partition.partition,
                   tuple(r.broker_id for r in p.old_replicas),
                   tuple(r.broker_id for r in p.new_replicas))
                  for p in proposals)


# ---------------------------------------------------------------------------
# shape buckets (no device work)
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_next_pow2(self):
        assert next_pow2(1) == 1
        assert next_pow2(3) == 4
        assert next_pow2(8) == 8
        assert next_pow2(9) == 16
        assert next_pow2(3, floor=8) == 8

    def test_bucket_and_padding_follow_dead_row_convention(self):
        state, _topo = fixtures.small_cluster()
        bucket = bucket_of(state, floor=8)
        assert bucket.brokers == 8 and bucket.replicas == 8
        padded = pad_state_to_bucket(state, bucket)
        assert padded.num_brokers == 8
        assert padded.num_replicas == 8
        assert padded.num_partitions == 8
        b0, r0, p0 = (state.num_brokers, state.num_replicas,
                      state.num_partitions)
        # padded brokers: dead, zero capacity; padded replicas: invalid,
        # weightless; padded partitions: zero leader bonus
        assert not np.asarray(padded.broker_alive)[b0:].any()
        assert not np.asarray(padded.broker_capacity)[b0:].any()
        assert not np.asarray(padded.replica_valid)[r0:].any()
        assert not np.asarray(padded.replica_base_load)[r0:].any()
        assert not np.asarray(padded.partition_leader_bonus)[p0:].any()
        # real rows untouched
        assert np.array_equal(np.asarray(padded.replica_broker)[:r0],
                              np.asarray(state.replica_broker))
        # idempotent: a state already at bucket shape passes through
        again = pad_state_to_bucket(padded, bucket)
        assert again.num_replicas == padded.num_replicas

    def test_dummy_disk_axis_never_buckets(self):
        state, _ = fixtures.small_cluster()
        assert state.num_disks == 1
        assert bucket_of(state, floor=8).disks == 1

    def test_bucket_index_meters_new_combos_only(self):
        class _Reg:
            def __init__(self):
                self.marks = []

            def meter(self, name):
                reg = self

                class _M:
                    def mark(self, n=1):
                        reg.marks.append(name)
                return _M()

        reg = _Reg()
        idx = BucketIndex(floor=8, max_tracked=2, metrics=reg)
        state, _ = fixtures.small_cluster()
        idx.observe(state, ("goals-a",))
        idx.observe(state, ("goals-a",))       # same combo: no new mark
        idx.observe(state, ("goals-b",))
        assert reg.marks == ["fleet-bucket-compiles"] * 2
        assert idx.to_json()["totalCombos"] == 2
        # LRU cap: a third distinct combo evicts, total keeps counting
        idx.observe(state, ("goals-c",))
        assert idx.to_json()["trackedCombos"] == 2
        assert idx.to_json()["totalCombos"] == 3


# ---------------------------------------------------------------------------
# registry lifecycle (stub facades; no device work)
# ---------------------------------------------------------------------------

class _StubFacade:
    def __init__(self):
        self.shut = False

    def shutdown(self):
        self.shut = True


class TestRegistryLifecycle:
    def make_registry(self, **kwargs):
        sched = DeviceTimeScheduler(SchedulerPolicy.default())
        return FleetRegistry(sched, **kwargs), sched

    def test_register_drain_unregister(self):
        fleet, sched = self.make_registry()
        a, b = _StubFacade(), _StubFacade()
        fleet.register("a", a, default=True)
        fleet.register("b", b)
        assert fleet.default_id == "a"
        assert fleet.get().facade is a            # default resolution
        assert fleet.get("b").facade is b
        with pytest.raises(UnknownTenantError):
            fleet.get("nope")
        with pytest.raises(ValueError, match="already registered"):
            fleet.register("b", _StubFacade())
        # draining: writes rejected, reads fine, then unregister
        fleet.drain("b")
        with pytest.raises(TenantDrainingError):
            fleet.get("b", for_write=True)
        assert fleet.get("b").status is TenantStatus.DRAINING
        with pytest.raises(ValueError, match="drained before"):
            fleet.unregister("a")
        fleet.unregister("b")
        assert b.shut
        with pytest.raises(UnknownTenantError):
            fleet.get("b")
        sched.stop()

    def test_default_tenant_protected_and_cap_enforced(self):
        fleet, sched = self.make_registry(max_tenants=2)
        fleet.register("a", _StubFacade(), default=True)
        fleet.register("b", _StubFacade())
        with pytest.raises(ValueError, match="default tenant"):
            fleet.drain("a")
        with pytest.raises(ValueError, match="tenant cap"):
            fleet.register("c", _StubFacade())
        sched.stop()

    def test_shutdown_stops_tenants_then_scheduler(self):
        fleet, sched = self.make_registry()
        a = _StubFacade()
        fleet.register("a", a)
        fleet.shutdown()
        assert a.shut
        assert not fleet.tenants()


# ---------------------------------------------------------------------------
# the live rig: a 3-tenant fleet + a fleet-free twin of tenant alpha
# ---------------------------------------------------------------------------

def _build_sim(num_brokers=4, partitions=12, rf=2, nw_out=300.0,
               pool=(0, 1)):
    sim = SimulatedCluster()
    for b in range(num_brokers):
        sim.add_broker(b, rack=f"rack{b % 2}")
    # skewed: everything on two brokers so there is work to do
    assignments = [[pool[i % len(pool)] for i in range(rf)]
                   for _ in range(partitions)]
    sim.create_topic("t0", assignments, size_bytes=1e4)
    for p in range(partitions):
        sim.set_partition_load(TopicPartition("t0", p), leader_cpu=2.0,
                               nw_in=100.0, nw_out=nw_out)
    return sim


def _make_facade(sim, clock, solve_scheduler=None, fleet_binding=None):
    cc = CruiseControl(
        sim, SimulatedClusterSampler(sim),
        time_fn=lambda: clock["now"],
        sleep_fn=lambda s: (sim.advance(s),
                            clock.__setitem__("now", clock["now"] + s)),
        monitor_kwargs=dict(num_windows=3, window_ms=10_000,
                            min_samples_per_window=1,
                            sampling_interval_ms=5_000),
        executor_kwargs=dict(progress_check_interval_s=1.0),
        auto_warmup=False, goal_names=list(FLEET_GOALS),
        warm_start_proposals=False,
        solve_scheduler=solve_scheduler, fleet_binding=fleet_binding)
    cc.start_up(do_sampling=False, start_detection=False)
    feed_samples(cc, clock)
    return cc


@pytest.fixture(scope="module")
def fleet_rig():
    """One shared fleet: alpha (default) + beta (same bucket, different
    load) + gamma (chaos victim), plus a fleet-FREE twin of alpha for
    the byte-identical pin.  Same-bucket tenants share compiled
    programs, so the rig pays roughly one pipeline compile."""
    clock = {"now": 10_000.0}
    sched = DeviceTimeScheduler(SchedulerPolicy.default(),
                                time_fn=lambda: clock["now"])
    fleet = FleetRegistry(sched, bucket_floor=8,
                          time_fn=lambda: clock["now"])
    sched.attach_metrics(fleet.metrics)
    tenants = {}
    # beta: FEWER partitions on DIFFERENT brokers — a genuinely distinct
    # cluster that still pads into alpha's shape bucket (P 10->16 vs
    # 12->16, R 20->32 vs 24->32), so the cross-tenant fold really
    # stacks heterogeneous tenants
    builds = {"alpha": dict(nw_out=300.0),
              "beta": dict(nw_out=150.0, partitions=10, pool=(1, 2)),
              "gamma": dict(nw_out=220.0)}
    for cid, kwargs in builds.items():
        cc = _make_facade(_build_sim(**kwargs), clock,
                          solve_scheduler=sched,
                          fleet_binding=fleet.binding_for(cid))
        fleet.register(cid, cc, default=cid == "alpha")
        tenants[cid] = cc
    plain = _make_facade(_build_sim(nw_out=300.0), clock)
    app = CruiseControlApp(tenants["alpha"], fleet=fleet,
                           async_response_timeout_s=120.0)
    yield dict(clock=clock, sched=sched, fleet=fleet, app=app,
               plain=plain, **tenants)
    plain.shutdown()
    fleet.shutdown()


class TestSingleTenantPin:
    def test_no_fleet_facade_is_fleet_free_and_byte_identical(
            self, fleet_rig, monkeypatch):
        """The pre-fleet path must survive the fleet landing untouched:
        a binding-less facade never calls bucket padding or the router
        (both are rigged to explode), and its proposals equal a fleet
        tenant's over the identical cluster — which simultaneously pins
        that bucket padding leaks nothing into the fleet solve."""
        plain = fleet_rig["plain"]
        assert plain._fleet_binding is None
        assert plain._owns_scheduler

        def boom(*a, **k):
            raise AssertionError("fleet code reached from a "
                                 "single-tenant facade")

        monkeypatch.setattr(buckets_mod, "pad_state_to_bucket", boom)
        monkeypatch.setattr(FleetRouter, "fold_run", boom)
        plain_result = plain.optimizations(ignore_proposal_cache=True)
        monkeypatch.undo()

        fleet_result = fleet_rig["alpha"].optimizations(
            ignore_proposal_cache=True)
        assert proposal_keys(plain_result.proposals) == \
            proposal_keys(fleet_result.proposals)
        assert plain_result.violated_goals_after == \
            fleet_result.violated_goals_after
        assert plain_result.balancedness_score() == \
            pytest.approx(fleet_result.balancedness_score())

    def test_fleet_solve_is_bucket_padded_and_rows_stay_dead(
            self, fleet_rig):
        """The fleet tenant's solve really ran at the bucket shape, and
        the padded rows never attracted replicas or load: proposals name
        only real brokers, and the final placement keeps every padded
        replica row invalid."""
        cc = fleet_rig["alpha"]
        result = cc.optimizations(ignore_proposal_cache=True)
        final = result.final_state
        assert final.num_brokers == 8            # 4 padded to bucket 8
        assert final.num_replicas == 32          # 24 padded up
        assert not np.asarray(final.replica_valid)[24:].any()
        real_brokers = set(range(4))
        for p in result.proposals:
            for r in p.new_replicas:
                assert r.broker_id in real_brokers
        # the (bucket, goal-list) combo was accounted
        assert fleet_rig["fleet"].buckets.total_combos >= 1
        sensors = fleet_rig["fleet"].metrics.to_json()
        assert sensors["fleet-bucket-compiles"]["count"] >= 1


class TestCrossTenantFold:
    def test_queued_tenant_solves_fold_and_split_back(self, fleet_rig):
        """Two tenants' solves queued behind a busy device dispatch as
        ONE vmapped batch; each caller gets exactly what its isolated
        solve produces."""
        sched, fleet = fleet_rig["sched"], fleet_rig["fleet"]
        cc_a, cc_b = fleet_rig["alpha"], fleet_rig["beta"]
        # isolated references (dispatch alone: the inline single path)
        ref_a = cc_a.optimizations(ignore_proposal_cache=True)
        ref_b = cc_b.optimizations(ignore_proposal_cache=True)
        assert proposal_keys(ref_a.proposals) != \
            proposal_keys(ref_b.proposals)       # genuinely distinct

        release, started = threading.Event(), threading.Event()

        def blocker():
            started.set()
            release.wait(60.0)

        threads = [threading.Thread(target=lambda: sched.submit(
            SolveJob(klass=SchedulerClass.ANOMALY_HEAL, run=blocker,
                     label="blocker")))]
        threads[0].start()
        assert started.wait(10.0)

        results = {}

        def solve(cc, key):
            results[key] = cc.optimizations(ignore_proposal_cache=True)

        for cc, key in ((cc_a, "a"), (cc_b, "b")):
            t = threading.Thread(target=solve, args=(cc, key))
            t.start()
            threads.append(t)
        deadline = time.time() + 10.0
        while sched.queue.depth() < 2:
            assert time.time() < deadline, "solves never queued"
            time.sleep(0.01)
        batches_before = fleet.router.total_fold_batches
        release.set()
        for t in threads:
            t.join(timeout=300.0)
            assert not t.is_alive()

        assert fleet.router.total_fold_batches == batches_before + 1
        assert fleet.router.total_folded >= 2
        sensors = fleet.metrics.to_json()
        assert sensors["fleet-folded-solves"]["count"] >= 2
        # split-back correctness: folded == isolated, per tenant
        assert proposal_keys(results["a"].proposals) == \
            proposal_keys(ref_a.proposals)
        assert proposal_keys(results["b"].proposals) == \
            proposal_keys(ref_b.proposals)
        assert results["a"].violated_goals_after == \
            ref_a.violated_goals_after
        assert results["b"].violated_goals_after == \
            ref_b.violated_goals_after
        # folded results carry PER-LANE final states (split back from
        # the batched placement fetch), so a folded solve seeds warm
        # starts exactly like the inline path; each lane's state keeps
        # its own bucket-padded shapes
        for key, cc in (("a", cc_a), ("b", cc_b)):
            final = results[key].final_state
            assert final is not None
            ref = (ref_a if key == "a" else ref_b).final_state
            assert final.num_replicas == ref.num_replicas
            assert final.num_brokers == ref.num_brokers
            assert np.array_equal(np.asarray(final.replica_broker),
                                  np.asarray(ref.replica_broker))
            assert np.array_equal(np.asarray(final.replica_is_leader),
                                  np.asarray(ref.replica_is_leader))


@pytest.mark.chaos
class TestTenantIsolationChaos:
    def test_faults_degrade_only_the_targeted_tenant(self, fleet_rig):
        """Persistent compile+runtime faults while gamma solves walk
        gamma's ladder down; alpha and beta keep solving FUSED — one
        tenant's incident never moves a neighbor's rung — and the
        degraded tenant stops offering itself to fused folds."""
        cc_g, cc_a = fleet_rig["gamma"], fleet_rig["alpha"]
        assert cc_g.solver_ladder.rung is SolverRung.FUSED

        plan = faults.FaultPlan() \
            .fail_always("optimizer.compile") \
            .fail_always("optimizer.execute")
        with faults.injected(plan):
            degraded = cc_g.optimizations(ignore_proposal_cache=True)
        assert degraded is not None              # served from CPU rung
        assert cc_g.solver_ladder.rung is SolverRung.CPU

        # neighbors: untouched ladders, healthy fused solves
        for other in ("alpha", "beta"):
            cc_o = fleet_rig[other]
            assert cc_o.solver_ladder.rung is SolverRung.FUSED
            healthy = cc_o.optimizations(ignore_proposal_cache=True)
            assert cc_o.solver_ladder.rung is SolverRung.FUSED
            assert healthy.proposals is not None

        # the degraded tenant is excluded from fused cross-tenant folds
        _key, payload, _run = cc_g._fleet_fold_spec(
            cc_g.goal_optimizer, True, None, None, None,
            lambda: None, lambda r, e: None)
        assert payload.fused_ok() is False
        _key, payload_a, _run = cc_a._fleet_fold_spec(
            cc_a.goal_optimizer, True, None, None, None,
            lambda: None, lambda r, e: None)
        assert payload_a.fused_ok() is True


class TestFleetRest:
    def test_fleet_endpoint_lists_tenants(self, fleet_rig):
        app = fleet_rig["app"]
        status, _, out = app.handle_request(
            "GET", "/kafkacruisecontrol/fleet", "")
        assert status == 200
        by_id = {c["clusterId"]: c for c in out["clusters"]}
        assert set(by_id) == {"alpha", "beta", "gamma"}
        assert by_id["alpha"]["isDefault"] is True
        assert out["defaultTenant"] == "alpha"
        assert out["buckets"]["totalCombos"] >= 1

    def test_cluster_param_routes_and_404s(self, fleet_rig):
        app = fleet_rig["app"]
        status, _, out = app.handle_request(
            "GET", "/kafkacruisecontrol/state",
            "cluster=beta&substates=monitor")
        assert status == 200
        assert out["MonitorState"]["numValidWindows"] > 0
        status, _, out = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "cluster=nope")
        assert status == 404
        assert "unknown cluster" in out["errorMessage"]
        # omitted cluster = default tenant, unchanged response shape
        status, _, out = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "substates=fleet")
        assert status == 200
        assert out["FleetState"]["defaultTenant"] == "alpha"

    def test_no_fleet_app_404s_cluster_param(self, fleet_rig):
        app = CruiseControlApp(fleet_rig["plain"])
        status, _, out = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "cluster=alpha")
        assert status == 404
        assert "not running a fleet" in out["errorMessage"]
        status, _, out = app.handle_request(
            "GET", "/kafkacruisecontrol/fleet", "")
        assert status == 404

    def test_sensors_are_tenant_tagged(self, fleet_rig):
        sensors = fleet_rig["fleet"].sensors_json()
        assert "fleet-bucket-compiles" in sensors
        assert any(k.startswith("cluster.alpha.") for k in sensors)
        assert any(k.startswith("cluster.beta.") for k in sensors)
        # gamma's degraded rung is visible through its tagged sensor
        assert sensors["cluster.gamma.solver-rung"]["value"] == \
            float(int(SolverRung.CPU))


class TestLifecycleLive:
    def test_drain_rejects_writes_allows_reads_then_unregister(
            self, fleet_rig):
        """Runs LAST: consumes the chaos tenant.  Draining answers 503
        to mutations while reads keep working; unregistering removes the
        tenant (404) and shuts its facade down without touching the
        shared scheduler."""
        app, fleet = fleet_rig["app"], fleet_rig["fleet"]
        fleet.drain("gamma")
        status, _, out = app.handle_request(
            "POST", "/kafkacruisecontrol/rebalance",
            "cluster=gamma&dryrun=true")
        assert status == 503
        assert "draining" in out["errorMessage"]
        status, _, _ = app.handle_request(
            "GET", "/kafkacruisecontrol/state",
            "cluster=gamma&substates=monitor")
        assert status == 200
        fleet.unregister("gamma")
        status, _, _ = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "cluster=gamma")
        assert status == 404
        # the shared scheduler survived the tenant teardown
        assert fleet_rig["sched"]._stop.is_set() is False
        result = fleet_rig["alpha"].optimizations(
            ignore_proposal_cache=True)
        assert result is not None
