"""Dispatch-budget pins (ISSUE 16): goal megaprogram fusion, device-side
convergence early-exit, host-side no-work skip, and the reduced-precision
tolerance gate.

* fusion plans: fused=False reproduces the historical fixed-width
  chunking byte-for-byte (key stability); fused=True groups adjacent
  same-group goals and covers every goal exactly once;
* byte-identity: the fused megaprogram pipeline (with the device-side
  convergence early-exit inside every segment) reproduces the eager
  per-goal reference driver's proposals/instruments at f32, on
  single-chip AND on the forced 8-device virtual mesh;
* dispatch count: a warm fused solve dispatches at most len(plan) + 2
  watched device programs — at least 2x below the eager driver's
  2 + 2G (parallel/health.py dispatch counter);
* host-side skip: with every member goal reporting no work the segment
  dispatch is elided entirely, the result is byte-identical, and the
  elided goals land in OptimizerResult.skipped_goals;
* precision gate: analyzer/precision.proposals_equivalent accepts an
  equivalent bf16 result and REJECTS an injected wrong answer (hard
  violation, balancedness drift, move-set divergence).
"""
from types import SimpleNamespace

import numpy as np

import conftest  # noqa: F401

import jax
import pytest

from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.analyzer.fusion import (GOAL_FUSION_GROUPS,
                                                GROUP_OF, plan_segments)
from cruise_control_tpu.analyzer.goals.registry import (GOAL_CLASSES,
                                                        default_goals)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.analyzer.precision import (cast_state_tables,
                                                   proposals_equivalent,
                                                   table_dtype)
from cruise_control_tpu.parallel import health
from cruise_control_tpu.parallel.mesh import make_mesh
from cruise_control_tpu.testing import fixtures

from test_fused_pipeline import GOAL_SUBSET, _unfused_reference_solve


def _proposal_key(p):
    return (p.partition.topic, p.partition.partition,
            tuple(r.broker_id for r in p.old_replicas),
            tuple(r.broker_id for r in p.new_replicas))


# ---------------------------------------------------------------- plans

def test_unfused_plan_is_historical_chunking():
    names = [f"g{i}" for i in range(15)]
    assert plan_segments(names, 4, False) == [(0, 4), (4, 8), (8, 12),
                                              (12, 15)]
    assert plan_segments(names, 2, False) == [
        (i, min(i + 2, 15)) for i in range(0, 15, 2)]
    assert plan_segments([], 4, False) == []
    assert plan_segments([], 4, True) == []


def test_fused_plan_groups_default_stack():
    from cruise_control_tpu.analyzer.goals.registry import (
        DEFAULT_GOAL_ORDER)
    plan = plan_segments(DEFAULT_GOAL_ORDER, 4, True)
    # capacity sextet -> distribution sextet -> leader trio
    assert plan == [(0, 6), (6, 12), (12, 15)]


def test_fused_plan_covers_every_goal_once():
    names = list(GOAL_SUBSET) + ["NotARegisteredGoal", "AlsoCustom"]
    plan = plan_segments(names, 2, True)
    covered = [i for start, stop in plan for i in range(start, stop)]
    assert covered == list(range(len(names)))
    # ungrouped goals fall back to width-chunking, never fuse into a
    # neighboring group's megaprogram
    for start, stop in plan:
        groups = {GROUP_OF.get(n) for n in names[start:stop]}
        assert len(groups) == 1


def test_fusion_groups_match_registry_both_directions():
    """The in-repo mirror of the tools/analysis drift rule: every
    registered goal belongs to exactly one fusion group and every group
    member is a registered goal."""
    registered = set(GOAL_CLASSES)
    grouped = [n for names in GOAL_FUSION_GROUPS.values() for n in names]
    assert len(grouped) == len(set(grouped)), "goal in two fusion groups"
    assert set(grouped) == registered


# --------------------------------------------- byte-identity (tentpole)

@pytest.mark.slow
def test_fused_megaprograms_match_eager_reference():
    """Fusion + device-side convergence early-exit at f32 reproduces the
    eager per-goal driver bit-for-bit (same plan, same float-refresh
    cadence)."""
    state, topo = fixtures.small_cluster()
    options = OptimizationOptions()
    opt = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                        pipeline_segment_size=2, fused_segments=True)
    assert opt._plan_segments() == [(0, 2), (2, 4), (4, 6)]
    fused = opt.optimizations(state, topo, options, check_sanity=False)
    ref = _unfused_reference_solve(opt, state, topo, options)

    assert fused.violated_broker_counts == ref["counts"]
    assert fused.rounds_by_goal == ref["rounds"]
    assert fused.regressed_goals == ref["regressed"]
    assert sorted(map(_proposal_key, fused.proposals)) == sorted(
        map(_proposal_key, ref["proposals"]))
    assert np.array_equal(
        np.asarray(fused.final_state.replica_broker),
        np.asarray(ref["final_state"].replica_broker))
    # the early-exit instrument: converged-at never exceeds rounds used
    for g, conv in fused.converged_at_by_goal.items():
        assert 0 <= conv <= fused.rounds_by_goal.get(g, 0)


@pytest.mark.slow
def test_fused_mesh8_matches_single_chip():
    """The fused megaprograms ride the 8-device virtual mesh (conftest
    forces it) and agree with the single-chip fused solve."""
    state, topo = fixtures.small_cluster()
    options = OptimizationOptions()
    opt = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                        pipeline_segment_size=2, fused_segments=True)
    single = opt.optimizations(state, topo, options, check_sanity=False)
    mesh = make_mesh(jax.devices()[:8])
    meshed = opt.optimizations(state, topo, options, check_sanity=False,
                               mesh=mesh)
    assert meshed.mesh_devices == 8
    assert sorted(map(_proposal_key, meshed.proposals)) == sorted(
        map(_proposal_key, single.proposals))
    assert meshed.rounds_by_goal == single.rounds_by_goal
    assert meshed.converged_at_by_goal == single.converged_at_by_goal
    assert np.array_equal(
        np.asarray(meshed.final_state.replica_broker),
        np.asarray(single.final_state.replica_broker))


# --------------------------------------------------- dispatch-count pin

@pytest.mark.slow
def test_warm_fused_solve_dispatch_budget():
    """A warm fused solve dispatches <= len(plan) + 2 device programs
    (pre + segments + post) through the watched gateway — >= 2x below
    the eager driver's 2 + 2G.  Counted AFTER warmup: the first-call
    inline-jit fallback bypasses watched_call by design."""
    state, topo = fixtures.small_cluster()
    options = OptimizationOptions()
    opt = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                        pipeline_segment_size=2, fused_segments=True)
    opt.warmup(state, topo, options)
    opt.optimizations(state, topo, options, check_sanity=False)

    plan = opt._plan_segments()
    budget = len(plan) + 2
    before = health.dispatch_count()
    opt.optimizations(state, topo, options, check_sanity=False)
    used = health.dispatch_count() - before
    eager_cost = 2 + 2 * len(GOAL_SUBSET)
    assert 0 < used <= budget, (used, budget)
    assert eager_cost >= 2 * used, (
        f"fused solve used {used} dispatches; eager driver pays "
        f"{eager_cost} — fusion must be >= 2x below")
    by_prog = health.dispatches_by_program()
    for start, stop in plan:
        assert by_prog.get(f"__seg_{start}_{stop}__", 0) >= 1


# ------------------------------------------------------ host-side skip

@pytest.mark.slow
def test_host_side_skip_elides_converged_segments():
    """Re-solving an already-balanced cluster with host_side_skip must
    elide every all-no-work segment dispatch, record the elided goals in
    skipped_goals, and stay byte-identical to the unskipped solve."""
    names = ["ReplicaCapacityGoal", "DiskCapacityGoal",
             "ReplicaDistributionGoal", "DiskUsageDistributionGoal"]
    state, topo = fixtures.small_cluster()
    options = OptimizationOptions()
    base = GoalOptimizer(default_goals(max_rounds=24, names=names),
                         pipeline_segment_size=2, fused_segments=True)
    balanced = base.optimizations(state, topo, options,
                                  check_sanity=False).final_state

    skip = GoalOptimizer(default_goals(max_rounds=24, names=names),
                         pipeline_segment_size=2, fused_segments=True,
                         host_side_skip=True)
    r_skip = skip.optimizations(balanced, topo, options,
                                check_sanity=False)
    r_ref = base.optimizations(balanced, topo, options,
                               check_sanity=False)

    # the capacity segment has provably no work and is elided whole; the
    # distribution segment must STILL dispatch because
    # DiskUsageDistributionGoal honestly reports residual violated
    # brokers on this fixture (it iterates and commits nothing) — the
    # skip only ever elides segments whose every goal proves no_work
    assert r_skip.skipped_goals == ["ReplicaCapacityGoal",
                                    "DiskCapacityGoal"]
    assert r_ref.skipped_goals == []
    assert not r_skip.proposals and not r_ref.proposals
    assert r_skip.rounds_by_goal == r_ref.rounds_by_goal
    assert all(r_skip.rounds_by_goal[g] == 0
               for g in r_skip.skipped_goals)
    assert r_skip.violated_broker_counts == r_ref.violated_broker_counts
    assert np.array_equal(
        np.asarray(r_skip.final_state.replica_broker),
        np.asarray(r_ref.final_state.replica_broker))


@pytest.mark.slow
def test_host_side_skip_noop_when_there_is_work():
    """A dirty cluster must veto the skip: results identical to the
    non-skipping optimizer, nothing in skipped_goals for segments that
    did work."""
    state, topo = fixtures.small_cluster()
    options = OptimizationOptions()
    kwargs = dict(pipeline_segment_size=2, fused_segments=True)
    plain = GoalOptimizer(default_goals(max_rounds=24,
                                        names=GOAL_SUBSET), **kwargs)
    skip = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                         host_side_skip=True, **kwargs)
    a = plain.optimizations(state, topo, options, check_sanity=False)
    b = skip.optimizations(state, topo, options, check_sanity=False)
    assert sorted(map(_proposal_key, a.proposals)) == sorted(
        map(_proposal_key, b.proposals))
    assert a.rounds_by_goal == b.rounds_by_goal
    # the fixture's forced rack move lives in the first segment; that
    # segment must not have been skipped
    assert "RackAwareGoal" not in b.skipped_goals


# ------------------------------------------------------ precision gate

def _fake_result(moves, balancedness, violated=(), hard=()):
    def mk(i, old, new):
        return SimpleNamespace(
            partition=("t", i),  # hashable, like the real partition key
            old_replicas=[SimpleNamespace(broker_id=b) for b in old],
            new_replicas=[SimpleNamespace(broker_id=b) for b in new],
            new_leader=new[0])
    return SimpleNamespace(
        proposals=[mk(i, old, new) for i, (old, new) in enumerate(moves)],
        violated_goals_after=list(violated),
        hard_goal_names=frozenset(hard),
        balancedness_score=lambda b=balancedness: b)


def test_table_dtype_rejects_unknown_precision():
    import jax.numpy as jnp
    assert table_dtype("float32") == jnp.float32
    assert table_dtype("bfloat16") == jnp.bfloat16
    with pytest.raises(ValueError, match="solver.precision"):
        table_dtype("float8")


def test_cast_state_tables_targets_only_float_planes():
    import jax.numpy as jnp
    state, _ = fixtures.small_cluster()
    assert cast_state_tables(state, "float32") is state
    cast = cast_state_tables(state, "bfloat16")
    assert cast.replica_base_load.dtype == jnp.bfloat16
    assert cast.partition_leader_bonus.dtype == jnp.bfloat16
    assert cast.broker_capacity.dtype == jnp.bfloat16
    # integer planes stay exact
    assert cast.replica_broker.dtype == state.replica_broker.dtype
    np.testing.assert_array_equal(np.asarray(cast.replica_broker),
                                  np.asarray(state.replica_broker))


def test_proposals_equivalent_accepts_close_and_rejects_wrong():
    moves = [((0, 1), (2, 1)), ((1, 2), (0, 2)), ((3, 0), (3, 1)),
             ((2, 0), (2, 1)), ((0, 3), (1, 3)), ((1, 0), (2, 0)),
             ((2, 3), (0, 3)), ((3, 2), (1, 2)), ((0, 2), (3, 2)),
             ((1, 3), (0, 1))]
    base = _fake_result(moves, 87.0)

    ok, report = proposals_equivalent(base, _fake_result(moves, 86.8))
    assert ok and report["moveOverlap"] == 1.0

    # one re-ranked near-tie out of ten stays above the 0.90 overlap
    # ... no: Jaccard with 1 differing move of 10 = 9/11 < 0.9 -> the
    # gate is strict by default; loosened explicitly it passes
    nearly = _fake_result(moves[:-1] + [((1, 3), (2, 3))], 86.9)
    ok, report = proposals_equivalent(base, nearly)
    assert not ok and report["moveOverlap"] < 0.9
    ok, _ = proposals_equivalent(base, nearly, min_move_overlap=0.8)
    assert ok

    # injected wrong answers: hard violation / balance drift / plan
    # divergence — each alone must fail the gate
    bad_hard = _fake_result(moves, 87.0, violated=["DiskCapacityGoal"],
                            hard=["DiskCapacityGoal"])
    ok, report = proposals_equivalent(base, bad_hard)
    assert not ok and report["hardViolated"] == ["DiskCapacityGoal"]

    ok, report = proposals_equivalent(base, _fake_result(moves, 80.0))
    assert not ok
    assert abs(report["balancednessBaseline"]
               - report["balancednessCandidate"]) > 0.5

    different = _fake_result([((i, 9), (9, i)) for i in range(10)], 87.0)
    ok, report = proposals_equivalent(base, different)
    assert not ok and report["moveOverlap"] == 0.0

    # two no-op solves are equivalent
    ok, report = proposals_equivalent(_fake_result([], 90.0),
                                      _fake_result([], 90.0))
    assert ok and report["moveOverlap"] == 1.0


@pytest.mark.slow
def test_bfloat16_solve_passes_gate_on_fixture():
    """End-to-end bf16: cast tables, solve the same model, pass the
    proposals-equivalence gate against the f32 result.  (Byte identity
    is NOT claimed — that is exactly what the gate is for.)"""
    state, topo = fixtures.small_cluster()
    options = OptimizationOptions()
    opt = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                        pipeline_segment_size=2, fused_segments=True)
    f32 = opt.optimizations(state, topo, options, check_sanity=False)
    bf16 = opt.optimizations(cast_state_tables(state, "bfloat16"), topo,
                             options, check_sanity=False)
    ok, report = proposals_equivalent(f32, bf16)
    assert ok, report


# -------------------------------------------- converged-at instrument

@pytest.mark.slow
def test_converged_at_round_reported_not_round_budget():
    """A goal that converges early reports the convergence round, not
    the round budget it never used (the r05 table reported 146 for a
    goal done at 3)."""
    state, topo = fixtures.small_cluster()
    opt = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                        pipeline_segment_size=2, fused_segments=True)
    res = opt.optimizations(state, topo, OptimizationOptions(),
                            check_sanity=False)
    assert set(res.converged_at_by_goal) == set(GOAL_SUBSET)
    for g in GOAL_SUBSET:
        conv = res.converged_at_by_goal[g]
        rounds = res.rounds_by_goal[g]
        assert 0 <= conv <= rounds, (g, conv, rounds)
    # at least one goal in the subset converges before its budget on
    # the small fixture — the instrument must be able to say so
    assert any(0 < res.converged_at_by_goal[g] for g in GOAL_SUBSET)
