"""Monitor-plane tests: simulated cluster → sampler → aggregator → model.

Modeled on the reference's LoadMonitorTest.java:1-652 (completeness math,
model building) and KafkaSampleStore round-trip tests, but driven end to
end through the in-process simulated cluster instead of EasyMock.
"""
import numpy as np
import pytest

from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.capacity import (
    BrokerCapacityConfigFileResolver, StaticCapacityResolver)
from cruise_control_tpu.core.aggregator import NotEnoughValidWindowsError
from cruise_control_tpu.model import state as S
from cruise_control_tpu.monitor.completeness import (
    ModelCompletenessRequirements)
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.sampling.holder import (
    BrokerMetricSample, PartitionMetricSample, complete_partition_values)
from cruise_control_tpu.monitor.sampling.sample_store import FileSampleStore
from cruise_control_tpu.monitor.sampling.sampler import (
    SimulatedClusterSampler)


def make_sim_cluster(num_brokers=4, partitions_per_topic=8, rf=2):
    sim = SimulatedCluster()
    for b in range(num_brokers):
        sim.add_broker(b, rack=f"rack{b % 2}")
    assignments = []
    for p in range(partitions_per_topic):
        replicas = [(p + i) % num_brokers for i in range(rf)]
        assignments.append(replicas)
    sim.create_topic("t0", assignments, size_bytes=1000.0)
    for p in range(partitions_per_topic):
        sim.set_partition_load(TopicPartition("t0", p), leader_cpu=2.0,
                               nw_in=100.0, nw_out=300.0)
    return sim


def make_monitor(sim, **kwargs):
    clock = {"now": 10_000.0}  # seconds
    defaults = dict(num_windows=3, window_ms=10_000, min_samples_per_window=1,
                    sampling_interval_ms=5_000,
                    time_fn=lambda: clock["now"])
    defaults.update(kwargs)
    monitor = LoadMonitor(sim, SimulatedClusterSampler(sim),
                          StaticCapacityResolver(), **defaults)
    return monitor, clock


class TestLoadMonitor:
    def test_not_enough_windows_raises(self):
        sim = make_sim_cluster()
        monitor, clock = make_monitor(sim)
        with pytest.raises(NotEnoughValidWindowsError):
            monitor.cluster_model()

    def test_model_from_samples(self):
        sim = make_sim_cluster()
        monitor, clock = make_monitor(sim)
        monitor.start_up(do_sampling=False)
        # fill several windows of samples
        for _ in range(8):
            monitor.task_runner.sample_once()
            clock["now"] += 10.0  # seconds
        state, topo = monitor.cluster_model()
        assert state.num_brokers == 4
        assert state.num_partitions == 8
        assert int(np.asarray(state.replica_valid).sum()) == 16
        load = np.asarray(S.broker_load(state))
        # per-partition leader nw_in is 100; 8 leaders spread over brokers
        assert np.isclose(load[:, Resource.NW_IN].sum(), 8 * 100.0 * 2,
                          rtol=1e-4)  # leader + follower replication inbound
        # NW_OUT only on leaders
        assert np.isclose(load[:, Resource.NW_OUT].sum(), 8 * 300.0,
                          rtol=1e-4)
        monitor.shutdown()

    def test_completeness_requirements(self):
        sim = make_sim_cluster()
        monitor, clock = make_monitor(sim)
        monitor.start_up(do_sampling=False)
        req = ModelCompletenessRequirements(min_required_num_windows=2)
        assert not monitor.meet_completeness_requirements(req)
        for _ in range(6):
            monitor.task_runner.sample_once()
            clock["now"] += 10.0
        assert monitor.meet_completeness_requirements(req)
        state = monitor.get_state()
        assert state.num_total_partitions == 8
        assert state.monitored_partitions_percentage == 1.0
        monitor.shutdown()

    def test_dead_broker_marks_replicas_offline(self):
        sim = make_sim_cluster()
        monitor, clock = make_monitor(sim)
        monitor.start_up(do_sampling=False)
        for _ in range(4):
            monitor.task_runner.sample_once()
            clock["now"] += 10.0
        sim.kill_broker(2)
        state, topo = monitor.cluster_model()
        b_idx = topo.broker_index[2]
        assert not bool(np.asarray(state.broker_alive)[b_idx])
        on_dead = (np.asarray(state.replica_broker) == b_idx) & \
            np.asarray(state.replica_valid)
        assert np.asarray(state.replica_offline)[on_dead].all()
        monitor.shutdown()

    def test_pause_resume(self):
        sim = make_sim_cluster()
        monitor, clock = make_monitor(sim)
        monitor.start_up(do_sampling=False)
        monitor.pause_metric_sampling("test pause")
        assert monitor.task_runner.state.value == "PAUSED"
        assert monitor.get_state().reason_of_pause == "test pause"
        monitor.resume_metric_sampling("test resume")
        assert monitor.task_runner.state.value == "RUNNING"
        monitor.shutdown()

    def test_model_generation_advances(self):
        sim = make_sim_cluster()
        monitor, clock = make_monitor(sim)
        monitor.start_up(do_sampling=False)
        g0 = monitor.model_generation()
        # cross a window boundary so the aggregator generation advances
        monitor.task_runner.sample_once()
        clock["now"] += 20.0
        monitor.task_runner.sample_once()
        g1 = monitor.model_generation()
        assert g0.is_stale(g1)
        assert g1.load_generation > g0.load_generation
        monitor.shutdown()


class TestSampleStore:
    def test_file_store_round_trip(self, tmp_path):
        store = FileSampleStore(str(tmp_path))
        from cruise_control_tpu.monitor.sampling.sampler import Samples
        p = PartitionMetricSample(
            1, TopicPartition("topic-x", 3), 123456.0,
            complete_partition_values({0: 1.5, 3: 42.0}))
        b = BrokerMetricSample(7, 123000.0, {0: 0.5, 5: 2.0})
        store.store_samples(Samples([p], [b]))
        store.close()

        loaded = []

        class L:
            def load_samples(self, samples):
                loaded.append(samples)

        store2 = FileSampleStore(str(tmp_path))
        store2.load_samples(L())
        store2.close()
        (samples,) = loaded
        assert samples.partition_samples[0].tp == TopicPartition("topic-x", 3)
        assert samples.partition_samples[0].values[3] == pytest.approx(42.0)
        assert samples.broker_samples[0].broker_id == 7
        assert samples.broker_samples[0].values[5] == pytest.approx(2.0)

    def test_monitor_reloads_samples(self, tmp_path):
        sim = make_sim_cluster()
        store = FileSampleStore(str(tmp_path))
        monitor, clock = make_monitor(sim, sample_store=store)
        monitor.start_up(do_sampling=False)
        for _ in range(4):
            monitor.task_runner.sample_once()
            clock["now"] += 10.0
        monitor.shutdown()

        # a fresh monitor (fresh aggregators) reloads history from the store
        store2 = FileSampleStore(str(tmp_path))
        monitor2, clock2 = make_monitor(sim, sample_store=store2)
        clock2["now"] = clock["now"]
        monitor2.start_up(do_sampling=False)
        state, _ = monitor2.cluster_model()
        assert int(np.asarray(state.replica_valid).sum()) == 16
        monitor2.shutdown()


class TestCapacityResolver:
    def test_file_resolver_jbod_and_default(self, tmp_path):
        path = tmp_path / "capacity.json"
        path.write_text("""
        {"brokerCapacities": [
          {"brokerId": "-1",
           "capacity": {"DISK": "500000", "CPU": "100",
                        "NW_IN": "50000", "NW_OUT": "50000"}},
          {"brokerId": "0",
           "capacity": {"DISK": {"/data/d0": "250000", "/data/d1": "250000"},
                        "CPU": {"num.cores": "8"},
                        "NW_IN": "200000", "NW_OUT": "200000"}}
        ]}""")
        resolver = BrokerCapacityConfigFileResolver(str(path))
        cap0 = resolver.capacity_for_broker("r", "h", 0)
        assert cap0.resource(Resource.DISK) == pytest.approx(500000)
        assert cap0.disk_capacity_by_logdir["/data/d1"] == pytest.approx(250000)
        assert cap0.resource(Resource.CPU) == pytest.approx(800.0)
        assert cap0.num_cpu_cores == 8
        cap9 = resolver.capacity_for_broker("r", "h", 9)
        assert cap9.is_estimated
        assert cap9.resource(Resource.DISK) == pytest.approx(500000)
        with pytest.raises(KeyError):
            resolver.capacity_for_broker("r", "h", 9, allow_estimation=False)


def test_train_endpoint_path_and_infinite_aggregate():
    """TRAIN fits real coefficients from broker history; aggregate over
    (-inf, inf) must cover the full retained history (regression: the
    window arithmetic crashed on int(-inf))."""
    import numpy as np
    sim = make_sim_cluster()
    monitor, clock = make_monitor(sim)
    monitor.start_up(do_sampling=False)
    for _ in range(8):
        monitor.task_runner.sample_once()
        clock["now"] += 10.0
    res = monitor.broker_aggregator.aggregate(-np.inf, np.inf)
    assert res.entity_values
    monitor.train()
    assert monitor.cpu_model.trained
    coefs = monitor.cpu_model.coefficients
    assert coefs.leader_bytes_in >= 0.0
    # trained model now drives follower CPU attribution in the model build,
    # consistently for BOTH follower loads and the leader base/bonus split
    # (a leadership transfer must leave the demoted leader carrying exactly
    # the trained follower estimate)
    state, topo = monitor.cluster_model()
    assert state.num_brokers == 4
    valid = np.asarray(state.replica_valid)
    base = np.asarray(state.replica_base_load)
    part = np.asarray(state.replica_partition)
    leader = np.asarray(state.replica_is_leader)
    bonus = np.asarray(state.partition_leader_bonus)
    # the trained regression (clamped to the leader's current-role CPU)
    # must drive EVERY replica's base CPU — leader split and follower
    # attribution alike; the untrained static estimator would not satisfy
    # this for a generic trained fit
    leader_cpu = np.zeros(state.num_partitions)
    leader_cpu[part[valid & leader]] = (base[valid & leader, Resource.CPU]
                                        + bonus[part[valid & leader],
                                                Resource.CPU])
    expect = np.clip(coefs.follower_bytes_in * base[valid, Resource.NW_IN],
                     0.0, leader_cpu[part[valid]])
    np.testing.assert_allclose(base[valid, Resource.CPU], expect,
                               rtol=1e-4, atol=1e-5)
    monitor.shutdown()


class TestSamplerFaultSites:
    """Chaos coverage for the `monitor.sampler.*` injection points: the
    analyzer's D320 drift rule requires every fault site armed in the
    package to be scripted by at least one test."""

    @pytest.mark.chaos
    def test_sampler_fetch_fault_yields_partial_round(self):
        from cruise_control_tpu.utils import faults
        sim = make_sim_cluster()
        monitor, clock = make_monitor(sim)
        monitor.start_up(do_sampling=False)
        plan = faults.FaultPlan()
        plan.fail_always("monitor.sampler.fetch")
        with faults.injected(plan) as injector:
            monitor.task_runner.sample_once()   # must not raise
        assert injector.failure_count("monitor.sampler.fetch") >= 1
        # the faulted round fed the aggregators nothing
        with pytest.raises(NotEnoughValidWindowsError):
            monitor.cluster_model()
        # recovery: healthy rounds afterwards still reach a model
        for _ in range(8):
            monitor.task_runner.sample_once()
            clock["now"] += 10.0
        state, _ = monitor.cluster_model()
        assert state.num_brokers == 4
        monitor.shutdown()

    @pytest.mark.chaos
    def test_sampler_store_fault_keeps_aggregation(self, tmp_path):
        from cruise_control_tpu.utils import faults
        sim = make_sim_cluster()
        store = FileSampleStore(str(tmp_path))
        monitor, clock = make_monitor(sim, sample_store=store)
        monitor.start_up(do_sampling=False)
        plan = faults.FaultPlan()
        plan.fail_always("monitor.sampler.store")
        with faults.injected(plan) as injector:
            for _ in range(8):
                monitor.task_runner.sample_once()
                clock["now"] += 10.0
        assert injector.failure_count("monitor.sampler.store") >= 1
        # aggregation survived the store outage: the model still builds
        state, _ = monitor.cluster_model()
        assert state.num_brokers == 4
        monitor.shutdown()

        # ... but nothing was persisted for the next process to reload
        loaded = []

        class L:
            def load_samples(self, samples):
                loaded.append(samples)

        store2 = FileSampleStore(str(tmp_path))
        store2.load_samples(L())
        store2.close()
        assert all(not s.partition_samples and not s.broker_samples
                   for s in loaded)
