"""Tests for the typed config framework (reference semantics:
CORE ConfigDef parsing/validation/defaults and AbstractConfig
getConfiguredInstance)."""
import pytest

from cruise_control_tpu.common.config import (
    AbstractConfig, ConfigDef, ConfigException, Password, Type, in_range,
    in_values, load_properties)


def make_def():
    return (ConfigDef()
            .define("num.windows", Type.INT, 5, in_range(min_value=1))
            .define("balance.threshold", Type.DOUBLE, 1.1,
                    in_range(min_value=1.0))
            .define("goals", Type.LIST, "a,b,c")
            .define("mode", Type.STRING, "auto", in_values("auto", "manual"))
            .define("enabled", Type.BOOLEAN, True)
            .define("secret", Type.PASSWORD, "hunter2")
            .define("required.key", Type.STRING))


def test_defaults_and_parsing():
    cfg = AbstractConfig(make_def(), {"required.key": "x",
                                      "num.windows": "12",
                                      "enabled": "false"})
    assert cfg.get_int("num.windows") == 12
    assert cfg.get_double("balance.threshold") == 1.1
    assert cfg.get_list("goals") == ["a", "b", "c"]
    assert cfg.get_boolean("enabled") is False
    assert cfg.get_string("required.key") == "x"


def test_missing_required_raises():
    with pytest.raises(ConfigException, match="required.key"):
        AbstractConfig(make_def(), {})


def test_validators():
    with pytest.raises(ConfigException, match="num.windows"):
        AbstractConfig(make_def(), {"required.key": "x", "num.windows": 0})
    with pytest.raises(ConfigException, match="mode"):
        AbstractConfig(make_def(), {"required.key": "x", "mode": "bogus"})


def test_bad_type_raises():
    with pytest.raises(ConfigException):
        AbstractConfig(make_def(), {"required.key": "x",
                                    "num.windows": "not-a-number"})


def test_password_hidden():
    cfg = AbstractConfig(make_def(), {"required.key": "x"})
    secret = cfg.get("secret")
    assert isinstance(secret, Password)
    assert "hunter2" not in repr(secret)
    assert secret.value == "hunter2"


def test_configured_instance():
    definition = ConfigDef().define(
        "impl.class", Type.CLASS,
        "cruise_control_tpu.common.config.Password")
    cfg = AbstractConfig(definition, {})
    # Password has no configure(); instantiation fails since it needs an arg —
    # use a class with a no-arg ctor instead
    definition2 = ConfigDef().define(
        "impl.class", Type.CLASS, "cruise_control_tpu.common.config.ConfigDef")
    cfg2 = AbstractConfig(definition2, {})
    instance = cfg2.get_configured_instance("impl.class", ConfigDef)
    assert isinstance(instance, ConfigDef)


def test_properties_file(tmp_path):
    path = tmp_path / "cc.properties"
    path.write_text("# comment\nbootstrap.servers=localhost:9092\n"
                    "num.windows: 7\n\n! other comment\n")
    props = load_properties(str(path))
    assert props == {"bootstrap.servers": "localhost:9092",
                     "num.windows": "7"}


def test_document_renders():
    doc = make_def().document()
    assert "num.windows" in doc and "(required)" in doc


def test_env_reference_resolution(tmp_path, monkeypatch):
    """${env:NAME} secret indirection in properties files (reference
    CC/config/EnvConfigProvider.java)."""
    import pytest
    from cruise_control_tpu.common.config import load_properties
    monkeypatch.setenv("CC_TEST_SECRET", "s3cr3t")
    p = tmp_path / "cc.properties"
    p.write_text("webserver.auth.password=${env:CC_TEST_SECRET}\n"
                 "plain.key=value\n")
    props = load_properties(str(p))
    assert props["webserver.auth.password"] == "s3cr3t"
    assert props["plain.key"] == "value"
    p.write_text("x=${env:CC_TEST_UNSET_VAR}\n")
    with pytest.raises(KeyError):
        load_properties(str(p))


def test_configuration_doc_is_current():
    """docs/CONFIGURATION.md must match the live config definitions
    (defs-as-source-of-truth, like the reference's ResponseTest walking
    @JsonResponseClass against the swagger YAML)."""
    import os
    from cruise_control_tpu.config.docgen import render
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "CONFIGURATION.md")
    with open(path) as f:
        committed = f.read()
    assert committed == render(), (
        "docs/CONFIGURATION.md is stale — regenerate with "
        "`python -m cruise_control_tpu.config.docgen > docs/CONFIGURATION.md`")
