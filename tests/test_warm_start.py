"""Warm-start solves (GoalOptimizer.optimizations(warm_start=...) and the
facade's seed gating).

Reference semantics being extended: GoalOptimizer's generation-keyed
cached-proposal reuse (reference cruise-control/src/main/java/com/linkedin/
kafka/cruisecontrol/analyzer/GoalOptimizer.java:210-217, 275-330) serves
the cache while the generation is unchanged; the warm start additionally
reuses the converged placement as the SEARCH SEED once the generation
moved.  The contract tested here: a warm-started solve's proposals still
diff against the fresh initial state, pass the same hard-goal
verification, and spend no more search rounds than a cold solve.
"""
import numpy as np
import pytest

from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.facade import _warm_start_compatible
from cruise_control_tpu.model import state as S
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)


@pytest.fixture(scope="module")
def cluster():
    return random_cluster(RandomClusterSpec(
        num_brokers=16, num_partitions=400, replication_factor=3,
        num_racks=4, num_topics=8, seed=7, skew_fraction=0.25))


@pytest.fixture(scope="module")
def optimizer():
    return GoalOptimizer(default_goals(max_rounds=96),
                         pipeline_segment_size=4)


def _perturb(state, noise=0.03, seed=3):
    rng = np.random.default_rng(seed)
    jit_r = (1.0 + noise * (2.0 * rng.random(
        (state.num_replicas, 1)) - 1.0)).astype(np.float32)
    jit_p = (1.0 + noise * (2.0 * rng.random(
        (state.num_partitions, 1)) - 1.0)).astype(np.float32)
    return state.replace(
        replica_base_load=state.replica_base_load * jit_r,
        partition_leader_bonus=state.partition_leader_bonus * jit_p)


@pytest.mark.slow
def test_warm_start_valid_and_cheaper(cluster, optimizer):
    state, topo = cluster
    cold = optimizer.optimizations(state, topo)
    perturbed = _perturb(state)

    warm = optimizer.optimizations(perturbed, topo,
                                   warm_start=cold.final_state)
    control = optimizer.optimizations(perturbed, topo)

    # proposals diff against the PERTURBED initial, not the seed: every
    # proposal's old replica set must be the initial state's placement
    part_index = topo.partition_index
    init_broker = np.asarray(perturbed.replica_broker)
    init_part = np.asarray(perturbed.replica_partition)
    valid = np.asarray(perturbed.replica_valid)
    for p in warm.proposals:
        pi = part_index[p.partition]
        rows = np.nonzero(valid & (init_part == pi))[0]
        assert ({pl.broker_id for pl in p.old_replicas}
                == {topo.broker_ids[init_broker[r]] for r in rows})

    # same validity as the cold control: no hard goal violated
    hard = {g.name for g in optimizer.goals if g.is_hard}
    assert not (set(warm.violated_goals_after) & hard)
    # the warm seed starts converged — the search spends fewer rounds
    assert (sum(warm.rounds_by_goal.values())
            <= sum(control.rounds_by_goal.values()))


def test_warm_start_compat_gates(cluster):
    state, _ = cluster
    assert _warm_start_compatible(state, state)
    # dead broker in the new model → cold solve (heal path first)
    dead = S.set_broker_state(state, 3, alive=False)
    assert not _warm_start_compatible(state, dead)
    # different topology → incompatible
    other, _ = random_cluster(RandomClusterSpec(
        num_brokers=16, num_partitions=500, replication_factor=3,
        num_racks=4, num_topics=8, seed=8))
    assert not _warm_start_compatible(other, state)
