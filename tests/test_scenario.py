"""Batched what-if scenario engine (cruise_control_tpu/scenario/).

Pins the PR-3 tentpole contract:

* batch-of-1 equivalence — the vmapped scenario solve reproduces the
  plain fused solve BIT-IDENTICALLY (stats, instruments, proposals) for
  the same model;
* heterogeneous-shape padding — a batch mixing broker counts shares one
  padded shape, and padded (dead, zero-capacity) broker rows never leak
  into any scenario's stats;
* transfer discipline — ≤ 2 device_gets for a WHOLE batch (one
  instrument fetch + one placement fetch), under a disallow transfer
  guard;
* halve-the-batch retry on RESOURCE_EXHAUSTED;
* facade routing — multiple candidate broker sets go through the
  engine (dry-run only) while the K=1 path stays byte-identical to the
  single-solve behavior;
* SCENARIOS REST endpoint: JSON body in, ranked report out, body-hash
  task dedup, result-size notes in USER_TASKS.

Ladder descent for the scenario fault sites lives in tests/test_chaos.py
(TestScenarioLadder).
"""
import json
import time

import conftest  # noqa: F401

import numpy as np
import pytest

import jax

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions)
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.scenario import (BASE_SCENARIO_NAME, BrokerAdd,
                                         ScenarioEngine, ScenarioSpec,
                                         ScenarioSpecError,
                                         candidate_broker_sets,
                                         parse_scenarios_payload)
from cruise_control_tpu.testing import fixtures
from cruise_control_tpu.utils import faults

pytestmark = pytest.mark.scenario

SCENARIO_GOALS = ["RackAwareGoal", "DiskCapacityGoal",
                  "ReplicaDistributionGoal"]


@pytest.fixture(scope="module")
def rig():
    """Shared (state, topo, optimizer, engine): one vmapped-program
    compile serves the whole module."""
    state, topo = fixtures.small_cluster()
    constraint = BalancingConstraint()
    opt = GoalOptimizer(default_goals(max_rounds=16, names=SCENARIO_GOALS),
                        constraint, pipeline_segment_size=2)

    def factory(names):
        return opt if names is None else GoalOptimizer(
            default_goals(max_rounds=16, names=names), constraint)

    engine = ScenarioEngine(factory, constraint)
    return state, topo, opt, engine


# ---------------------------------------------------------------------------
# spec + payload validation
# ---------------------------------------------------------------------------

class TestSpec:
    def test_json_roundtrip(self):
        spec = ScenarioSpec(
            name="s1",
            add_brokers=(BrokerAdd(broker_id=9, rack="B",
                                   capacity={"disk": 123.0}),),
            remove_brokers=(1,), demote_brokers=(2,),
            load_scale={"disk": 1.5},
            capacity_overrides={0: {"cpu": 50.0}},
            goals=("RackAwareGoal",), only_move_to_added=True)
        again = ScenarioSpec.from_json(spec.to_json())
        assert again == spec
        assert not spec.is_noop()
        assert ScenarioSpec(name="base").is_noop()

    def test_validation_rejects_garbage(self):
        with pytest.raises(ScenarioSpecError, match="name"):
            ScenarioSpec(name="").validate()
        with pytest.raises(ScenarioSpecError, match="unknown resource"):
            ScenarioSpec(name="x", load_scale={"ram": 2.0}).validate()
        with pytest.raises(ScenarioSpecError, match="positive"):
            ScenarioSpec(name="x", load_scale={"disk": -1.0}).validate()
        with pytest.raises(ScenarioSpecError, match="added and removed"):
            ScenarioSpec(name="x", add_brokers=(BrokerAdd(1),),
                         remove_brokers=(1,)).validate()
        _, topo = fixtures.small_cluster()
        with pytest.raises(ScenarioSpecError, match="unknown brokers"):
            ScenarioSpec(name="x", remove_brokers=(77,)).validate(topo)

    def test_payload_parser(self):
        specs, goals, include_base = parse_scenarios_payload(json.dumps({
            "scenarios": [{"name": "a"}, {"name": "b",
                                          "loadScale": {"cpu": 2.0}}],
            "goals": ["RackAwareGoal"], "includeBase": False}))
        assert [s.name for s in specs] == ["a", "b"]
        assert goals == ["RackAwareGoal"] and include_base is False
        # absent includeBase -> None: the facade's config default
        # (scenario.include.base.solve) must not be overridden
        _, _, absent = parse_scenarios_payload(
            json.dumps({"scenarios": [{"name": "a"}]}))
        assert absent is None
        with pytest.raises(ScenarioSpecError):
            parse_scenarios_payload(None)
        with pytest.raises(ScenarioSpecError):
            parse_scenarios_payload("{}")
        with pytest.raises(ScenarioSpecError, match="unique"):
            parse_scenarios_payload(json.dumps(
                {"scenarios": [{"name": "a"}, {"name": "a"}]}))

    def test_candidate_broker_sets(self):
        assert candidate_broker_sets([1, 2]) is None
        assert candidate_broker_sets([]) is None
        assert candidate_broker_sets([[2, 1], [3]]) == [[1, 2], [3]]
        with pytest.raises(ScenarioSpecError, match="mix"):
            candidate_broker_sets([1, [2]])


# ---------------------------------------------------------------------------
# batch-of-1 equivalence + padding correctness
# ---------------------------------------------------------------------------

class TestBatchedSolve:
    def test_batch_of_one_bit_identical_to_fused_solve(self, rig):
        """The vmapped scenario solve of the no-op scenario must
        reproduce the plain fused solve EXACTLY: same stats bits, same
        instruments, same proposals."""
        state, topo, opt, engine = rig
        single = opt.optimizations(state, topo, OptimizationOptions(),
                                   check_sanity=False)
        res = engine.evaluate(state, topo,
                              [ScenarioSpec(name=BASE_SCENARIO_NAME)])
        out = res.outcomes[0]
        assert out.feasible and out.rung == "FUSED"
        assert out.violated_goals_before == single.violated_goals_before
        assert out.violated_goals_after == single.violated_goals_after
        assert out.violated_broker_counts == single.violated_broker_counts
        assert out.rounds_by_goal == single.rounds_by_goal
        for field in ("util_avg", "util_std", "util_max",
                      "replica_count_std", "leader_count_std"):
            assert np.array_equal(
                np.asarray(getattr(single.stats_after, field)),
                np.asarray(getattr(out.stats_after, field))), field

        def key(p):
            return (p.partition.topic, p.partition.partition,
                    tuple(r.broker_id for r in p.old_replicas),
                    tuple(r.broker_id for r in p.new_replicas))
        assert sorted(map(key, single.proposals)) == \
            sorted(map(key, out.proposals))
        assert out.num_replica_moves == single.num_replica_movements

    def test_heterogeneous_padding_does_not_leak(self, rig):
        """A batch mixing broker counts (hypothetical addition + base)
        pads everyone to the widest shape; the base scenario's stats
        must be identical to its unbatched, unpadded solve — padded
        rows are dead and weightless."""
        state, topo, opt, engine = rig
        single = opt.optimizations(state, topo, OptimizationOptions(),
                                   check_sanity=False)
        res = engine.evaluate(state, topo, [
            ScenarioSpec(name=BASE_SCENARIO_NAME),
            ScenarioSpec(name="add",
                         add_brokers=(BrokerAdd(broker_id=42, rack="B"),)),
        ])
        base = res.outcome(BASE_SCENARIO_NAME)
        added = res.outcome("add")
        # base solved at the PADDED width yet sees only its 3 brokers
        assert int(np.asarray(base.stats_after.num_alive_brokers)) == 3
        assert np.array_equal(np.asarray(base.stats_after.util_std),
                              np.asarray(single.stats_after.util_std))
        assert base.violated_broker_counts == \
            single.violated_broker_counts
        # the addition scenario sees 4 alive brokers
        assert int(np.asarray(added.stats_after.num_alive_brokers)) == 4
        # one device batch served both shapes
        assert res.batch_sizes == [2]

    def test_goal_override_opens_own_subbatch(self, rig):
        state, topo, opt, engine = rig
        res = engine.evaluate(state, topo, [
            ScenarioSpec(name="default-goals"),
            ScenarioSpec(name="rack-only", goals=("RackAwareGoal",)),
        ])
        assert sorted(res.batch_sizes) == [1, 1]   # two programs
        assert set(res.outcome("rack-only").violated_broker_counts) == \
            {"RackAwareGoal"}
        assert set(res.outcome("default-goals").violated_broker_counts) \
            == set(SCENARIO_GOALS)

    def test_transfer_guard_two_device_gets_per_batch(self, rig,
                                                      monkeypatch):
        """≤ 2 device_gets for the WHOLE batch — the instrument fetch
        and the placement fetch — under a disallow transfer guard."""
        state, topo, opt, engine = rig
        specs = [ScenarioSpec(name=BASE_SCENARIO_NAME),
                 ScenarioSpec(name="g1", load_scale={"disk": 1.2}),
                 ScenarioSpec(name="g2", load_scale={"nw_in": 1.3}),
                 ScenarioSpec(name="g3", demote_brokers=(1,))]
        calls = []
        real_device_get = jax.device_get

        def counting(x):
            calls.append(1)
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting)
        with jax.transfer_guard_device_to_host("disallow"):
            res = engine.evaluate(state, topo, specs)
        assert len(calls) <= 2, (
            f"expected instrument fetch + placement fetch, saw "
            f"{len(calls)} device_gets for the batch")
        assert all(o.feasible for o in res.outcomes)
        assert res.batch_sizes == [4]

    def test_oom_halving_retry(self, rig):
        """A scripted RESOURCE_EXHAUSTED on the first batched dispatch
        halves the batch and solves both halves; the ladder does NOT
        descend (OOM is a sizing problem, not a solver fault)."""
        from cruise_control_tpu.analyzer.degradation import SolverRung
        state, topo, opt, engine = rig
        specs = [ScenarioSpec(name=f"g{i}",
                              load_scale={"disk": 1.0 + 0.1 * i})
                 for i in range(4)]
        plan = faults.FaultPlan().fail_nth(
            "scenario.execute", 1,
            exc_factory=lambda site: RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating scenario "
                "batch"))
        with faults.injected(plan):
            res = engine.evaluate(state, topo, specs)
        assert res.oom_halvings == 1
        assert sorted(res.batch_sizes) == [2, 2]
        assert all(o.feasible and o.rung == "FUSED"
                   for o in res.outcomes)
        assert engine.ladder.rung is SolverRung.FUSED

    def test_oom_at_batch_of_one_descends(self, rig):
        """Un-halvable OOM (K=1) exhausts the halving path and descends
        the ladder instead of failing the request."""
        from cruise_control_tpu.analyzer.degradation import SolverRung
        state, topo, opt, engine = rig
        plan = faults.FaultPlan().fail_always(
            "scenario.execute",
            exc_factory=lambda site: RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory"))
        try:
            with faults.injected(plan):
                res = engine.evaluate(
                    state, topo, [ScenarioSpec(name="solo")])
            assert res.outcomes[0].feasible
            assert res.outcomes[0].rung == "EAGER"
            assert engine.ladder.rung is SolverRung.EAGER
        finally:
            # heal the module-shared engine for later tests
            engine.ladder.on_success(SolverRung.EAGER)
            res = engine.evaluate(state, topo,
                                  [ScenarioSpec(name="heal")])
            assert engine.ladder.rung is SolverRung.FUSED


# ---------------------------------------------------------------------------
# ranking report
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ranked_run(rig):
    """One shared K=4 evaluation (same shapes as the transfer-guard
    batch, so the programs are compiled once per module) feeding the
    infeasibility-verdict, ranking, and schema tests."""
    state, topo, opt, engine = rig
    return engine.evaluate(state, topo, [
        ScenarioSpec(name=BASE_SCENARIO_NAME),
        ScenarioSpec(name="ok", load_scale={"disk": 1.1}),
        ScenarioSpec(name="ok2", load_scale={"nw_in": 1.2}),
        ScenarioSpec(name="doomed", remove_brokers=(2,)),
    ])


class TestReport:
    def test_doomed_scenario_reports_infeasible_not_raises(self,
                                                           ranked_run):
        """Removing the only rack-B broker makes RackAwareGoal
        unsatisfiable: the batched path must report THAT scenario
        infeasible (clean verdict, no exception) while its batchmates
        solve normally."""
        assert ranked_run.outcome(BASE_SCENARIO_NAME).feasible
        bad = ranked_run.outcome("doomed")
        assert not bad.feasible
        assert "RackAwareGoal" in bad.reason
        assert bad.proposals == []
        assert ranked_run.outcome("ok").feasible

    def test_ranking_and_vs_base(self, ranked_run):
        from cruise_control_tpu.scenario.report import batch_report, rank
        ranked = rank(ranked_run.outcomes)
        assert ranked[-1].spec.name == "doomed"   # infeasible ranks last
        report = batch_report(ranked_run, verbose=True)
        names = [s["name"] for s in report["scenarios"]]
        assert BASE_SCENARIO_NAME not in names
        assert names[-1] == "doomed"
        assert report["base"]["name"] == BASE_SCENARIO_NAME
        assert report["dryRun"] is True
        ok = next(s for s in report["scenarios"] if s["name"] == "ok")
        assert "vsBase" in ok and "balancednessDelta" in ok["vsBase"]
        assert "proposals" in ok   # verbose
        doomed = next(s for s in report["scenarios"]
                      if s["name"] == "doomed")
        assert doomed["feasible"] is False and doomed["reason"]

    def test_report_conforms_to_schema(self, ranked_run):
        jsonschema = pytest.importorskip("jsonschema")
        from cruise_control_tpu.api.schema import ENDPOINT_SCHEMAS
        from cruise_control_tpu.scenario.report import batch_report
        jsonschema.validate(batch_report(ranked_run),
                            ENDPOINT_SCHEMAS["SCENARIOS"])


# ---------------------------------------------------------------------------
# facade routing: candidate broker sets + K=1 pin
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def facade_rig():
    """ONE facade stack + app shared by the routing and endpoint tests:
    every class building its own stack re-traces the whole vmapped
    pipeline (~1 min per stack on the 1-core CI host); sharing the
    engine lets same-shape batches reuse compiled programs."""
    from test_facade import feed_samples, make_stack
    from cruise_control_tpu.api.server import CruiseControlApp
    sim, cc, clock = make_stack(num_brokers=4, skewed=True)
    cc.start_up(do_sampling=False, start_detection=False)
    feed_samples(cc, clock)
    app = CruiseControlApp(cc, async_response_timeout_s=30.0)
    yield sim, cc, clock, app
    cc.shutdown()


class TestFacadeRouting:
    @pytest.fixture()
    def stack(self, facade_rig):
        sim, cc, clock, _app = facade_rig
        return sim, cc, clock

    def test_k1_path_is_byte_identical_and_engine_free(self, stack,
                                                       monkeypatch):
        """A flat broker list (and a single candidate set) must take
        TODAY'S single-solve path — the scenario engine is never
        consulted — and produce identical results either way."""
        sim, cc, clock = stack

        def boom(*a, **k):
            raise AssertionError("scenario engine used for K=1 request")

        monkeypatch.setattr(cc.scenario_engine, "evaluate", boom)
        flat = cc.remove_brokers([3], dryrun=True)
        nested = cc.remove_brokers([[3]], dryrun=True)
        assert flat.scenario_report is None
        assert nested.scenario_report is None

        def key(p):
            return (p.partition.topic, p.partition.partition,
                    tuple(r.broker_id for r in p.old_replicas),
                    tuple(r.broker_id for r in p.new_replicas))
        assert sorted(map(key, flat.proposals)) == \
            sorted(map(key, nested.proposals))
        assert np.array_equal(
            np.asarray(flat.optimizer_result.final_state.replica_broker),
            np.asarray(
                nested.optimizer_result.final_state.replica_broker))

    def test_multi_candidate_routes_through_engine(self, stack):
        sim, cc, clock = stack
        op = cc.remove_brokers([[0], [3]], dryrun=True)
        assert op.dryrun and op.execution_uuid is None
        assert op.scenario_report is not None
        names = {s["name"] for s in op.scenario_report["scenarios"]}
        assert names == {"remove-0", "remove-3"}
        assert op.scenario_report["base"] is not None
        # best candidate's proposals came back
        assert op.proposals

    def test_multi_candidate_refuses_execution(self, stack):
        sim, cc, clock = stack
        with pytest.raises(ValueError, match="dry-run only"):
            cc.remove_brokers([[0], [3]], dryrun=False)

    @pytest.mark.slow
    def test_demote_candidates_use_leadership_goal(self, stack):
        """slow: compiles the PreferredLeaderElectionGoal pipeline on
        top of the shared stack's programs."""
        sim, cc, clock = stack
        op = cc.demote_brokers([[0], [1]], dryrun=True)
        assert op.scenario_report is not None
        for s in op.scenario_report["scenarios"]:
            assert s["name"] in ("demote-0", "demote-1")
        # demotion what-ifs must not move replicas, only leadership
        for p in op.proposals:
            assert not p.replicas_to_add

    def test_state_and_sensors_expose_engine(self, stack):
        sim, cc, clock = stack
        st = cc.state()
        eng = st["ScenarioEngineState"]
        assert eng["enabled"] is True
        assert eng["totalScenarios"] >= 2
        sensors = cc.metrics.to_json()
        assert "scenario-batch-size" in sensors
        assert sensors["scenario-rung"]["value"] == 0


# ---------------------------------------------------------------------------
# REST endpoint + user-task body dedup
# ---------------------------------------------------------------------------

class TestScenariosEndpoint:
    @pytest.fixture()
    def app_rig(self, facade_rig):
        sim, cc, _clock, app = facade_rig
        return sim, cc, app

    def _post_body(self, app, body, query="", headers=None,
                   deadline_s=300.0):
        from cruise_control_tpu.api.user_tasks import USER_TASK_ID_HEADER
        headers = dict(headers or {})
        end = time.time() + deadline_s
        while True:
            status, hdrs, out = app.handle_request(
                "POST", "/kafkacruisecontrol/scenarios", query, headers,
                body=body)
            if status != 202:
                return status, hdrs, out
            headers = {USER_TASK_ID_HEADER: hdrs[USER_TASK_ID_HEADER]}
            assert time.time() < end, "scenario task never completed"
            time.sleep(0.2)

    def test_post_roundtrip(self, app_rig):
        sim, cc, app = app_rig
        body = json.dumps({"scenarios": [
            {"name": "grow", "loadScale": {"disk": 1.3}},
            {"name": "demote-1", "demoteBrokers": [1]},
        ]})
        status, _, out = self._post_body(app, body, "verbose=true")
        assert status == 200, out
        assert out["dryRun"] is True
        assert {s["name"] for s in out["scenarios"]} == \
            {"grow", "demote-1"}
        assert out["base"]["name"] == BASE_SCENARIO_NAME
        assert out["batch"]["numScenarios"] == 3

    def test_bad_body_is_400(self, app_rig):
        sim, cc, app = app_rig
        status, _, out = app.handle_request(
            "POST", "/kafkacruisecontrol/scenarios", "", {},
            body="this is not json")
        assert status == 400 and "JSON" in out["errorMessage"]
        status, _, out = app.handle_request(
            "POST", "/kafkacruisecontrol/scenarios", "", {}, body=None)
        assert status == 400
        status, _, out = app.handle_request(
            "POST", "/kafkacruisecontrol/scenarios", "", {},
            body=json.dumps({"scenarios": [{"name": "x",
                                            "bogusField": 1}]}))
        assert status == 400 and "bogusField" in out["errorMessage"]

    def test_disabled_engine_rejected_at_request_time(self, app_rig,
                                                      monkeypatch):
        sim, cc, app = app_rig
        monkeypatch.setattr(cc, "_scenario_enabled", False)
        status, _, out = app.handle_request(
            "POST", "/kafkacruisecontrol/scenarios", "", {},
            body=json.dumps({"scenarios": [{"name": "x"}]}))
        assert status == 400 and "disabled" in out["errorMessage"]

    def test_brokerid_candidate_sets_via_rest(self, app_rig):
        sim, cc, app = app_rig
        from test_api import TestDispatch
        status, _, out = TestDispatch._poll(
            app, "POST", "/kafkacruisecontrol/remove_broker",
            "brokerid=0;3&dryrun=true")
        assert status == 200, out
        assert out["dryRun"] is True
        assert "scenarioReport" in out
        assert {s["name"] for s in out["scenarioReport"]["scenarios"]} \
            == {"remove-0", "remove-3"}

    def test_two_step_approval_binds_the_body(self, facade_rig):
        """With two-step verification on, an approved SCENARIOS review
        is bound to the reviewed BODY: replaying the approval with a
        different payload must be rejected."""
        from cruise_control_tpu.api.server import CruiseControlApp
        sim, cc, _clock, _app = facade_rig
        app2 = CruiseControlApp(cc, two_step_verification=True,
                                async_response_timeout_s=30.0)
        body = json.dumps({"scenarios": [
            {"name": "r1", "loadScale": {"disk": 1.1}},
            {"name": "r2", "loadScale": {"nw_in": 1.1}}]})
        status, _, out = app2.handle_request(
            "POST", "/kafkacruisecontrol/scenarios", "", {}, body=body)
        assert status == 202 and "reviewResult" in out
        rid = out["reviewResult"]["Id"]
        app2.handle_request("POST", "/kafkacruisecontrol/review",
                            f"approve={rid}")
        # a DIFFERENT body behind the approved review id: rejected
        status, _, out = app2.handle_request(
            "POST", "/kafkacruisecontrol/scenarios",
            f"review_id={rid}", {},
            body=json.dumps({"scenarios": [{"name": "evil"}]}))
        assert status == 400
        # the reviewed body goes through
        status, hdrs, out = app2.handle_request(
            "POST", "/kafkacruisecontrol/scenarios",
            f"review_id={rid}", {}, body=body)
        from cruise_control_tpu.api.user_tasks import USER_TASK_ID_HEADER
        headers = {USER_TASK_ID_HEADER: hdrs[USER_TASK_ID_HEADER]}
        end = time.time() + 300.0
        while status == 202:
            assert time.time() < end
            time.sleep(0.2)
            status, hdrs, out = app2.handle_request(
                "POST", "/kafkacruisecontrol/scenarios",
                f"review_id={rid}", headers, body=body)
        assert status == 200, out
        assert {s["name"] for s in out["scenarios"]} == {"r1", "r2"}

    def test_user_task_dedup_includes_body_hash(self):
        """Two ACTIVE tasks with identical endpoint+query but different
        bodies must not coalesce; identical bodies must."""
        from cruise_control_tpu.api.user_tasks import UserTaskManager
        utm = UserTaskManager()

        def slow_op():
            time.sleep(0.5)
            return {"ok": True}

        a = utm.get_or_create("SCENARIOS", "verbose=true", "c", slow_op,
                              body='{"scenarios":[{"name":"a"}]}')
        b = utm.get_or_create("SCENARIOS", "verbose=true", "c", slow_op,
                              body='{"scenarios":[{"name":"b"}]}')
        a2 = utm.get_or_create("SCENARIOS", "verbose=true", "c", slow_op,
                               body='{"scenarios":[{"name":"a"}]}')
        assert a.task_id != b.task_id
        assert a2.task_id == a.task_id
        # a reused task id with a DIFFERENT body must not attach
        with pytest.raises(ValueError, match="different request body"):
            utm.get_or_create("SCENARIOS", "verbose=true", "c", slow_op,
                              task_id=a.task_id,
                              body='{"scenarios":[{"name":"z"}]}')
        # body-less re-poll attaches fine (header-only long-poll)
        same = utm.get_or_create("SCENARIOS", "verbose=true", "c2",
                                 slow_op, task_id=a.task_id)
        assert same.task_id == a.task_id
        a.future.result(timeout=5.0)
        b.future.result(timeout=5.0)
        utm.shutdown()

    def test_user_task_reports_result_size(self):
        from cruise_control_tpu.api.user_tasks import (TaskStatus,
                                                       UserTaskManager)
        utm = UserTaskManager()
        info = utm.get_or_create("SCENARIOS", "", "c",
                                 lambda: {"big": "x" * 1000},
                                 body='{"scenarios":[{"name":"s"}]}')
        info.future.result(timeout=5.0)
        for _ in range(50):
            if info.status is not TaskStatus.ACTIVE:
                break
            time.sleep(0.05)
        out = info.to_json()
        assert out["Status"] == "Completed"
        assert out["ResultSizeBytes"] > 1000
        assert out["RequestBodySha"]
        utm.shutdown()
