"""Multichip production-solve tests (`multichip` marker).

PR 6 promotes the mesh from a dryrun artifact (test_parallel.py jits the
goal chain directly) to a first-class runtime resource: the PRODUCTION
solve path — GoalOptimizer.optimizations, the facade's degradation
ladder, the device-time scheduler's mesh token — dispatches over all
visible devices.  These tests run it on the virtual 8-device CPU rig
(conftest forces XLA host-platform devices, the same rig the multichip
dryrun used), so tier CI exercises the mesh path without TPUs:

* mesh=1 vs mesh=8 PROPOSAL EQUALITY at small scale, optimizer-level
  (with replica padding actually engaged) and facade-level (the
  acceptance pin: with >1 device the production path dispatches over
  the mesh AND returns the single-chip proposals);
* scheduler mesh-token semantics under a FORCED mesh>1 runtime:
  K=1 scheduled-vs-inline byte-identical, heal-preempts-sweep ordering;
* the ladder's MESH→FUSED rung: a mesh-path runtime failure descends to
  the single-chip fused solve without tripping the breaker past FUSED,
  and the next healthy solve probes back up to MESH.

The DEFAULT test runtime stays single-chip (mesh.enabled=auto treats
multiple CPU devices as the test rig, not a mesh), so every existing
byte-identical pin runs unchanged; tests here force `mesh_enabled=True`.
"""
import threading
import time as _real_time

import conftest  # noqa: F401

import jax
import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.analyzer.degradation import BreakerState, SolverRung
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.parallel.mesh import MeshToken, make_mesh, runtime_mesh
from cruise_control_tpu.sched.policy import SchedulerClass
from cruise_control_tpu.sched.runtime import segment_checkpoint
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)
from cruise_control_tpu.utils import faults

from test_facade import feed_samples, make_stack

pytestmark = [
    pytest.mark.multichip,
    pytest.mark.skipif(len(jax.devices()) < 8,
                       reason="needs the 8-device CPU mesh"),
]

MESH_TEST_GOALS = ["RackAwareGoal", "DiskCapacityGoal",
                   "DiskUsageDistributionGoal"]


def proposal_key(p):
    return (p.partition.topic, p.partition.partition,
            tuple(r.broker_id for r in p.old_replicas),
            tuple(r.broker_id for r in p.new_replicas))


def test_runtime_mesh_token_resolution():
    """auto on the CPU rig = degenerate single-chip token; forced =
    all 8 devices; max_devices clips; 1 remaining device degenerates."""
    assert runtime_mesh(enabled=None).size == 1          # auto on CPU rig
    assert runtime_mesh(enabled=False).size == 1
    forced = runtime_mesh(enabled=True)
    assert forced.size == 8 and forced.is_multichip
    assert forced.to_json()["axis"] == "replica"
    assert runtime_mesh(enabled=True, max_devices=4).size == 4
    assert runtime_mesh(enabled=True, max_devices=1).size == 1
    assert not MeshToken(None).is_multichip


def test_optimizer_mesh1_vs_mesh8_proposal_equality():
    """The PRODUCTION pipeline (optimizations(): pre program, fused
    segments, post sweep, diff) over the 8-device mesh returns the exact
    single-chip proposals — with a replica count that does NOT divide
    the mesh, so the dead-row padding path is engaged too."""
    # 97 partitions x rf3 = 291 replicas -> pads to 296 on 8 devices
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=12, num_partitions=97, replication_factor=3,
        num_racks=4, num_topics=4, seed=3, skew_fraction=0.3))
    assert state.num_replicas % 8 != 0

    opt1 = GoalOptimizer(default_goals(max_rounds=8,
                                       names=MESH_TEST_GOALS))
    r1 = opt1.optimizations(state, topo, OptimizationOptions())
    assert r1.mesh_devices == 1

    mesh = make_mesh(jax.devices()[:8])
    opt8 = GoalOptimizer(default_goals(max_rounds=8,
                                       names=MESH_TEST_GOALS))
    r8 = opt8.optimizations(state, topo, OptimizationOptions(),
                            mesh=mesh)
    assert r8.mesh_devices == 8
    assert sorted(map(proposal_key, r1.proposals)) == \
        sorted(map(proposal_key, r8.proposals))
    # final state un-padded back to the raw replica count (warm-start
    # seeds must transplant row-for-row onto the next raw model)
    assert r8.final_state.num_replicas == state.num_replicas
    np.testing.assert_array_equal(
        np.asarray(r1.final_state.replica_broker),
        np.asarray(r8.final_state.replica_broker))
    np.testing.assert_array_equal(
        np.asarray(r1.final_state.replica_is_leader),
        np.asarray(r8.final_state.replica_is_leader))


def test_facade_forced_mesh_dispatches_over_mesh_same_proposals():
    """The ACCEPTANCE pin: with >1 device visible and the mesh forced
    on, the production solve path (facade -> scheduler -> ladder ->
    optimizer) dispatches over the mesh — result.mesh_devices spans all
    8 devices, the ladder rests at MESH, the scheduler reports the mesh
    token — and the proposals equal the default single-chip stack's."""
    sim1, cc1, clock1 = make_stack()
    sim8, cc8, clock8 = make_stack(mesh_enabled=True)
    try:
        for cc, clock in ((cc1, clock1), (cc8, clock8)):
            cc.start_up(do_sampling=False, start_detection=False)
            feed_samples(cc, clock)
        assert cc1._mesh_token.size == 1        # auto: CPU rig stays 1
        assert cc8._mesh_token.size == 8
        assert cc8._solver_top_rung is SolverRung.MESH
        r1 = cc1.optimizations()
        r8 = cc8.optimizations()
        assert r1.mesh_devices == 1
        assert r8.mesh_devices == 8             # sharded execution
        assert cc8.solver_ladder.rung is SolverRung.MESH
        assert cc8.solve_scheduler.to_json()["mesh"]["devices"] == 8
        assert cc8.state(("analyzer",))["AnalyzerState"][
            "solverDegradation"]["meshDevices"] == 8
        assert sorted(map(proposal_key, r1.proposals)) == \
            sorted(map(proposal_key, r8.proposals))
        np.testing.assert_array_equal(
            np.asarray(r1.final_state.replica_broker),
            np.asarray(r8.final_state.replica_broker))
    finally:
        cc1.shutdown()
        cc8.shutdown()


def test_k1_scheduled_vs_inline_byte_identical_under_mesh():
    """The K=1 scheduled-vs-inline pin re-run under a FORCED mesh>1
    runtime: the dispatch thread's mesh token and the inline path's
    facade token must produce byte-identical results."""
    sim1, cc1, clock1 = make_stack(mesh_enabled=True)
    sim2, cc2, clock2 = make_stack(mesh_enabled=True)
    cc2.solve_scheduler.enabled = False
    try:
        for cc, clock in ((cc1, clock1), (cc2, clock2)):
            cc.start_up(do_sampling=False, start_detection=False)
            feed_samples(cc, clock)
        r1 = cc1.optimizations()
        r2 = cc2.optimizations()
        assert r1.mesh_devices == r2.mesh_devices == 8
        assert sorted(map(proposal_key, r1.proposals)) == \
            sorted(map(proposal_key, r2.proposals))
        np.testing.assert_array_equal(
            np.asarray(r1.final_state.replica_broker),
            np.asarray(r2.final_state.replica_broker))
        np.testing.assert_array_equal(
            np.asarray(r1.final_state.replica_is_leader),
            np.asarray(r2.final_state.replica_is_leader))
    finally:
        cc1.shutdown()
        cc2.shutdown()


def test_mesh_ladder_descends_to_fused_without_breaker_trip():
    """Under the MANUAL OVERRIDE (mesh.recovery.enabled=false — the
    pre-PR-12 behavior, kept as the operator runbook's escape hatch): a
    collective/runtime failure on the mesh path descends MESH → FUSED
    (single-chip fused solve serves the request) WITHOUT tripping the
    breaker past FUSED; once the mesh heals, the next solve probes one
    rung up and service returns to MESH.  With recovery ENABLED the
    mesh supervisor absorbs the failure via the span ladder instead —
    pinned in tests/test_meshhealth.py."""
    sim, cc, clock = make_stack(mesh_enabled=True,
                                mesh_recovery_enabled=False)
    try:
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        cc._sleep = lambda s: None          # skip retry backoff sleeps

        plan = faults.FaultPlan()
        plan.fail_always("optimizer.mesh")  # fires ONLY on the mesh path
        faults.install(plan)
        try:
            r = cc.optimizations(ignore_proposal_cache=True)
        finally:
            faults.uninstall()
        assert r.mesh_devices == 1                       # served FUSED
        assert cc.solver_ladder.rung is SolverRung.FUSED
        # descent did not cascade: the breaker is not open and nothing
        # descended past FUSED (EAGER/CPU untouched)
        assert cc.solver_breaker.state is BreakerState.CLOSED
        assert cc.solver_ladder.entry_rung() is SolverRung.MESH  # probe
        r2 = cc.optimizations(ignore_proposal_cache=True)
        assert r2.mesh_devices == 8                      # recovered
        assert cc.solver_ladder.rung is SolverRung.MESH
        assert cc.solver_breaker.consecutive_failures == 0
    finally:
        cc.shutdown()


@pytest.mark.slow
def test_heal_preempts_sweep_under_mesh():
    """Heal-preempts-sweep ordering re-run under a forced mesh>1
    runtime: an ANOMALY_HEAL submitted while a SCENARIO_SWEEP holds the
    mesh begins executing before the preempted sweep resumes, and both
    classes run under the SAME mesh token (whole mesh each)."""
    from cruise_control_tpu.scenario.spec import ScenarioSpec
    from cruise_control_tpu.sched import runtime as sched_runtime
    sim, cc, clock = make_stack(mesh_enabled=True)
    try:
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        order = []
        order_lock = threading.Lock()
        heal_queued = threading.Event()
        tokens = {}

        def note(tag):
            with order_lock:
                order.append(tag)

        orig_eval = cc.scenario_engine.evaluate

        def hooked_eval(*a, **k):
            tokens["sweep"] = sched_runtime.current_mesh_token()
            note("sweep-solve")
            assert heal_queued.wait(60.0)
            segment_checkpoint()            # yields to the queued heal
            note("sweep-complete")
            return orig_eval(*a, **k)

        cc.scenario_engine.evaluate = hooked_eval
        orig_opt = cc.goal_optimizer.optimizations

        def hooked_opt(*a, **k):
            tokens["heal"] = sched_runtime.current_mesh_token()
            note("heal-solve")
            return orig_opt(*a, **k)

        cc.goal_optimizer.optimizations = hooked_opt

        sweep_out = {}

        def sweep():
            sweep_out["res"] = cc.evaluate_scenarios(
                [ScenarioSpec(name="grow",
                              load_scale={"disk": 1.2})])

        sweep_thread = threading.Thread(target=sweep, daemon=True)
        sweep_thread.start()
        deadline = _real_time.monotonic() + 30.0
        while not order and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        assert order == ["sweep-solve"]     # the sweep holds the mesh

        heal_out = {}

        def heal():
            heal_out["res"] = cc.rebalance(
                dryrun=True, reason="self-healing: goal violation",
                _scheduler_class=SchedulerClass.ANOMALY_HEAL)

        heal_thread = threading.Thread(target=heal, daemon=True)
        heal_thread.start()
        deadline = _real_time.monotonic() + 30.0
        while cc.solve_scheduler.queue.depth() < 1 \
                and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        heal_queued.set()
        heal_thread.join(timeout=300.0)
        sweep_thread.join(timeout=300.0)
        assert heal_out["res"].proposals is not None
        assert all(o.feasible for o in sweep_out["res"].outcomes)
        # the preempted sweep yielded; the heal ran FIRST; the sweep
        # then re-ran to completion
        assert order == ["sweep-solve", "heal-solve", "sweep-solve",
                         "sweep-complete"]
        assert cc.solve_scheduler.stats.preemptions >= 1
        # both classes ran under the scheduler's ONE mesh token
        assert tokens["heal"] is cc.solve_scheduler.mesh_token
        assert tokens["sweep"] is cc.solve_scheduler.mesh_token
        assert tokens["heal"].size == 8
    finally:
        cc.shutdown()


@pytest.mark.slow
def test_full_default_stack_mesh_solve_matches_quality():
    """The FULL default goal stack through the PRODUCTION pipeline over
    the 8-device mesh (the promoted multichip dryrun): must execute end
    to end, span all 8 devices, and land within the single-chip solve's
    per-goal violated counts (exact equality is not required at the
    full stack: sharded float reductions reorder sums)."""
    from cruise_control_tpu.analyzer.goals.registry import \
        DEFAULT_GOAL_ORDER
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=12, num_partitions=96, replication_factor=3,
        num_racks=4, num_topics=4, seed=3, skew_fraction=0.3))
    goals1 = default_goals(max_rounds=4, names=DEFAULT_GOAL_ORDER)
    opt1 = GoalOptimizer(goals1, pipeline_segment_size=2)
    r1 = opt1.optimizations(state, topo, OptimizationOptions())

    mesh = make_mesh(jax.devices()[:8])
    opt8 = GoalOptimizer(default_goals(max_rounds=4,
                                       names=DEFAULT_GOAL_ORDER),
                         pipeline_segment_size=2)
    r8 = opt8.optimizations(state, topo, OptimizationOptions(),
                            mesh=mesh)
    assert r8.mesh_devices == 8
    for g in DEFAULT_GOAL_ORDER:
        _, _, after1 = r1.violated_broker_counts[g]
        _, _, after8 = r8.violated_broker_counts[g]
        assert after8 <= after1 + 2, (g, after1, after8)
    # no goal's own pass worsened its own statistic on either path
    for r in (r1, r8):
        for g, (_, own, _a) in r.violated_broker_counts.items():
            assert own <= r.entry_broker_counts[g], (g, r.mesh_devices)
