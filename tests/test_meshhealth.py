"""Elastic mesh recovery (`multichip` + `chaos` markers).

PR 12: the serving loop must ride through chip loss and wedged
collectives without a process bounce (parallel/health.py).  On the
virtual 8-device CPU rig these tests pin:

* the WATCHDOG: a scripted hang at the watched-dispatch fault site
  wedges the worker thread, the dispatch thread gets
  DispatchWedgedError within mesh.watchdog.ms, the executable is
  quarantined and the worker replaced;
* the SPAN LADDER: a wedge or collective failure shrinks
  MESH8→MESH4→MESH2→FUSED; a condemned chip is excluded from the
  rebuilt token (and therefore from scenario lanes); probe recovery
  climbs back one rung per probe cycle when the chip returns;
* the ACCEPTANCE pin: with a collective hang injected, the scheduler
  dispatch thread is released, the job re-queues (PR-4 machinery), the
  solve completes on the shrunk span with proposals byte-equal a clean
  mesh-4 twin, and a MESH_DEGRADATION anomaly is emitted;
* the PROGCACHE pin (slow): a span shrink with `@meshN` entries on
  disk is hydrate-only — zero source compiles.
"""
import threading
import time as _real_time

import conftest  # noqa: F401

import jax
import pytest

from cruise_control_tpu.core.anomaly import AnomalyType
from cruise_control_tpu.detector.notifier import (AnomalyNotifier,
                                                  NotificationAction)
from cruise_control_tpu.parallel import health
from cruise_control_tpu.parallel.mesh import MeshToken, make_mesh
from cruise_control_tpu.sched.scheduler import DeviceTimeScheduler
from cruise_control_tpu.utils import faults

from test_facade import feed_samples, make_stack

pytestmark = [
    pytest.mark.multichip,
    pytest.mark.chaos,
    pytest.mark.skipif(len(jax.devices()) < 8,
                       reason="needs the 8-device CPU mesh"),
]

MESH_GOALS = ["RackAwareGoal", "DiskCapacityGoal"]


class RecordingNotifier(AnomalyNotifier):
    def __init__(self):
        self.anomalies = []

    def on_anomaly(self, anomaly):
        self.anomalies.append(anomaly)
        return NotificationAction.ignore()

    def self_healing_enabled(self):
        return {}


def proposal_key(p):
    return (p.partition.topic, p.partition.partition,
            tuple(r.broker_id for r in p.old_replicas),
            tuple(r.broker_id for r in p.new_replicas))


def forced_token(n=8):
    return MeshToken(make_mesh(jax.devices()[:n]))


def mesh_anomalies(cc, notifier):
    cc.anomaly_detector.process_all()
    return [a for a in notifier.anomalies
            if a.anomaly_type is AnomalyType.MESH_DEGRADATION]


# ---------------------------------------------------------------------------
# units: span ladder + watchdog + supervisor
# ---------------------------------------------------------------------------

def test_span_ladder():
    assert health.span_ladder(8) == [8, 4, 2, 1]
    assert health.span_ladder(8, min_devices=4) == [8, 4, 1]
    assert health.span_ladder(8, min_devices=3) == [8, 4, 1]
    assert health.span_ladder(1) == [1]
    assert health.span_ladder(5) == [5, 2, 1]


def test_faults_hang_site():
    release = threading.Event()
    plan = faults.FaultPlan().hang_nth("unit.hang", 1, release)
    done = []
    with faults.injected(plan) as injector:
        t = threading.Thread(
            target=lambda: (faults.inject("unit.hang"), done.append(1)),
            daemon=True)
        t.start()
        t.join(0.3)
        assert t.is_alive() and not done      # wedged on the event
        assert injector.hang_count("unit.hang") == 1
        release.set()
        t.join(2.0)
        assert done                           # released
        faults.inject("unit.hang")            # 2nd call: no hang


def test_watchdog_releases_wedged_dispatch():
    release = threading.Event()
    fires0 = health.watchdog_fires()
    plan = faults.FaultPlan().hang_nth("mesh.dispatch", 1, release)
    try:
        with health.watchdog_armed(250), faults.injected(plan):
            t0 = _real_time.monotonic()
            with pytest.raises(health.DispatchWedgedError):
                health.watched_call(lambda: 1, program="__pre__@mesh8")
            waited = _real_time.monotonic() - t0
            # released within the deadline (generous slack for CI)
            assert waited < 2.0
            assert health.watchdog_fires() == fires0 + 1
            assert health.is_quarantined("__pre__@mesh8")
            # a quarantined program is refused BEFORE dispatch
            with pytest.raises(health.DispatchWedgedError):
                health.watched_call(lambda: 1, program="__pre__@mesh8")
            # the replacement worker serves other programs immediately
            assert health.watched_call(lambda: 41 + 1,
                                       program="__post__") == 42
    finally:
        release.set()
        health.clear_quarantine()


def test_watchdog_disarmed_is_plain_call():
    health.configure_watchdog(enabled=False, deadline_ms=0.0)
    assert health.watched_call(lambda: "ok") == "ok"


def test_supervisor_wedge_shrink_and_gated_recovery():
    clock = {"now": 1000.0}
    sup = health.MeshSupervisor(
        forced_token(8), watchdog_ms=500.0, probe_interval_ms=10_000.0,
        time_fn=lambda: clock["now"])
    assert sup.span == 8 and sup.current_token().size == 8
    summary = sup.handle_wedge("__pre__@mesh8")
    assert summary["fromSpan"] == 8 and summary["toSpan"] == 4
    assert sup.span == 4 and sup.current_token().size == 4
    assert sup.shrinks == 1
    # recovery is probe-interval gated: same instant -> no climb
    assert not sup.maybe_recover()
    clock["now"] += 11.0
    assert sup.maybe_recover()
    assert sup.span == 8 and sup.recoveries == 1
    # healthy at full span: nothing to do
    assert not sup.maybe_recover()


def test_supervisor_condemns_probed_dead_chip():
    clock = {"now": 1000.0}
    sup = health.MeshSupervisor(
        forced_token(8), watchdog_ms=500.0, probe_interval_ms=0.0,
        time_fn=lambda: clock["now"])
    dead = jax.devices()[5].id
    plan = faults.FaultPlan().fail_always(f"mesh.probe.dev{dead}")
    with faults.injected(plan):
        summary = sup.handle_collective_failure()
        assert summary["condemned"] == [dead]
        assert sup.span == 4 and sup.probe_failures == 1
        token = sup.current_token()
        assert dead not in [d.id for d in token.mesh.devices.flat]
        # chip still dead: probes re-run but the span cannot climb
        clock["now"] += 1.0
        assert not sup.maybe_recover()
        assert sup.condemned == [dead]
    # chip returns: one probe cycle climbs one rung back to full span
    clock["now"] += 1.0
    assert sup.maybe_recover()
    assert sup.span == 8 and sup.condemned == []


def test_supervisor_transient_failure_keeps_span():
    """A collective FAILURE whose probe sweep condemns nothing is
    transient (or not mesh material): the supervisor declines, keeping
    the full span — the classic ladder's retry-with-backoff handles it
    instead of degrading capacity for nothing."""
    sup = health.MeshSupervisor(forced_token(8), probe_interval_ms=0.0,
                                time_fn=lambda: 1000.0)
    assert sup.handle_collective_failure() is None
    assert sup.span == 8 and sup.shrinks == 0


def test_supervisor_span_always_matches_a_ladder_width():
    """Mass condemnation during a RECOVERY probe must step the span
    down to a ladder width the survivors can fill — never a token
    narrower than the reported span (a width-3 mesh has no @mesh3
    programs anywhere)."""
    clock = {"now": 1000.0}
    sup = health.MeshSupervisor(
        forced_token(8), probe_interval_ms=0.0,
        time_fn=lambda: clock["now"])
    sup.handle_wedge("__pre__@mesh8")
    assert sup.span == 4
    dead = [d.id for d in jax.devices()[:5]]
    plan = faults.FaultPlan()
    for i in dead:
        plan.fail_always(f"mesh.probe.dev{i}")
    with faults.injected(plan):
        clock["now"] += 1.0
        assert not sup.maybe_recover()       # 3 survivors: no climb
    # ...but the span/token pair stayed consistent: 4 -> 2 (the
    # largest ladder width three healthy chips can fill)
    assert sup.span == 2
    assert sup.current_token().size == 2
    assert sorted(sup.condemned) == sorted(dead)


def test_supervisor_disabled_is_manual_override():
    sup = health.MeshSupervisor(forced_token(8), enabled=False)
    assert sup.handle_wedge("x") is None
    assert sup.handle_collective_failure() is None
    assert not sup.maybe_recover()
    assert sup.span == 8


def test_shared_scheduler_supervisor_governs_fleet_tenants():
    """The fleet half of the condemned-device exclusion pin: ONE
    supervisor wraps the SHARED scheduler's token (main.build_fleet),
    every dispatch — and therefore every cross-tenant fold — resolves
    through it, and a tenant facade handed the shared scheduler adopts
    the same supervisor instead of building its own."""
    dead = jax.devices()[3].id
    sup = health.MeshSupervisor(forced_token(8), probe_interval_ms=1e12)
    sched = DeviceTimeScheduler(enabled=True, mesh_token=forced_token(8),
                                mesh_supervisor=sup)
    try:
        with faults.injected(
                faults.FaultPlan().fail_always(f"mesh.probe.dev{dead}")):
            assert sup.handle_collective_failure() is not None
        live = sched._current_mesh_token()
        assert live.size == 4
        assert dead not in [d.id for d in live.mesh.devices.flat]
        assert sched.to_json()["meshSupervisor"]["condemnedDevices"] \
            == [dead]
        sim, cc, clock = make_stack(solve_scheduler=sched)
        try:
            assert cc.mesh_supervisor is sup
        finally:
            cc.shutdown()
    finally:
        sched.stop()


def test_scheduler_quiesce_idle_and_busy():
    sched = DeviceTimeScheduler(enabled=True)
    assert sched.quiesce(1.0)
    from cruise_control_tpu.sched.scheduler import SolveJob
    from cruise_control_tpu.sched.policy import SchedulerClass
    release = threading.Event()
    t = threading.Thread(
        target=lambda: sched.submit(SolveJob(
            klass=SchedulerClass.USER_INTERACTIVE,
            run=lambda: release.wait(10.0))),
        daemon=True)
    t.start()
    deadline = _real_time.monotonic() + 5.0
    while sched.quiesce(0.0) and _real_time.monotonic() < deadline:
        _real_time.sleep(0.01)       # wait for the job to be picked up
    assert not sched.quiesce(0.2)    # busy: bounded wait returns False
    release.set()
    assert sched.quiesce(5.0)        # drains back to idle
    sched.stop()


# ---------------------------------------------------------------------------
# integration: the acceptance pin
# ---------------------------------------------------------------------------

def test_collective_hang_recovers_on_shrunk_span():
    """THE chaos pin: a collective hang wedges the first mesh-8
    dispatch; the watchdog releases the dispatch thread within
    mesh.watchdog.ms, the job re-queues, and the solve completes on
    the shrunk 4-chip span WITHOUT a process restart — proposals
    byte-equal a clean mesh-4 twin, MESH_DEGRADATION anomaly emitted,
    flight-recorder dump taken."""
    notifier = RecordingNotifier()
    sim, cc, clock = make_stack(
        goal_names=MESH_GOALS, notifier=notifier,
        mesh_enabled=True, auto_warmup=True,
        mesh_watchdog_ms=1500.0, mesh_probe_interval_ms=1e12)
    sim4, cc4, clock4 = make_stack(goal_names=MESH_GOALS,
                                   mesh_enabled=True, mesh_max_devices=4)
    release = threading.Event()
    fires0 = health.watchdog_fires()
    try:
        feed_samples(cc, clock)
        feed_samples(cc4, clock4)
        plan = faults.FaultPlan().hang_nth("mesh.dispatch", 1, release)
        with faults.injected(plan) as injector:
            result = cc.optimizations()
        assert injector.hang_count("mesh.dispatch") == 1
        # the dispatch thread was released by the watchdog, not by the
        # hang clearing (the wedged worker is still blocked right now)
        assert not release.is_set()
        assert health.watchdog_fires() == fires0 + 1
        assert health.last_fire_wait_s() < 1.5 * 3
        # span shrank 8 -> 4 and the job re-queued through the PR-4
        # machinery (aging intact) instead of failing
        sup = cc.mesh_supervisor
        assert sup is not None and sup.span == 4 and sup.shrinks == 1
        assert result.mesh_devices == 4
        requeues = cc.metrics.meter("sched-mesh-requeues").to_json()
        assert requeues["count"] == 1
        # byte-equal a clean mesh-4 twin
        twin = cc4.optimizations()
        assert twin.mesh_devices == 4
        assert sorted(map(proposal_key, result.proposals)) == \
            sorted(map(proposal_key, twin.proposals))
        # the incident self-reported: MESH_DEGRADATION through the
        # notifier plane, wedge evidence attached
        found = mesh_anomalies(cc, notifier)
        assert found and found[0].watchdog_fired
        assert found[0].from_span == 8 and found[0].to_span == 4
        # the solver breaker did NOT open: a chip problem is mesh
        # material, not solver material
        assert cc.solver_breaker.consecutive_failures == 0
        # probe recovery: chips are healthy (the hang was transient) —
        # one probe cycle climbs back toward the full span
        sup.probe_interval_ms = 0.0
        clock["now"] += 60.0
        again = cc.optimizations(ignore_proposal_cache=True)
        assert sup.span == 8
        assert again.mesh_devices == 8
        assert sorted(map(proposal_key, again.proposals)) == \
            sorted(map(proposal_key, twin.proposals))
    finally:
        release.set()
        health.clear_quarantine()
        cc.shutdown()
        cc4.shutdown()


def test_chip_loss_condemns_and_excludes_device():
    """Chip loss: a mesh-rung collective FAILURE triggers a probe
    sweep; the dead chip is condemned, the token is rebuilt over
    survivors (scenario lanes and folds shard over the shrunk span),
    and recovery waits until the chip actually answers probes again."""
    notifier = RecordingNotifier()
    sim, cc, clock = make_stack(goal_names=MESH_GOALS, notifier=notifier,
                                mesh_enabled=True,
                                mesh_probe_interval_ms=1e12)
    dead = jax.devices()[5].id
    try:
        feed_samples(cc, clock)
        plan = (faults.FaultPlan()
                .fail_always(f"mesh.probe.dev{dead}")
                .fail_nth("optimizer.mesh", 1))
        with faults.injected(plan):
            result = cc.optimizations()
            sup = cc.mesh_supervisor
            assert sup.span == 4 and sup.condemned == [dead]
            assert result.mesh_devices == 4
            token = sup.current_token()
            assert dead not in [d.id for d in token.mesh.devices.flat]
            found = mesh_anomalies(cc, notifier)
            assert found and not found[0].watchdog_fired
            assert found[0].condemned_devices == [dead]
            # scenario lanes re-shard over the survivor span: a sweep
            # against the shrunk token completes and never touches the
            # condemned chip
            from cruise_control_tpu.scenario.spec import ScenarioSpec
            batch = cc.evaluate_scenarios(
                [ScenarioSpec(name="whatif", load_scale={"disk": 1.2})])
            assert all(o.feasible is not None for o in batch.outcomes)
            assert sup.condemned == [dead]
        # the chip returns: probe recovery climbs back and clears the
        # condemnation
        sup.probe_interval_ms = 0.0
        clock["now"] += 60.0
        again = cc.optimizations(ignore_proposal_cache=True)
        assert sup.span == 8 and sup.condemned == []
        assert again.mesh_devices == 8
    finally:
        health.clear_quarantine()
        cc.shutdown()


@pytest.mark.slow
def test_shrink_hydrates_from_progcache_zero_source_compiles(tmp_path):
    """The coldstart-style pin for span shrink: with `@mesh8` AND
    `@mesh4` entries in the persistent program cache, a wedge-driven
    shrink is HYDRATE-ONLY — the whole wedge→shrink→re-solve cycle
    performs zero source compiles."""
    from cruise_control_tpu.analyzer import optimizer as opt_mod
    from cruise_control_tpu.parallel import progcache

    cache_kw = dict(progcache_enabled=True, progcache_dir=str(tmp_path),
                    goal_names=MESH_GOALS, mesh_enabled=True,
                    auto_warmup=True)
    # populate: one process-life at mesh8, one at mesh4
    for extra in (dict(), dict(mesh_max_devices=4)):
        sim, cc, clock = make_stack(**cache_kw, **extra)
        feed_samples(cc, clock)
        cc.optimizations()
        cc.shutdown()
    # simulated restart: drop every in-memory executable
    with opt_mod._SHARED_LOCK:
        opt_mod._SHARED_PROGRAMS.clear()
        opt_mod._SHARED_LRU.clear()
        opt_mod._SHARED_AOT.clear()
    jax.clear_caches()
    pc = progcache.get_cache()
    pc.reset_counters()

    sim, cc, clock = make_stack(**cache_kw, mesh_watchdog_ms=1500.0,
                                mesh_probe_interval_ms=1e12)
    release = threading.Event()
    try:
        feed_samples(cc, clock)
        plan = faults.FaultPlan().hang_nth("mesh.dispatch", 1, release)
        with faults.injected(plan):
            result = cc.optimizations()
        assert cc.mesh_supervisor.span == 4
        assert result.mesh_devices == 4
        # hydrate-only: warmup AND the post-shrink mesh-4 programs all
        # came from disk — zero source compiles in this whole process
        assert pc.fresh_compiles == 0, pc.stats()
        assert pc.hits > 0
    finally:
        release.set()
        health.clear_quarantine()
        cc.shutdown()


def test_progcache_flush_sweeps_nested_orphans(tmp_path):
    """The drain path's cache flush must find temp files where
    _atomic_write actually leaves them — inside the nested
    <fingerprint>/<goal_sig>/ entry directories, not the cache root."""
    from cruise_control_tpu.parallel import progcache
    pc = progcache.get_cache()
    prev_enabled, prev_dir = pc.enabled, pc.cache_dir
    nested = tmp_path / "fp0" / "gs0"
    nested.mkdir(parents=True)
    (nested / ".tmp-dead~").write_bytes(b"orphan")
    (nested / "entry.stablehlo").write_bytes(b"keep")
    try:
        pc.configure(enabled=True, cache_dir=str(tmp_path))
        assert pc.flush() == 1
        assert not (nested / ".tmp-dead~").exists()
        assert (nested / "entry.stablehlo").exists()
    finally:
        pc.configure(enabled=prev_enabled, cache_dir=prev_dir or "")


# ---------------------------------------------------------------------------
# lint rule
# ---------------------------------------------------------------------------

def test_watchdog_gateway_lint_rule(tmp_path):
    """G106 via the whole-program analyzer (tools/analysis/ — the
    ISSUE-15 successor of the flat lint; single-file parse set = the
    old per-file semantics)."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    try:
        from analysis import cli
    finally:
        sys.path.pop(0)

    def findings(case, relpath, source):
        path = tmp_path / case / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
        return [f.render() for f in cli.analyze([path], tmp_path / case)
                if "watchdog-gateway" in f.message]

    bad = ("def _run(self, key, fn, *args):\n"
           "    aot = self._aot.get(key)\n"
           "    return aot(*args)\n")
    good = ("def _run(self, key, fn, *args):\n"
            "    aot = self._aot.get(key)\n"
            "    return health.watched_call(lambda: aot(*args),\n"
            "                               program=key)\n")
    exec_file = "cruise_control_tpu/analyzer/optimizer.py"
    assert findings("bad", exec_file, bad)
    assert not findings("good", exec_file, good)
    # outside the exec files the rule does not apply
    assert not findings("other", "cruise_control_tpu/facade.py", bad)
