"""Python client + CLI tests against a live in-process REST server.

Models the reference's python-client tests (cruise-control-client/tests):
endpoint wrappers, async long-polling, error surfacing, and the cccli
argument surface.
"""
import json

import conftest  # noqa: F401
import pytest

from cruise_control_tpu.client.cli import build_parser, main as cli_main
from cruise_control_tpu.client.client import (CruiseControlClient,
                                              CruiseControlClientError)

from test_facade import feed_samples, make_stack
from cruise_control_tpu.api.server import CruiseControlApp


@pytest.fixture(scope="module")
def live_server():
    sim, cc, clock = make_stack(num_brokers=4, skewed=True)
    cc.start_up(do_sampling=False, start_detection=False)
    feed_samples(cc, clock)
    app = CruiseControlApp(cc, async_response_timeout_s=5.0)
    port = app.start(port=0)
    yield sim, cc, f"http://127.0.0.1:{port}/kafkacruisecontrol"
    app.stop()
    cc.shutdown()


class TestClient:
    def test_state_and_load(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url)
        st = client.state()
        assert st["MonitorState"]["numValidWindows"] > 0
        load = client.load()
        assert len(load["brokers"]) == 4

    def test_proposals_long_poll(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url, poll_interval_s=0.5,
                                     timeout_s=600.0)
        out = client.proposals(verbose=True)
        assert out["summary"]["numProposals"] > 0
        assert "proposals" in out

    def test_dryrun_rebalance(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url, poll_interval_s=0.5,
                                     timeout_s=600.0)
        out = client.rebalance(dryrun=True)
        assert out["dryRun"] is True

    def test_error_surfacing(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url)
        with pytest.raises(CruiseControlClientError) as err:
            client.remove_broker([])     # missing brokerid
        assert err.value.status == 400
        with pytest.raises(ValueError):
            client.request("STATE", {"bogus": 1})

    def test_user_tasks_listed(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url)
        client.state()
        tasks = client.user_tasks()
        assert "userTasks" in tasks


class TestCli:
    def test_parser_covers_endpoints(self):
        parser = build_parser()
        for argv in (["state"], ["load"], ["proposals", "--verbose"],
                     ["rebalance", "--execute"],
                     ["add_broker", "1,2"], ["remove_broker", "3"],
                     ["demote_broker", "0"],
                     ["topic_configuration", "t", "3"],
                     ["stop_execution", "--force"],
                     ["admin", "--enable-self-healing-for",
                      "broker_failure"],
                     ["review", "--approve", "1,2"]):
            args = parser.parse_args(argv)
            assert args.command == argv[0]

    def test_cli_end_to_end(self, live_server, capsys):
        _, _, url = live_server
        rc = cli_main(["-a", url, "state"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert "MonitorState" in out

    def test_cli_error_exit_code(self, live_server, capsys):
        _, _, url = live_server
        rc = cli_main(["-a", url, "remove_broker", ""])
        assert rc == 1
