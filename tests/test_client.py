"""Python client + CLI tests against a live in-process REST server.

Models the reference's python-client tests (cruise-control-client/tests):
endpoint wrappers, async long-polling, error surfacing, and the cccli
argument surface.
"""
import json

import conftest  # noqa: F401
import pytest

from cruise_control_tpu.client.cli import build_parser, main as cli_main
from cruise_control_tpu.client.client import (CruiseControlClient,
                                              CruiseControlClientError)

from test_facade import feed_samples, make_stack
from cruise_control_tpu.api.server import CruiseControlApp


@pytest.fixture(scope="module")
def live_server():
    sim, cc, clock = make_stack(num_brokers=4, skewed=True)
    cc.start_up(do_sampling=False, start_detection=False)
    feed_samples(cc, clock)
    app = CruiseControlApp(cc, async_response_timeout_s=5.0)
    port = app.start(port=0)
    yield sim, cc, f"http://127.0.0.1:{port}/kafkacruisecontrol"
    app.stop()
    cc.shutdown()


class TestClient:
    def test_state_and_load(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url)
        st = client.state()
        assert st["MonitorState"]["numValidWindows"] > 0
        load = client.load()
        assert len(load["brokers"]) == 4

    def test_proposals_long_poll(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url, poll_interval_s=0.5,
                                     timeout_s=600.0)
        out = client.proposals(verbose=True)
        assert out["summary"]["numProposals"] > 0
        assert "proposals" in out

    def test_dryrun_rebalance(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url, poll_interval_s=0.5,
                                     timeout_s=600.0)
        out = client.rebalance(dryrun=True)
        assert out["dryRun"] is True

    def test_error_surfacing(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url)
        with pytest.raises(CruiseControlClientError) as err:
            client.remove_broker([])     # missing brokerid
        assert err.value.status == 400
        with pytest.raises(ValueError):
            client.request("STATE", {"bogus": 1})

    def test_user_tasks_listed(self, live_server):
        _, _, url = live_server
        client = CruiseControlClient(url)
        client.state()
        tasks = client.user_tasks()
        assert "userTasks" in tasks


class TestRetry429:
    """Scheduler backpressure handling: HTTP 429 + Retry-After gets
    capped exponential backoff with DETERMINISTIC jitter, then the
    request is resubmitted (previously a 429 was a hard failure)."""

    def make_client(self, responses, sleeps, token="pinned-test-client"):
        client = CruiseControlClient(
            "http://cc.test/kafkacruisecontrol",
            retry_backoff_base_s=1.0, retry_backoff_max_s=30.0,
            retry_jitter_token=token,
            sleep_fn=sleeps.append)
        calls = []

        def fake_http(method, url, task_id, data=None):
            calls.append((method, url, task_id, data))
            return responses[min(len(calls) - 1, len(responses) - 1)]
        client._http = fake_http
        return client, calls

    def test_429_retries_honor_retry_after_and_succeed(self):
        sleeps = []
        rejected = (429, {"Retry-After": "7"},
                    {"errorMessage": "QueueFullError: solve queue full",
                     "retryAfterSeconds": 7, "version": 1})
        ok = (200, {}, {"version": 1, "summary": {}})
        client, calls = self.make_client([rejected, rejected, ok], sleeps)
        out = client.request("PROPOSALS")
        assert out["version"] == 1
        assert len(calls) == 3
        # Retry-After (7s) floors the 1s/2s exponential backoff, and
        # per-client jitter scales it UP — never sleep less than the
        # server's floor, never exactly the floor for every client
        assert len(sleeps) == 2
        for delay in sleeps:
            assert 7.0 <= delay < 7.0 * 1.5

    def test_429_backoff_is_exponential_with_deterministic_jitter(self):
        def run():
            sleeps = []
            rejected = (429, {}, {"errorMessage": "full", "version": 1})
            ok = (200, {}, {"version": 1})
            client, _ = self.make_client(
                [rejected, rejected, rejected, ok], sleeps)
            client.request("PROPOSALS")
            return sleeps

        first, second = run(), run()
        assert first == second                 # deterministic per token
        assert len(first) == 3
        # capped exponential shape: each delay within [0.5, 1.0) x
        # base * 2^attempt, and strictly growing
        for attempt, delay in enumerate(first):
            assert 0.5 * 2 ** attempt <= delay < 1.0 * 2 ** attempt
        assert first[0] < first[1] < first[2]

    def test_429_jitter_desynchronizes_distinct_clients(self):
        """A fleet rejected at the same instant must NOT retry in
        lockstep (that would refill the queue and 429 everyone again):
        distinct client tokens hash to distinct delays, and the
        auto-generated token is distinct per client instance."""
        def run(token, headers=None):
            sleeps = []
            rejected = (429, headers or {},
                        {"errorMessage": "full", "version": 1})
            ok = (200, {}, {"version": 1})
            client, _ = self.make_client(
                [rejected, rejected, rejected, ok], sleeps, token=token)
            client.request("PROPOSALS")
            return sleeps

        assert run("client-a") != run("client-b")
        # jitter must survive a dominating Retry-After: an unjittered
        # max(retry_after, backoff*jitter) would give every client
        # exactly 7.0 and re-stampede the queue in lockstep
        floored = {"Retry-After": "7"}
        a, b = run("client-a", floored), run("client-b", floored)
        assert a != b
        assert all(d >= 7.0 for d in a + b)
        c1 = CruiseControlClient("http://cc.test")
        c2 = CruiseControlClient("http://cc.test")
        assert c1._jitter_token != c2._jitter_token

    def test_429_retry_discards_the_failed_task_id_and_resends_body(self):
        """The 429 response carries the FAILED task's User-Task-ID for
        diagnostics; the retry must NOT reuse it (it would attach to the
        dead task and replay its cached rejection) and must resend the
        request body."""
        from cruise_control_tpu.api.user_tasks import USER_TASK_ID_HEADER
        sleeps = []
        rejected = (429, {USER_TASK_ID_HEADER: "dead-task",
                          "Retry-After": "1"},
                    {"errorMessage": "QueueFullError: full", "version": 1})
        ok = (200, {}, {"version": 1, "scenarios": [], "batch": {},
                        "dryRun": True})
        client, calls = self.make_client([rejected, ok], sleeps)
        out = client.request("SCENARIOS", body={"scenarios": []})
        assert out["version"] == 1
        assert len(calls) == 2
        # retry went out WITHOUT the dead task id and WITH the body
        assert calls[1][2] is None
        assert calls[1][3] is not None

    def test_429_gives_up_after_max_retries(self):
        sleeps = []
        rejected = (429, {"Retry-After": "1"},
                    {"errorMessage": "QueueFullError: full", "version": 1})
        client, calls = self.make_client([rejected], sleeps)
        client._max_retries_429 = 2
        with pytest.raises(CruiseControlClientError) as err:
            client.request("PROPOSALS")
        assert err.value.status == 429
        assert "gave up after 2 retries" in err.value.message
        assert len(calls) == 3                 # initial + 2 retries

    def test_zero_retries_fails_fast(self):
        sleeps = []
        rejected = (429, {}, {"errorMessage": "full", "version": 1})
        client, calls = self.make_client([rejected], sleeps)
        client._max_retries_429 = 0
        with pytest.raises(CruiseControlClientError):
            client.request("PROPOSALS")
        assert len(calls) == 1 and not sleeps

    def test_cli_exposes_max_retries(self):
        args = build_parser().parse_args(["--max-retries", "0", "state"])
        assert args.max_retries == 0
        args = build_parser().parse_args(["state"])
        assert args.max_retries == 4


class TestRetry503Draining:
    """Graceful-drain handling (PR-12 satellite): a 503 WITH a
    Retry-After hint (the api/server drain signature) gets the exact
    429 treatment — capped backoff, deterministic jitter, resubmit
    against the replacement process.  A bare 503 stays a hard error."""

    make_client = TestRetry429.make_client

    def test_503_draining_retries_like_429(self):
        sleeps = []
        draining = (503, {"Retry-After": "5"},
                    {"errorMessage": "ServerDraining: shutting down",
                     "retryAfterSeconds": 5, "version": 1})
        ok = (200, {}, {"version": 1, "summary": {}})
        client, calls = self.make_client([draining, draining, ok], sleeps)
        out = client.request("REBALANCE")
        assert out["version"] == 1
        assert len(calls) == 3
        assert len(sleeps) == 2
        # Retry-After floors the backoff, jittered upward — the same
        # contract the 429 path pins
        for delay in sleeps:
            assert 5.0 <= delay < 5.0 * 1.5

    def test_503_draining_body_hint_suffices(self):
        sleeps = []
        draining = (503, {}, {"errorMessage": "ServerDraining",
                              "retryAfterSeconds": 3, "version": 1})
        ok = (200, {}, {"version": 1})
        client, calls = self.make_client([draining, ok], sleeps)
        assert client.request("PROPOSALS")["version"] == 1
        assert len(calls) == 2 and len(sleeps) == 1

    def test_bare_503_is_a_hard_error(self):
        """No Retry-After hint = not draining (e.g. a fleet tenant
        drained for good): retrying blind would hammer a server that
        never asked for patience."""
        sleeps = []
        hard = (503, {}, {"errorMessage": "TenantDrainingError: gone",
                          "version": 1})
        client, calls = self.make_client([hard], sleeps)
        with pytest.raises(CruiseControlClientError) as err:
            client.request("REBALANCE")
        assert err.value.status == 503
        assert len(calls) == 1 and not sleeps

    def test_503_draining_gives_up_after_max_retries(self):
        sleeps = []
        draining = (503, {"Retry-After": "1"},
                    {"errorMessage": "ServerDraining",
                     "retryAfterSeconds": 1, "version": 1})
        client, calls = self.make_client([draining], sleeps)
        client._max_retries_429 = 2
        with pytest.raises(CruiseControlClientError) as err:
            client.request("PROPOSALS")
        assert err.value.status == 503
        assert len(calls) == 3


class TestServerDrain:
    """The REST half of graceful shutdown: app.drain() turns every
    mutating endpoint into 503 + Retry-After while reads keep
    answering (operators watch the drain through STATE)."""

    def test_drain_rejects_writes_keeps_reads(self, live_server):
        _, cc, _url = live_server
        app = CruiseControlApp(cc, async_response_timeout_s=5.0)
        # serving normally: writes admitted
        status, _, _ = app.handle_request(
            "POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
        assert status in (200, 202)
        app.drain(retry_after_s=17)
        assert app.draining
        status, headers, body = app.handle_request(
            "POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
        assert status == 503
        assert headers["Retry-After"] == "17"
        assert body["retryAfterSeconds"] == 17
        assert "ServerDraining" in body["errorMessage"]
        # reads still serve (operators watch the drain through STATE)
        status, _, body = app.handle_request(
            "GET", "/kafkacruisecontrol/state", "")
        assert status == 200 and body


class TestClusterFlag:
    """Fleet tenancy from the client side: `--cluster` threads
    `cluster=<id>` through every subcommand, and an unknown tenant's
    404 surfaces as a clean CruiseControlClientError."""

    def make_client(self, cluster):
        client = CruiseControlClient("http://cc.test/kafkacruisecontrol",
                                     cluster=cluster)
        urls = []

        def fake_http(method, url, task_id, data=None):
            urls.append(url)
            return 200, {}, {"version": 1, "summary": {},
                             "userTasks": [], "clusters": []}
        client._http = fake_http
        return client, urls

    def test_cluster_rides_on_every_subcommand(self):
        client, urls = self.make_client("prod-7")
        client.state()
        client.proposals()
        client.rebalance(dryrun=True)
        client.user_tasks()
        client.remove_broker([3])
        for url in urls:
            assert "cluster=prod-7" in url
        # FLEET spans the whole fleet: no cluster param
        client.fleet()
        assert "cluster=" not in urls[-1]

    def test_explicit_param_beats_client_default(self):
        client, urls = self.make_client("prod-7")
        client.request("STATE", {"cluster": "other"})
        assert "cluster=other" in urls[0]
        assert "cluster=prod-7" not in urls[0]

    def test_no_cluster_means_no_param(self):
        client, urls = self.make_client(None)
        client.state()
        assert "cluster=" not in urls[0]

    def test_unknown_tenant_404_is_a_clean_client_error(self,
                                                        live_server):
        """The live (fleet-less) server rejects any ?cluster= with 404;
        the client surfaces it as CruiseControlClientError(404), not a
        poll loop or a JSON decode crash."""
        _, _, url = live_server
        client = CruiseControlClient(url, cluster="nope")
        with pytest.raises(CruiseControlClientError) as err:
            client.state()
        assert err.value.status == 404
        assert "nope" in err.value.message

    def test_cli_cluster_flag(self):
        args = build_parser().parse_args(
            ["--cluster", "prod-7", "rebalance"])
        assert args.cluster == "prod-7"
        args = build_parser().parse_args(["fleet", "--verbose"])
        assert args.command == "fleet" and args.verbose
        args = build_parser().parse_args(["state"])
        assert args.cluster is None


class TestCli:
    def test_parser_covers_endpoints(self):
        parser = build_parser()
        for argv in (["state"], ["load"], ["proposals", "--verbose"],
                     ["rebalance", "--execute"],
                     ["add_broker", "1,2"], ["remove_broker", "3"],
                     ["demote_broker", "0"],
                     ["topic_configuration", "t", "3"],
                     ["stop_execution", "--force"],
                     ["admin", "--enable-self-healing-for",
                      "broker_failure"],
                     ["review", "--approve", "1,2"]):
            args = parser.parse_args(argv)
            assert args.command == argv[0]

    def test_cli_end_to_end(self, live_server, capsys):
        _, _, url = live_server
        rc = cli_main(["-a", url, "state"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert "MonitorState" in out

    def test_cli_error_exit_code(self, live_server, capsys):
        _, _, url = live_server
        rc = cli_main(["-a", url, "remove_broker", ""])
        assert rc == 1
