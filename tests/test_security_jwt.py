"""JwtSecurityProvider (RFC 7515/7519) and TLS serving tests.

Reference behavior being covered: servlet/security/jwt/JwtLoginService
.java:1-226 (JWT bearer authentication) and the optional SSL connector in
KafkaCruiseControlApp.java:100-173 (HTTPS round trip).
"""
import datetime
import json
import ssl
import urllib.request

import conftest  # noqa: F401
import pytest

from cruise_control_tpu.api.security import (AuthenticationError,
                                             JwtSecurityProvider, Role)

SECRET = b"test-hs256-secret"


def _provider(**kw):
    kw.setdefault("hs256_secret", SECRET)
    return JwtSecurityProvider(**kw)


def _headers(token):
    return {"Authorization": f"Bearer {token}"}


class TestHs256:
    def test_roundtrip_and_role(self):
        p = _provider(time_fn=lambda: 1000.0)
        tok = p.issue_hs256({"sub": "alice", "role": "ADMIN", "exp": 2000})
        principal = p.authenticate(_headers(tok))
        assert principal.name == "alice"
        assert principal.role == Role.ADMIN

    def test_default_role_when_claim_absent(self):
        p = _provider(default_role=Role.VIEWER, time_fn=lambda: 0.0)
        tok = p.issue_hs256({"sub": "bob"})
        assert p.authenticate(_headers(tok)).role == Role.VIEWER

    def test_expired_and_leeway(self):
        p = _provider(leeway_s=10.0, time_fn=lambda: 1000.0)
        tok = p.issue_hs256({"sub": "a", "exp": 995})
        p.authenticate(_headers(tok))          # inside leeway
        tok = p.issue_hs256({"sub": "a", "exp": 900})
        with pytest.raises(AuthenticationError, match="expired"):
            p.authenticate(_headers(tok))

    def test_nbf(self):
        p = _provider(leeway_s=0.0, time_fn=lambda: 1000.0)
        tok = p.issue_hs256({"sub": "a", "nbf": 2000})
        with pytest.raises(AuthenticationError, match="not yet valid"):
            p.authenticate(_headers(tok))

    def test_bad_signature(self):
        p = _provider(time_fn=lambda: 0.0)
        other = JwtSecurityProvider(hs256_secret=b"other",
                                    time_fn=lambda: 0.0)
        tok = other.issue_hs256({"sub": "a"})
        with pytest.raises(AuthenticationError, match="signature"):
            p.authenticate(_headers(tok))

    def test_alg_none_rejected(self):
        from cruise_control_tpu.api.security import _b64url
        p = _provider(time_fn=lambda: 0.0)
        header = _b64url(json.dumps({"alg": "none"}).encode())
        body = _b64url(json.dumps({"sub": "evil"}).encode())
        with pytest.raises(AuthenticationError, match="not accepted"):
            p.authenticate(_headers(f"{header}.{body}."))

    def test_issuer_audience(self):
        p = _provider(issuer="cc", audience="ops", time_fn=lambda: 0.0)
        good = p.issue_hs256({"sub": "a", "iss": "cc", "aud": ["ops", "x"]})
        p.authenticate(_headers(good))
        bad = p.issue_hs256({"sub": "a", "iss": "cc", "aud": "other"})
        with pytest.raises(AuthenticationError, match="audience"):
            p.authenticate(_headers(bad))
        bad = p.issue_hs256({"sub": "a", "iss": "zz", "aud": "ops"})
        with pytest.raises(AuthenticationError, match="issuer"):
            p.authenticate(_headers(bad))

    def test_unknown_role_rejected(self):
        p = _provider(time_fn=lambda: 0.0)
        tok = p.issue_hs256({"sub": "a", "role": "SUPERUSER"})
        with pytest.raises(AuthenticationError, match="unknown role"):
            p.authenticate(_headers(tok))


def _rsa_keypair():
    # optional dependency: RS256/TLS tests need `cryptography` to forge
    # keys/certs (the server-side verification under test is stdlib-only)
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    return key, pub


def _sign_rs256(private_key, claims):
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    from cruise_control_tpu.api.security import _b64url
    header = _b64url(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    body = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{body}".encode()
    sig = private_key.sign(signing_input, padding.PKCS1v15(),
                           hashes.SHA256())
    return f"{header}.{body}.{_b64url(sig)}"


class TestRs256:
    def test_roundtrip(self):
        key, pub = _rsa_keypair()
        p = JwtSecurityProvider(rs256_public_key_pem=pub,
                                time_fn=lambda: 0.0)
        tok = _sign_rs256(key, {"sub": "carol", "role": "USER"})
        principal = p.authenticate(_headers(tok))
        assert principal.name == "carol"
        assert principal.role == Role.USER

    def test_wrong_key_rejected(self):
        key, _ = _rsa_keypair()
        _, other_pub = _rsa_keypair()
        p = JwtSecurityProvider(rs256_public_key_pem=other_pub,
                                time_fn=lambda: 0.0)
        tok = _sign_rs256(key, {"sub": "carol"})
        with pytest.raises(AuthenticationError, match="signature"):
            p.authenticate(_headers(tok))

    def test_hs256_token_against_rs256_only_provider(self):
        """Algorithm confusion: an HS256 token signed with the PEM bytes
        must not pass an RS256-only provider."""
        _, pub = _rsa_keypair()
        p = JwtSecurityProvider(rs256_public_key_pem=pub,
                                time_fn=lambda: 0.0)
        forger = JwtSecurityProvider(hs256_secret=pub, time_fn=lambda: 0.0)
        tok = forger.issue_hs256({"sub": "evil", "role": "ADMIN"})
        with pytest.raises(AuthenticationError, match="not accepted"):
            p.authenticate(_headers(tok))


def _self_signed_cert(tmp_path):
    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=1))
            .sign(key, hashes.SHA256()))
    pem = tmp_path / "server.pem"
    pem.write_bytes(
        key.private_bytes(serialization.Encoding.PEM,
                          serialization.PrivateFormat.TraditionalOpenSSL,
                          serialization.NoEncryption())
        + cert.public_bytes(serialization.Encoding.PEM))
    return str(pem)


def test_https_round_trip(tmp_path):
    """Boot the real server with TLS and hit STATE over https."""
    from cruise_control_tpu.api.server import make_server_ssl_context
    from test_api import make_app

    pem = _self_signed_cert(tmp_path)
    sim, cc, app = make_app()
    try:
        port = app.start(host="127.0.0.1", port=0,
                         ssl_context=make_server_ssl_context(pem))
        client_ctx = ssl.create_default_context()
        client_ctx.check_hostname = False
        client_ctx.verify_mode = ssl.CERT_NONE
        with urllib.request.urlopen(
                f"https://127.0.0.1:{port}/kafkacruisecontrol/state",
                context=client_ctx, timeout=30) as resp:
            body = json.loads(resp.read())
        assert resp.status == 200
        assert "MonitorState" in body or "monitorState" in body or body
    finally:
        app.stop()
        cc.shutdown()
