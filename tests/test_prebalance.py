"""Contracts of the joint pre-balance pass (analyzer/prebalance.py) and
the global leadership sweep (analyzer/leadership.py).

These are the round-4 performance passes; their safety contracts (never
create violations, honor add-broker semantics, respect single-commit
fallbacks) are what lets them run before / inside the goal pipeline
without weakening the verifier invariants."""
import conftest  # noqa: F401

import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.analyzer.leadership import (global_leadership_sweep,
                                                    limit_bounds,
                                                    mean_bounds)
from cruise_control_tpu.analyzer.prebalance import prebalance
from cruise_control_tpu.model import state as S
from cruise_control_tpu.model.sanity import sanity_check
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)


def _mk(seed=21, **kw):
    spec = RandomClusterSpec(num_brokers=16, num_partitions=200,
                             replication_factor=3, num_racks=4,
                             num_topics=6, seed=seed, skew_fraction=0.5,
                             **kw)
    state, topo = random_cluster(spec)
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    return state, topo, ctx


def _upper(state, ctx):
    cap = np.asarray(state.broker_capacity)
    up = np.minimum(np.asarray(ctx.balance_upper_pct),
                    np.asarray(ctx.capacity_threshold))
    return up[None, :] * cap


def test_prebalance_reduces_over_band_and_keeps_invariants():
    state, topo, ctx = _mk()
    before_load = np.asarray(S.broker_load(state))
    upper = _upper(state, ctx)
    over_before = ((before_load > upper)
                   & np.asarray(state.broker_alive)[:, None]).sum()
    assert over_before > 0, "fixture must start unbalanced"
    prc_before = np.asarray(S.partition_rack_count(state))

    out, rounds, _ = prebalance(state, ctx)
    sanity_check(out)
    assert int(rounds) > 0
    after_load = np.asarray(S.broker_load(out))
    over_after = ((after_load > upper)
                  & np.asarray(out.broker_alive)[:, None]).sum()
    assert over_after < over_before
    # rack awareness can only improve: arrivals require a rack with no
    # copy of the partition
    prc_after = np.asarray(S.partition_rack_count(out))
    assert (prc_after > 1).sum() <= (prc_before > 1).sum()


def test_prebalance_never_creates_new_over_band_brokers():
    state, topo, ctx = _mk(seed=7)
    upper = _upper(state, ctx)
    before = np.asarray(S.broker_load(state))
    out, _, _ = prebalance(state, ctx)
    after = np.asarray(S.broker_load(out))
    newly_over = (after > upper) & ~(before > upper)
    assert not newly_over.any(), np.argwhere(newly_over)


def test_prebalance_inactive_dimensions_do_nothing():
    state, topo, ctx = _mk()
    out, rounds, _ = prebalance(state, ctx,
                             active_resources=(False,) * 4,
                             balance_counts=False)
    assert int(rounds) == 0
    np.testing.assert_array_equal(np.asarray(out.replica_broker),
                                  np.asarray(state.replica_broker))


def test_prebalance_add_broker_targets_only_new_brokers():
    spec = RandomClusterSpec(num_brokers=16, num_partitions=200,
                             replication_factor=3, num_racks=4,
                             num_topics=6, seed=3, skew_fraction=0.5,
                             new_brokers=2)
    state, topo = random_cluster(spec)
    ctx = make_context(state, BalancingConstraint(), OptimizationOptions(),
                       topo)
    out, _, _ = prebalance(state, ctx)
    moved = (np.asarray(out.replica_broker)
             != np.asarray(state.replica_broker))
    dest_new = np.asarray(state.broker_new)[np.asarray(out.replica_broker)]
    assert not (moved & ~dest_new & np.asarray(out.replica_valid)).any(), \
        "pre-balance moved a replica onto a pre-existing broker while " \
        "new brokers exist"


def _leader_counts(state):
    return np.asarray(S.broker_leader_count(state)).astype(float)


def test_sweep_mean_mode_contracts_leader_imbalance():
    state, topo, ctx = _mk(seed=11)
    counts0 = _leader_counts(state)
    avg = counts0[np.asarray(state.broker_alive)].mean()

    def upper_of(st, W):
        alive = st.broker_alive
        a = jnp.sum(W * alive) / jnp.maximum(jnp.sum(alive), 1)
        return jnp.full((st.num_brokers,), jnp.ceil(a * 1.09) + 1)

    out, rounds, _, _ = global_leadership_sweep(
        state, ctx, [],
        measure=lambda c: c.leader_count.astype(jnp.float32),
        value_r=jnp.ones(state.num_replicas, jnp.float32),
        bounds=mean_bounds(upper_of), improve_gate=True)
    counts1 = _leader_counts(out)
    assert int(rounds) > 0
    # total imbalance strictly shrinks, and no broker crosses the bound
    assert np.abs(counts1 - avg).sum() < np.abs(counts0 - avg).sum()
    upper = np.ceil(avg * 1.09) + 1
    assert not ((counts1 > upper) & ~(counts0 > upper)).any()
    sanity_check(out)


def test_sweep_limit_mode_respects_hard_cap():
    state, topo, ctx = _mk(seed=13)
    from cruise_control_tpu.common.resources import Resource
    res = int(Resource.CPU)
    cache = make_round_cache(state)
    W0 = np.asarray(cache.broker_load)[:, res]
    limit = jnp.asarray(np.quantile(W0, 0.7) * np.ones(state.num_brokers,
                                                       np.float32))
    mid = limit * 0.8
    out, rounds, _, _ = global_leadership_sweep(
        state, ctx, [],
        measure=lambda c: c.broker_load[:, res],
        value_r=(state.partition_leader_bonus[
            state.replica_partition, res]
            * state.replica_valid),
        bounds=limit_bounds(limit, mid), improve_gate=False)
    W1 = np.asarray(make_round_cache(out).broker_load)[:, res]
    lim = np.asarray(limit)
    assert (W0 > lim).sum() >= (W1 > lim).sum()
    # no under-limit broker got pushed over the hard cap
    assert not ((W1 > lim) & ~(W0 > lim)).any()


class _OpaqueLeadershipGoal(Goal):
    """Prior goal whose leadership acceptance is boolean-only
    (leadership_headroom_terms None — the documented-safe default)."""

    name = "OpaqueLeadershipGoal"

    def optimize(self, state, ctx, prev_goals):  # pragma: no cover
        return state

    def leadership_headroom_terms(self, state, ctx, cache):
        return None


def test_sweep_single_commit_fallback_for_opaque_prior_goal():
    state, topo, ctx = _mk(seed=11)
    counts0 = _leader_counts(state)

    def upper_of(st, W):
        return jnp.full((st.num_brokers,), jnp.inf)

    out, rounds, _, _ = global_leadership_sweep(
        state, ctx, [_OpaqueLeadershipGoal()],
        measure=lambda c: c.leader_count.astype(jnp.float32),
        value_r=jnp.ones(state.num_replicas, jnp.float32),
        bounds=mean_bounds(upper_of), improve_gate=True, max_rounds=1)
    counts1 = _leader_counts(out)
    delta = counts1 - counts0
    # one round, opaque prior goal: at most ONE transfer in and out per
    # broker (the boolean snapshot validates single actions only)
    assert delta.max() <= 1.0 and delta.min() >= -1.0
