"""Device-time solve scheduler (cruise_control_tpu/sched/).

Host-side units (policy aging, admission caps, coalescing, folding,
preemption, no-starvation — stub jobs, no device work) plus the
chaos-marker stress scenarios the PR-4 acceptance pins:

* single-gateway: under 16 concurrent mixed requests every device solve
  enters via sched/ (runtime `under_gateway` assertion; the static half
  is tools/lint.py's gateway rule, unit-tested here too);
* single-flight: N identical concurrent requests coalesce to exactly
  one compile+solve;
* preemption ordering: an ANOMALY_HEAL submitted mid-precompute begins
  executing before the preempted precompute work resumes;
* backpressure: clean 429 + Retry-After at the queue cap;
* the single-client K=1 path stays byte-identical to the unscheduled
  solve.
"""
import threading
import time as _real_time

import conftest  # noqa: F401

import pytest

from cruise_control_tpu.sched import runtime
from cruise_control_tpu.sched.policy import (PREEMPTIBLE_CLASSES,
                                             SchedulerClass,
                                             SchedulerPolicy)
from cruise_control_tpu.sched.queue import AdmissionQueue, QueueFullError
from cruise_control_tpu.sched.scheduler import (DeviceTimeScheduler,
                                                SchedulerStoppedError,
                                                SolveJob)

from test_facade import feed_samples, make_stack

pytestmark = pytest.mark.chaos

HEAL = SchedulerClass.ANOMALY_HEAL
USER = SchedulerClass.USER_INTERACTIVE
PRE = SchedulerClass.PRECOMPUTE
SWEEP = SchedulerClass.SCENARIO_SWEEP


def job(klass=USER, run=lambda: "ok", **kw):
    return SolveJob(klass=klass, run=run, **kw)


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------
class TestPolicy:
    def test_base_priority_order(self):
        p = SchedulerPolicy.default()
        scores = [p.effective_priority(c, 0.0) for c in SchedulerClass]
        assert scores == sorted(scores)

    def test_aging_beats_base_priority_eventually(self):
        """A SCENARIO_SWEEP that waited past its deadline budget earns
        enough credit to beat a fresh PRECOMPUTE — and with enough wait,
        even a fresh heal (no starvation)."""
        p = SchedulerPolicy.default()
        assert p.effective_priority(SWEEP, 0.0) \
            > p.effective_priority(PRE, 0.0)
        budget = p.classes[SWEEP].deadline_budget_s
        assert p.effective_priority(SWEEP, budget * 2) \
            < p.effective_priority(PRE, 0.0)
        assert p.effective_priority(SWEEP, budget * 100) \
            < p.effective_priority(HEAL, 0.0)

    def test_from_lists_validates(self):
        with pytest.raises(ValueError, match="exactly 4"):
            SchedulerPolicy.from_lists(weights=[1, 2, 3])
        with pytest.raises(ValueError, match="queue cap"):
            SchedulerPolicy.from_lists(queue_caps=[0, 1, 1, 1])

    def test_preemptible_classes(self):
        p = SchedulerPolicy.default()
        assert not p.is_preemptible(HEAL)
        assert not p.is_preemptible(USER)
        assert p.is_preemptible(PRE)
        assert p.is_preemptible(SWEEP)
        assert PREEMPTIBLE_CLASSES == {PRE, SWEEP}


# ---------------------------------------------------------------------------
# queue units
# ---------------------------------------------------------------------------
class TestAdmissionQueue:
    def make(self, caps=(8, 16, 2, 8), now=None):
        clock = now if now is not None else {"t": 0.0}
        q = AdmissionQueue(SchedulerPolicy.from_lists(queue_caps=caps),
                           lambda: clock["t"])
        return q, clock

    def test_cap_rejects_with_retry_after(self):
        q, clock = self.make(caps=(8, 2, 2, 8))
        q.offer(job())
        q.offer(job(coalesce_key=None))
        q.observe_latency(3.0)
        with pytest.raises(QueueFullError) as exc:
            q.offer(job())
        assert exc.value.klass is USER
        # depth 2 + the incoming one, 3.0s EWMA
        assert exc.value.retry_after_s == pytest.approx(9.0)

    def test_caps_are_per_class(self):
        q, clock = self.make(caps=(1, 1, 1, 1))
        q.offer(job(klass=USER))
        with pytest.raises(QueueFullError):
            q.offer(job(klass=USER))
        q.offer(job(klass=HEAL))  # other classes unaffected

    def test_coalesce_attaches_and_upgrades(self):
        q, clock = self.make()
        t1, created1 = q.offer(job(klass=PRE, coalesce_key=("k",)))
        t2, created2 = q.offer(job(klass=HEAL, coalesce_key=("k",)))
        assert created1 and not created2 and t1 is t2
        assert t1.attach_count == 1
        assert q.depth() == 1
        # the heal waiter upgraded the entry's dispatch class: it now
        # beats a fresh USER on the real preemption predicate
        assert q.has_effective_better_than(float(USER.value))
        # ...and the shared ticket reports the upgraded class, so a
        # USER_TASKS row for the heal waiter is not mislabeled as
        # background precompute work
        assert t1.klass is HEAL
        stop = threading.Event()
        entry = q.take(stop)
        assert entry.best_klass is HEAL and entry.klass is PRE

    def test_inflight_coalesce_until_finish(self):
        q, clock = self.make()
        t1, _ = q.offer(job(coalesce_key=("k",)))
        entry = q.take(threading.Event())
        # dispatched but unresolved: identical offers still attach
        t2, created = q.offer(job(coalesce_key=("k",)))
        assert t2 is t1 and not created
        q.finish(entry)
        t1.resolve("r")
        t3, created = q.offer(job(coalesce_key=("k",)))
        assert created and t3 is not t1

    def test_dispatch_order_priority_then_fifo(self):
        q, clock = self.make()
        ta, _ = q.offer(job(klass=SWEEP, run=lambda: "a"))
        tb, _ = q.offer(job(klass=USER, run=lambda: "b"))
        tc, _ = q.offer(job(klass=USER, run=lambda: "c"))
        stop = threading.Event()
        order = [q.take(stop).ticket for _ in range(3)]
        assert order == [tb, tc, ta]

    def test_queue_position_and_eta(self):
        q, clock = self.make()
        q.observe_latency(2.0)
        t1, _ = q.offer(job(klass=USER))
        t2, _ = q.offer(job(klass=SWEEP))
        assert t1.queue_position() == 0 and t2.queue_position() == 1
        # queued ETA: now + (pos + 1) * ewma
        assert t2.estimated_start_ms() == pytest.approx(4000.0)
        entry = q.take(threading.Event())
        assert entry.ticket is t1
        assert t1.queue_position() is None
        assert t1.estimated_start_ms() == pytest.approx(0.0)

    def test_requeue_keeps_enqueue_time(self):
        q, clock = self.make()
        t1, _ = q.offer(job(klass=PRE))
        entry = q.take(threading.Event())
        clock["t"] = 100.0
        q.requeue(entry)
        assert entry.enqueued_at == 0.0
        assert q.oldest_wait_s() == pytest.approx(100.0)

    def test_preemption_predicate_respects_running_aging(self):
        """The segment-checkpoint predicate compares EFFECTIVE
        priorities on both sides: a freshly-dispatched PRECOMPUTE
        yields to a fresh USER, but one whose aging credit has closed
        the base-class gap does NOT — so sustained interactive traffic
        delays a preemptible job a bounded number of segments instead
        of livelocking it (a heal still preempts until the credit
        covers two classes)."""
        clock = {"t": 0.0}
        p = SchedulerPolicy.default()   # PRE: weight 2, budget 120s
        q = AdmissionQueue(p, lambda: clock["t"])
        q.offer(job(klass=PRE))
        entry = q.take(threading.Event())

        def running_eff():
            return p.effective_priority(entry.best_klass,
                                        clock["t"] - entry.enqueued_at)

        clock["t"] = 1.0
        q.offer(job(klass=USER))
        assert q.has_effective_better_than(running_eff())
        q.take(threading.Event())       # drain the USER entry
        # 70s of accrued aging: credit 2*(70/120) > the 1-class gap to
        # USER_INTERACTIVE, < the 2-class gap to ANOMALY_HEAL
        clock["t"] = 70.0
        q.offer(job(klass=USER))
        assert not q.has_effective_better_than(running_eff())
        q.offer(job(klass=HEAL))
        assert q.has_effective_better_than(running_eff())


# ---------------------------------------------------------------------------
# scheduler units (stub jobs, no device work)
# ---------------------------------------------------------------------------
class TestSchedulerUnits:
    def blocked_scheduler(self, policy=None):
        """A scheduler whose dispatcher is parked on a gate job, so
        submissions from test threads queue deterministically."""
        sched = DeviceTimeScheduler(policy or SchedulerPolicy.default())
        gate = threading.Event()
        started = threading.Event()

        def gate_run():
            started.set()
            assert gate.wait(30.0)
            return "gate"

        waiter = threading.Thread(
            target=lambda: sched.submit(job(klass=USER, run=gate_run)),
            daemon=True)
        waiter.start()
        assert started.wait(10.0)
        return sched, gate

    def submit_async(self, sched, j):
        out = {}

        def run():
            try:
                out["result"] = sched.submit(j)
            except BaseException as exc:  # noqa: BLE001 - asserted below
                out["exc"] = exc
        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t, out

    def test_coalesced_submits_share_one_execution(self):
        sched, gate = self.blocked_scheduler()
        calls = []

        def solve():
            calls.append(1)
            return "r"

        threads = [self.submit_async(
            sched, job(run=solve, coalesce_key=("same",)))
            for _ in range(6)]
        deadline = _real_time.monotonic() + 10.0
        while sched.queue.depth() < 1 and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        # 6 submissions -> 1 queued entry
        assert sched.queue.depth() == 1
        gate.set()
        for t, out in threads:
            t.join(timeout=10.0)
            assert out.get("result") == "r"
        assert len(calls) == 1
        assert sched.stats.coalesced == 5
        sched.stop()

    def test_priority_dispatch_and_fold(self):
        sched, gate = self.blocked_scheduler()
        order = []

        def fold_run(payloads):
            order.append(("fold", sorted(payloads)))
            return [f"r{p}" for p in payloads]

        waiters = []
        for i in range(3):
            waiters.append(self.submit_async(sched, job(
                klass=SWEEP, run=lambda: None, fold_key=("f",),
                fold_payload=i, fold_run=fold_run)))
        waiters.append(self.submit_async(sched, job(
            klass=HEAL, run=lambda: order.append("heal") or "h")))
        deadline = _real_time.monotonic() + 10.0
        while sched.queue.depth() < 4 and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        gate.set()
        for t, _ in waiters:
            t.join(timeout=10.0)
        # the heal dispatched first; the three sweeps folded into ONE
        # execution whose results were split back per caller
        assert order[0] == "heal"
        assert order[1] == ("fold", [0, 1, 2])
        assert waiters[0][1]["result"] == "r0"
        assert waiters[2][1]["result"] == "r2"
        assert sched.stats.folded == 2
        sched.stop()

    def test_preemption_requeues_and_runs_urgent_first(self):
        sched = DeviceTimeScheduler(SchedulerPolicy.default())
        order = []
        pre_entered = threading.Event()
        heal_queued = threading.Event()

        def pre_run():
            order.append("pre-start")
            pre_entered.set()
            assert heal_queued.wait(10.0)
            runtime.segment_checkpoint()   # the optimizer does this
            order.append("pre-finish")     # only reached on the re-run
            return "pre"

        pre_thread, pre_out = self.submit_async(
            sched, job(klass=PRE, run=pre_run, preemptible=True))
        assert pre_entered.wait(10.0)
        pre_entered.clear()
        heal_thread, heal_out = self.submit_async(
            sched, job(klass=HEAL,
                       run=lambda: order.append("heal") or "h"))
        deadline = _real_time.monotonic() + 10.0
        while sched.queue.depth() < 1 and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        heal_queued.set()
        heal_thread.join(timeout=10.0)
        pre_thread.join(timeout=10.0)
        assert heal_out["result"] == "h"
        assert pre_out["result"] == "pre"
        # preempted at the checkpoint, heal ran, THEN the re-run finished
        assert order == ["pre-start", "heal", "pre-start", "pre-finish"]
        assert sched.stats.preemptions == 1
        sched.stop()

    def test_no_preemption_when_disabled(self):
        sched = DeviceTimeScheduler(
            SchedulerPolicy.default(preemption_enabled=False))
        entered = threading.Event()
        release = threading.Event()

        def pre_run():
            entered.set()
            assert release.wait(10.0)
            runtime.segment_checkpoint()   # must NOT raise
            return "pre"

        pre_thread, pre_out = self.submit_async(
            sched, job(klass=PRE, run=pre_run, preemptible=True))
        assert entered.wait(10.0)
        heal_thread, heal_out = self.submit_async(
            sched, job(klass=HEAL, run=lambda: "h"))
        deadline = _real_time.monotonic() + 10.0
        while sched.queue.depth() < 1 and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        release.set()
        pre_thread.join(timeout=10.0)
        heal_thread.join(timeout=10.0)
        assert pre_out["result"] == "pre" and heal_out["result"] == "h"
        assert sched.stats.preemptions == 0
        sched.stop()

    def test_no_starvation_under_sustained_high_priority(self):
        """A queued SCENARIO_SWEEP must dispatch even under a sustained
        stream of FRESH high-priority arrivals: its aging credit
        (weight x waited / deadline budget) eventually beats the fresh
        class's base priority.  Deterministic virtual clock against the
        real queue: every round one fresh USER request arrives and one
        entry dispatches, each 'solve' taking 10s."""
        clock = {"t": 0.0}
        q = AdmissionQueue(
            SchedulerPolicy.from_lists(
                deadline_budgets_s=[5.0, 30.0, 120.0, 60.0]),
            lambda: clock["t"])
        sweep_ticket, _ = q.offer(job(klass=SWEEP))
        stop = threading.Event()
        rounds = 0
        for rounds in range(1, 101):
            q.offer(job(klass=USER))          # fresh arrival every round
            entry = q.take(stop)
            clock["t"] += 10.0                # the solve runs
            if entry.ticket is sweep_ticket:
                break
        # weight 1, budget 60s: the sweep needs 2 classes of credit
        # (base 3 -> beat fresh USER base 1) = 120s waited = 12 rounds
        assert entry.ticket is sweep_ticket
        assert rounds < 20, "sweep starved behind fresh USER traffic"

    def test_disabled_scheduler_runs_inline_under_gateway(self):
        sched = DeviceTimeScheduler(enabled=False)
        seen = {}

        def solve():
            seen["gateway"] = runtime.under_gateway()
            seen["thread"] = threading.current_thread().name
            return "inline"

        assert sched.submit(job(run=solve)) == "inline"
        assert seen["gateway"] is True
        assert seen["thread"] == threading.current_thread().name
        assert sched.stats.completed == 1
        sched.stop()

    def test_nested_submit_from_dispatcher_runs_inline(self):
        sched = DeviceTimeScheduler()

        def outer():
            # a scheduled job submitting nested device work must not
            # deadlock on the busy dispatcher
            return sched.submit(job(run=lambda: "inner"))

        assert sched.submit(job(run=outer)) == "inner"
        sched.stop()

    def test_failure_propagates_to_every_waiter(self):
        sched, gate = self.blocked_scheduler()
        boom = RuntimeError("solve exploded")
        waiters = [self.submit_async(sched, job(
            run=lambda: (_ for _ in ()).throw(boom),
            coalesce_key=("fail",))) for _ in range(3)]
        deadline = _real_time.monotonic() + 10.0
        while sched.queue.depth() < 1 and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        gate.set()
        for t, out in waiters:
            t.join(timeout=10.0)
            assert out["exc"] is boom
        sched.stop()

    def test_failed_solves_do_not_feed_the_latency_ewma(self):
        """A fast failure is NOT a latency sample (same rule as
        preemption): a crash-looping solver (0.1s per failure vs minutes
        per real solve) would collapse the EWMA and have Retry-After
        invite a client stampede mid-incident."""
        sched = DeviceTimeScheduler(SchedulerPolicy.default())
        with pytest.raises(RuntimeError, match="boom"):
            sched.submit(job(
                run=lambda: (_ for _ in ()).throw(RuntimeError("boom"))))
        assert sched.queue.latency_ewma_s() == 0.0
        assert sched.submit(job(
            run=lambda: (_real_time.sleep(0.005), "ok")[1])) == "ok"
        assert sched.queue.latency_ewma_s() > 0.0
        sched.stop()

    def test_stop_fails_queued_tickets(self):
        sched, gate = self.blocked_scheduler()
        t, out = self.submit_async(sched, job(run=lambda: "late"))
        deadline = _real_time.monotonic() + 10.0
        while sched.queue.depth() < 1 and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        stopper = threading.Thread(target=sched.stop, daemon=True)
        stopper.start()
        gate.set()                 # unblock the gate job
        stopper.join(timeout=10.0)
        t.join(timeout=10.0)
        assert isinstance(out.get("exc"), SchedulerStoppedError)

    def test_submit_after_stop_is_rejected(self):
        """A post-stop submission fails fast instead of silently running
        a full device solve inline on the caller's thread, racing the
        rest of facade teardown.  The disabled scheduler keeps its
        inline semantics regardless."""
        sched = DeviceTimeScheduler()
        assert sched.submit(job(run=lambda: "ok")) == "ok"
        sched.stop()
        with pytest.raises(SchedulerStoppedError):
            sched.submit(job(run=lambda: "late"))
        inline = DeviceTimeScheduler(enabled=False)
        inline.stop()
        assert inline.submit(job(run=lambda: "still")) == "still"

    def test_chaos_dispatch_fault_resolves_waiter(self):
        from cruise_control_tpu.utils import faults
        sched, gate = self.blocked_scheduler()
        # the gate job already dispatched before the plan installed, so
        # the NEXT dispatch is call #1 for this injector
        plan = faults.FaultPlan().fail_nth("sched.dispatch", 1)
        with faults.injected(plan):
            t, out = self.submit_async(sched, job(run=lambda: "x"))
            deadline = _real_time.monotonic() + 10.0
            while sched.queue.depth() < 1 \
                    and _real_time.monotonic() < deadline:
                _real_time.sleep(0.01)
            gate.set()
            t.join(timeout=10.0)
        assert isinstance(out.get("exc"), faults.FaultError)
        assert out["exc"].site == "sched.dispatch"
        sched.stop()


# ---------------------------------------------------------------------------
# the optimizer really checkpoints between segments
# ---------------------------------------------------------------------------
class TestOptimizerCheckpoint:
    def test_segment_loop_raises_solve_preempted(self):
        from cruise_control_tpu.analyzer.goals.registry import default_goals
        from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
        from cruise_control_tpu.testing import fixtures
        state, topo = fixtures.small_cluster()
        optimizer = GoalOptimizer(default_goals(
            names=["RackAwareGoal", "DiskCapacityGoal"]))
        with runtime.gateway(lambda: True):
            with pytest.raises(runtime.SolvePreempted):
                optimizer.optimizations(state, topo, check_sanity=False)
        # without a check the same solve completes
        result = optimizer.optimizations(state, topo, check_sanity=False)
        assert result.final_state is not None


# ---------------------------------------------------------------------------
# lint single-gateway rule (the static half of the invariant)
# ---------------------------------------------------------------------------
class TestGatewayLintRule:
    def lint(self, tmp_path, relpath, source):
        """Per-file G101 findings from the whole-program analyzer
        (tools/analysis/ — the ISSUE-15 successor of the flat lint;
        single-file parse set = the old per-file semantics)."""
        import pathlib
        import sys
        sys.path.insert(0, str(pathlib.Path(conftest.__file__)
                               .parent.parent / "tools"))
        try:
            from analysis import cli
        finally:
            sys.path.pop(0)
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
        return [f.render() for f in cli.analyze([path], tmp_path)
                if "single-gateway" in f.message]

    def test_flags_direct_optimizer_solve_outside_gateway(self, tmp_path):
        bad = ("def f(optimizer, s, t):\n"
               "    return optimizer.optimizations(s, t)\n")
        assert self.lint(tmp_path, "cruise_control_tpu/rogue.py", bad)
        # same code inside the gateway files / sched/ is fine
        assert not self.lint(tmp_path, "cruise_control_tpu/facade.py", bad)
        assert not self.lint(tmp_path,
                             "cruise_control_tpu/sched/rogue.py", bad)
        # outside the package the rule does not apply
        assert not self.lint(tmp_path, "tools/rogue.py", bad)

    def test_flags_scenario_engine_and_host_fallback(self, tmp_path):
        bad = ("def f(self, s, t, specs, opts):\n"
               "    self.scenario_engine.evaluate(s, t, specs)\n"
               "    return host_fallback_solve(s, t, options=opts)\n")
        findings = self.lint(tmp_path, "cruise_control_tpu/rogue.py", bad)
        assert len(findings) == 2

    def test_facade_methods_not_flagged(self, tmp_path):
        ok = ("def op(cc):\n"
              "    return cc.optimizations()\n")
        assert not self.lint(tmp_path, "cruise_control_tpu/api/x.py", ok)

    def test_exemption_is_by_relative_path_not_filename(self, tmp_path):
        """Only the REAL solver modules are exempt — a future module
        that merely shares a filename (detector/engine.py,
        monitor/optimizer.py) must not inherit the exemption."""
        bad = ("def f(optimizer, s, t):\n"
               "    return optimizer.optimizations(s, t)\n")
        assert not self.lint(
            tmp_path, "cruise_control_tpu/analyzer/optimizer.py", bad)
        assert not self.lint(
            tmp_path, "cruise_control_tpu/scenario/engine.py", bad)
        assert self.lint(
            tmp_path, "cruise_control_tpu/monitor/optimizer.py", bad)
        assert self.lint(
            tmp_path, "cruise_control_tpu/detector/engine.py", bad)


# ---------------------------------------------------------------------------
# chaos stress: the wired stack under mixed concurrent load
# ---------------------------------------------------------------------------
class TestSchedulerStress:
    @pytest.fixture()
    def stack(self):
        sim, cc, clock = make_stack()
        cc.start_up(do_sampling=False, start_detection=False)
        feed_samples(cc, clock)
        yield sim, cc, clock
        cc.shutdown()

    def test_identical_concurrent_rebalances_coalesce_to_one_solve(
            self, stack):
        sim, cc, clock = stack
        solves = []
        orig = cc.goal_optimizer.optimizations

        def counting(*a, **k):
            solves.append(1)
            return orig(*a, **k)

        cc.goal_optimizer.optimizations = counting
        gate = threading.Event()
        started = threading.Event()

        def gate_run():
            started.set()
            assert gate.wait(30.0)
            return None

        gate_thread = threading.Thread(
            target=lambda: cc.solve_scheduler.submit(
                SolveJob(klass=USER, run=gate_run, label="gate")),
            daemon=True)
        gate_thread.start()
        assert started.wait(10.0)

        results = []
        lock = threading.Lock()

        def rebalance():
            r = cc.optimizations(ignore_proposal_cache=True)
            with lock:
                results.append(r)

        threads = [threading.Thread(target=rebalance, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()
        deadline = _real_time.monotonic() + 10.0
        while cc.solve_scheduler.queue.depth() < 1 \
                and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        assert cc.solve_scheduler.queue.depth() == 1  # 6 requests, 1 entry
        gate.set()
        for t in threads:
            t.join(timeout=60.0)
        gate_thread.join(timeout=10.0)
        assert len(results) == 6
        assert len(solves) == 1                       # ONE compile+solve
        assert all(r is results[0] for r in results)  # shared result
        assert cc.solve_scheduler.stats.coalesced >= 5

    def test_heal_preempts_inflight_precompute(self, stack):
        """An ANOMALY_HEAL submitted mid-precompute begins executing
        before the preempted precompute work resumes (the acceptance
        pin).  The precompute solve blocks at its first real segment
        checkpoint until the heal is queued."""
        from cruise_control_tpu.analyzer.context import OptimizationOptions
        sim, cc, clock = stack
        order = []
        order_lock = threading.Lock()
        heal_queued = threading.Event()
        orig = cc.goal_optimizer.optimizations

        def note(tag):
            with order_lock:
                order.append(tag)

        def hooked(*a, **k):
            # classify by options: the heal request carries an exclusion
            opts = k.get("options") or (a[2] if len(a) > 2 else None)
            is_heal = opts is not None and opts.excluded_topics
            note("heal-solve" if is_heal else "pre-solve")
            if not is_heal:
                assert heal_queued.wait(30.0)
                runtime.segment_checkpoint()
                note("pre-complete")
            return orig(*a, **k)

        cc.goal_optimizer.optimizations = hooked

        pre_out = {}

        def precompute():
            pre_out["status"] = cc._precompute_once_status()

        pre_thread = threading.Thread(target=precompute, daemon=True)
        pre_thread.start()
        deadline = _real_time.monotonic() + 10.0
        while not order and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        assert order == ["pre-solve"]        # precompute on the device

        heal_out = {}

        def heal():
            heal_out["result"] = cc.rebalance(
                dryrun=True,
                options=OptimizationOptions(
                    excluded_topics=frozenset({"__none__"})),
                reason="self-healing: goal violation",
                _scheduler_class=HEAL)

        heal_thread = threading.Thread(target=heal, daemon=True)
        heal_thread.start()
        deadline = _real_time.monotonic() + 10.0
        while cc.solve_scheduler.queue.depth() < 1 \
                and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        heal_queued.set()
        heal_thread.join(timeout=120.0)
        pre_thread.join(timeout=120.0)
        assert heal_out["result"].proposals is not None
        assert pre_out["status"] == "computed"
        # preempted precompute yielded; heal solved FIRST; precompute
        # then re-ran to completion
        assert order == ["pre-solve", "heal-solve", "pre-solve",
                         "pre-complete"]
        assert cc.solve_scheduler.stats.preemptions >= 1

    def test_sixteen_concurrent_mixed_requests_single_gateway(self, stack):
        """16 concurrent mixed requests (REST rebalances + proposals,
        some identical across clients, plus precompute passes): every
        optimizer invocation must happen inside the scheduler gateway,
        and every request must complete cleanly."""
        from cruise_control_tpu.api.server import CruiseControlApp
        from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
        sim, cc, clock = stack
        app = CruiseControlApp(cc, async_response_timeout_s=120.0)
        violations = []
        orig = GoalOptimizer.optimizations

        def asserting(self, *a, **k):
            if not runtime.under_gateway():
                violations.append("optimizer call outside the gateway")
            return orig(self, *a, **k)

        GoalOptimizer.optimizations = asserting
        try:
            statuses = []
            lock = threading.Lock()

            def rest(i):
                # half the rebalances share a query -> coalesce; the
                # rest are distinct
                if i % 4 == 0:
                    status, _, _ = app.handle_request(
                        "POST", "/kafkacruisecontrol/rebalance",
                        "dryrun=true", {}, client=f"client{i}")
                elif i % 4 == 1:
                    status, _, _ = app.handle_request(
                        "GET", "/kafkacruisecontrol/proposals",
                        "ignore_proposal_cache=true", {},
                        client=f"client{i}")
                elif i % 4 == 2:
                    status, _, _ = app.handle_request(
                        "POST", "/kafkacruisecontrol/rebalance",
                        "dryrun=true&verbose=true", {},
                        client=f"client{i}")
                else:
                    status = (200 if cc.precompute_proposals_once()
                              in (True, False) else 500)
                with lock:
                    statuses.append(status)

            threads = [threading.Thread(target=rest, args=(i,),
                                        daemon=True) for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180.0)
            assert len(statuses) == 16
            assert all(s in (200, 202) for s in statuses)
            assert not violations
        finally:
            GoalOptimizer.optimizations = orig
            app.user_tasks.shutdown()

    def test_queue_cap_surfaces_as_429_with_retry_after(self):
        """At the class queue cap the REST layer answers 429 with a
        Retry-After header (clean backpressure, not a 500)."""
        from cruise_control_tpu.api.server import CruiseControlApp
        sim, cc, clock = make_stack()
        try:
            cc.start_up(do_sampling=False, start_detection=False)
            feed_samples(cc, clock)
            # shrink the USER_INTERACTIVE cap to 1
            cc.solve_scheduler.policy = SchedulerPolicy.from_lists(
                queue_caps=[8, 1, 2, 8])
            cc.solve_scheduler.queue._policy = cc.solve_scheduler.policy
            app = CruiseControlApp(cc, async_response_timeout_s=5.0)
            gate = threading.Event()
            started = threading.Event()

            def gate_run():
                started.set()
                assert gate.wait(30.0)
                return None

            gate_thread = threading.Thread(
                target=lambda: cc.solve_scheduler.submit(
                    SolveJob(klass=USER, run=gate_run, label="gate")),
                daemon=True)
            gate_thread.start()
            assert started.wait(10.0)

            # fills the single USER queue slot (async task; distinct
            # queries so user-task dedup does not attach)
            filler = {}

            def fill():
                filler["resp"] = app.handle_request(
                    "GET", "/kafkacruisecontrol/proposals",
                    "ignore_proposal_cache=true", {}, client="a")

            fill_thread = threading.Thread(target=fill, daemon=True)
            fill_thread.start()
            deadline = _real_time.monotonic() + 10.0
            while cc.solve_scheduler.queue.depth() < 1 \
                    and _real_time.monotonic() < deadline:
                _real_time.sleep(0.01)
            assert cc.solve_scheduler.queue.depth() == 1

            # an IDENTICAL request coalesces rather than rejects (that
            # is the point of single-flight) — to hit the cap the next
            # request must be a different solve (excluded topics change
            # the options fingerprint)
            status, headers, body = app.handle_request(
                "GET", "/kafkacruisecontrol/proposals",
                "ignore_proposal_cache=true&excluded_topics=zzz", {},
                client="b")
            assert status == 429
            assert int(headers["Retry-After"]) >= 1
            assert body["retryAfterSeconds"] >= 1
            assert "QueueFullError" in body["errorMessage"]

            # and USER_TASKS shows the queued task's scheduler fields
            _, _, tasks = app.handle_request(
                "GET", "/kafkacruisecontrol/user_tasks", "", {},
                client="a")
            active = [t for t in tasks["userTasks"]
                      if t["Status"] == "Active"
                      and "SchedulerClass" in t]
            assert active
            assert active[0]["SchedulerClass"] == "USER_INTERACTIVE"
            # queued -> 1-based position (0 is reserved for on-device)
            assert active[0]["QueuePosition"] == 1
            assert "EstimatedStartMs" in active[0]

            gate.set()
            fill_thread.join(timeout=60.0)
            gate_thread.join(timeout=10.0)
            app.user_tasks.shutdown()
        finally:
            cc.shutdown()

    def test_429_re_arms_consumed_two_step_approval(self):
        """A reviewed request rejected at the queue cap must not burn
        its one-shot approval: the purgatory gate consumes the review
        BEFORE scheduler admission, so the 429 path rolls it back to
        APPROVED and the client's automatic retry (same review_id) is
        admitted once capacity frees up."""
        from cruise_control_tpu.api.server import CruiseControlApp
        sim, cc, clock = make_stack()
        try:
            cc.start_up(do_sampling=False, start_detection=False)
            feed_samples(cc, clock)
            cc.solve_scheduler.policy = SchedulerPolicy.from_lists(
                queue_caps=[8, 1, 2, 8])
            cc.solve_scheduler.queue._policy = cc.solve_scheduler.policy
            app = CruiseControlApp(cc, two_step_verification=True,
                                   async_response_timeout_s=30.0)
            # park + approve a dry-run rebalance (excluded_topics makes
            # its solve distinct from the filler below: an identical
            # request would coalesce instead of hitting the cap)
            query = "dryrun=true&excluded_topics=zzz"
            status, _, parked = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance", query, {},
                client="op")
            assert status == 202 and "reviewResult" in parked
            review_id = parked["reviewResult"]["Id"]
            app.purgatory.review([review_id], [], reason="lgtm")

            gate = threading.Event()
            started = threading.Event()

            def gate_run():
                started.set()
                assert gate.wait(30.0)
                return None

            gate_thread = threading.Thread(
                target=lambda: cc.solve_scheduler.submit(
                    SolveJob(klass=USER, run=gate_run, label="gate")),
                daemon=True)
            gate_thread.start()
            assert started.wait(10.0)
            filler = {}

            def fill():
                filler["resp"] = app.handle_request(
                    "GET", "/kafkacruisecontrol/proposals",
                    "ignore_proposal_cache=true", {}, client="a")

            fill_thread = threading.Thread(target=fill, daemon=True)
            fill_thread.start()
            deadline = _real_time.monotonic() + 10.0
            while cc.solve_scheduler.queue.depth() < 1 \
                    and _real_time.monotonic() < deadline:
                _real_time.sleep(0.01)
            assert cc.solve_scheduler.queue.depth() == 1

            status, _, _ = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance",
                f"{query}&review_id={review_id}", {}, client="op")
            assert status == 429
            # the consumed approval was rolled back, not burned
            assert app.purgatory._requests[review_id].status.value \
                == "APPROVED"

            gate.set()
            fill_thread.join(timeout=60.0)
            gate_thread.join(timeout=10.0)
            # the retry client.py would send after Retry-After: same
            # review id, now admitted and consumed for real
            status, _, _ = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance",
                f"{query}&review_id={review_id}", {}, client="op")
            assert status in (200, 202)
            assert app.purgatory._requests[review_id].status.value \
                == "SUBMITTED"
            app.user_tasks.shutdown()
        finally:
            cc.shutdown()

    def test_re_arm_fires_without_a_poll_and_never_after_retry(self):
        """The queue-cap rejection of a reviewed request may surface on
        a re-poll (task id attached) or on NO poll at all — the re-arm
        runs inside the task, so the approval is restored either way;
        and a stale poll of the dead task after a successful retry must
        NOT re-arm the approval the retry re-consumed (that would
        authorize a second execution of a one-shot review)."""
        from cruise_control_tpu.api.server import CruiseControlApp
        from cruise_control_tpu.api.user_tasks import USER_TASK_ID_HEADER
        sim, cc, clock = make_stack()
        try:
            cc.start_up(do_sampling=False, start_detection=False)
            feed_samples(cc, clock)
            cc.solve_scheduler.policy = SchedulerPolicy.from_lists(
                queue_caps=[8, 1, 2, 8])
            cc.solve_scheduler.queue._policy = cc.solve_scheduler.policy
            # tiny async timeout: the initial request answers 202 before
            # the worker hits the queue cap, so NO response carries the
            # rejection to the gate-running request
            app = CruiseControlApp(cc, two_step_verification=True,
                                   async_response_timeout_s=0.05)
            query = "dryrun=true&excluded_topics=zzz"
            status, _, parked = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance", query, {},
                client="op")
            assert status == 202 and "reviewResult" in parked
            review_id = parked["reviewResult"]["Id"]
            app.purgatory.review([review_id], [], reason="lgtm")

            gate = threading.Event()
            started = threading.Event()

            def gate_run():
                started.set()
                assert gate.wait(30.0)
                return None

            gate_thread = threading.Thread(
                target=lambda: cc.solve_scheduler.submit(
                    SolveJob(klass=USER, run=gate_run, label="gate")),
                daemon=True)
            gate_thread.start()
            assert started.wait(10.0)
            filler = {}

            def fill():
                filler["resp"] = app.handle_request(
                    "GET", "/kafkacruisecontrol/proposals",
                    "ignore_proposal_cache=true", {}, client="a")

            fill_thread = threading.Thread(target=fill, daemon=True)
            fill_thread.start()
            deadline = _real_time.monotonic() + 10.0
            while cc.solve_scheduler.queue.depth() < 1 \
                    and _real_time.monotonic() < deadline:
                _real_time.sleep(0.01)
            assert cc.solve_scheduler.queue.depth() == 1

            status, hdrs, _ = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance",
                f"{query}&review_id={review_id}", {}, client="op")
            dead_task = hdrs[USER_TASK_ID_HEADER]
            if status == 202:
                # rejection not yet surfaced — the re-arm still happens,
                # inside the task, with no poll observing it
                deadline = _real_time.monotonic() + 10.0
                while (app.purgatory._requests[review_id].status.value
                       != "APPROVED"
                       and _real_time.monotonic() < deadline):
                    _real_time.sleep(0.01)
            assert app.purgatory._requests[review_id].status.value \
                == "APPROVED"
            # a re-poll of the dead task replays the rejection as 429
            status, _, _ = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance",
                f"{query}&review_id={review_id}",
                {USER_TASK_ID_HEADER: dead_task}, client="op")
            assert status == 429

            gate.set()
            fill_thread.join(timeout=60.0)
            gate_thread.join(timeout=10.0)
            # the retry re-consumes the re-armed approval...
            status, _, _ = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance",
                f"{query}&review_id={review_id}", {}, client="op")
            assert status in (200, 202)
            assert app.purgatory._requests[review_id].status.value \
                == "SUBMITTED"
            # ...and a STALE poll of the dead task must not re-arm it
            status, _, _ = app.handle_request(
                "POST", "/kafkacruisecontrol/rebalance",
                f"{query}&review_id={review_id}",
                {USER_TASK_ID_HEADER: dead_task}, client="op")
            assert status == 429
            assert app.purgatory._requests[review_id].status.value \
                == "SUBMITTED"
            app.user_tasks.shutdown()
        finally:
            cc.shutdown()

    def test_k1_path_byte_identical_scheduled_vs_inline(self):
        """The single-client path must be byte-identical with the
        scheduler on and off (pinned: same fixture, same proposals,
        same final placement)."""
        import numpy as np
        sim1, cc1, clock1 = make_stack()
        sim2, cc2, clock2 = make_stack()
        cc2.solve_scheduler.enabled = False
        try:
            for cc, clock in ((cc1, clock1), (cc2, clock2)):
                cc.start_up(do_sampling=False, start_detection=False)
                feed_samples(cc, clock)
            r1 = cc1.optimizations()
            r2 = cc2.optimizations()

            def key(p):
                return (p.partition.topic, p.partition.partition,
                        tuple(r.broker_id for r in p.old_replicas),
                        tuple(r.broker_id for r in p.new_replicas))
            assert sorted(map(key, r1.proposals)) == \
                sorted(map(key, r2.proposals))
            assert np.array_equal(
                np.asarray(r1.final_state.replica_broker),
                np.asarray(r2.final_state.replica_broker))
            assert np.array_equal(
                np.asarray(r1.final_state.replica_is_leader),
                np.asarray(r2.final_state.replica_is_leader))
        finally:
            cc1.shutdown()
            cc2.shutdown()

    def test_concurrent_sweeps_fold_into_one_engine_batch(self, stack):
        """Two compatible concurrent evaluate_scenarios calls fold into
        ONE engine evaluation with the shared no-op base solved once;
        each caller gets back exactly its own scenarios (base first)."""
        from cruise_control_tpu.scenario.engine import BASE_SCENARIO_NAME
        from cruise_control_tpu.scenario.spec import ScenarioSpec
        sim, cc, clock = stack
        engine_calls = []
        orig_evaluate = cc.scenario_engine.evaluate

        def counting_evaluate(state, topo, specs, **kw):
            engine_calls.append([s.name for s in specs])
            return orig_evaluate(state, topo, specs, **kw)

        cc.scenario_engine.evaluate = counting_evaluate
        gate = threading.Event()
        started = threading.Event()

        def gate_run():
            started.set()
            assert gate.wait(30.0)
            return None

        gate_thread = threading.Thread(
            target=lambda: cc.solve_scheduler.submit(
                SolveJob(klass=USER, run=gate_run, label="gate")),
            daemon=True)
        gate_thread.start()
        assert started.wait(10.0)

        results = {}

        def sweep(name, scale):
            results[name] = cc.evaluate_scenarios(
                [ScenarioSpec(name=name, load_scale={"disk": scale})],
                include_proposals=False)

        t1 = threading.Thread(target=sweep, args=("grow", 1.2),
                              daemon=True)
        t2 = threading.Thread(target=sweep, args=("shrink", 0.8),
                              daemon=True)
        t1.start()
        deadline = _real_time.monotonic() + 10.0
        while cc.solve_scheduler.queue.depth() < 1 \
                and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        t2.start()
        while cc.solve_scheduler.queue.depth() < 2 \
                and _real_time.monotonic() < deadline:
            _real_time.sleep(0.01)
        gate.set()
        t1.join(timeout=300.0)
        t2.join(timeout=300.0)
        gate_thread.join(timeout=10.0)

        # ONE engine evaluation: shared base + both callers' scenarios
        assert len(engine_calls) == 1
        assert engine_calls[0] == [BASE_SCENARIO_NAME, "grow", "shrink"]
        assert cc.solve_scheduler.stats.folded == 1
        for name in ("grow", "shrink"):
            outs = results[name].outcomes
            assert [o.spec.name for o in outs] == [BASE_SCENARIO_NAME,
                                                   name]
        # the shared base outcome is literally shared
        assert results["grow"].outcomes[0] is results["shrink"].outcomes[0]

    def test_scheduler_state_and_sensors_exposed(self, stack):
        sim, cc, clock = stack
        cc.optimizations()
        st = cc.state()
        sched_state = st["SchedulerState"]
        assert sched_state["enabled"] is True
        assert sched_state["completed"] >= 1
        assert sched_state["deviceBusySeconds"] >= 0.0
        assert "ANOMALY_HEAL" in sched_state["queueDepthByClass"]
        sensors = cc.metrics.to_json()
        assert "sched-queue-depth" in sensors
        assert "sched-occupancy" in sensors
        assert "sched-queue-depth-user-interactive" in sensors
