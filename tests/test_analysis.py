"""Whole-program analyzer tests (tools/analysis/; marker: analysis).

Fixture-tree tests: every rule family gets one seeded TRUE POSITIVE and
one NEAR-MISS NEGATIVE, built as miniature `cruise_control_tpu`
packages under tmp_path (never checked in — seeded violations in the
repo tree would fire on the repo's own `make lint`).

Also pinned here:
  * the repo itself is CLEAN — zero unsuppressed, un-baselined findings
    (this is the regression test for every ISSUE-15 fix: the facade /
    load-monitor / task-runner lock fixes, the eager device-comparator
    init, the declared `cluster.admin.class`, the fault-site docs) and
    the lock-order graph over sched/ + parallel/health.py +
    fleet/registry.py + executor/ stays cycle-free;
  * the G101 laundering acceptance case: a bypass through one helper
    that the OLD receiver-name lint provably missed (both outcomes
    encoded);
  * byte-compatibility of the ported flat-rule messages;
  * suppression + baseline mechanics, including the empty-or-shrinking
    gate (the checked-in baseline is pinned EMPTY);
  * the canonical-sensor-name mirror matches utils/metrics.py;
  * analyzer wall-clock budget: < 30 s on the full package.
"""
from __future__ import annotations

import ast
import json
import sys
import textwrap
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

from analysis import cli, concurrency_rules, drift_rules, framework  # noqa: E402
from analysis.project import Project  # noqa: E402

pytestmark = pytest.mark.analysis

# the suppression marker, assembled so the analyzer's own scan of THIS
# file never mistakes fixture text for live suppressions
CC = "# cc-" + "lint: disable="


def build(tmp_path: Path, files: dict):
    """Write a fixture tree and analyze it; returns the finding list."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
    return cli.analyze(cli.collect_files([tmp_path]), tmp_path)


def rules_of(findings, path_part=""):
    return {f.rule for f in findings if path_part in f.path}


# ----------------------------------------------------------------------
# gateway reachability (G101): the acceptance-criteria laundering case
# ----------------------------------------------------------------------

_LAUNDERED = {
    "cruise_control_tpu/__init__.py": "",
    "cruise_control_tpu/analyzer/__init__.py": "",
    "cruise_control_tpu/analyzer/optimizer.py": """
        class GoalOptimizer:
            def __init__(self, cfg):
                self.cfg = cfg

            def optimizations(self, state, topology):
                return state
        """,
    "cruise_control_tpu/helpers.py": """
        from cruise_control_tpu.analyzer.optimizer import GoalOptimizer


        def grab(cfg, state, topo):
            o = GoalOptimizer(cfg)
            return o.optimizations(state, topo)
        """,
    "cruise_control_tpu/api/__init__.py": "",
    "cruise_control_tpu/api/server.py": """
        from cruise_control_tpu.helpers import grab


        def handle(cfg, state, topo):
            return grab(cfg, state, topo)
        """,
}


def _old_lint_receiver_heuristic(src: str):
    """The DELETED flat lint's G101 detection, verbatim semantics:
    `<recv>.optimizations(...)` fires only when the receiver's terminal
    identifier contains 'optimizer'."""
    hits = []
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "optimizations":
            base = node.func.value
            while isinstance(base, ast.Attribute):
                base = base.attr if False else base.value
            recv = getattr(base, "id", getattr(base, "attr", ""))
            if "optimizer" in str(recv).lower():
                hits.append(node.lineno)
    return hits


class TestGatewayReachability:
    def test_laundered_bypass_caught_where_name_match_missed(
            self, tmp_path):
        findings = build(tmp_path, _LAUNDERED)
        helper_src = (tmp_path / "cruise_control_tpu/helpers.py"
                      ).read_text()
        # outcome 1: the old receiver-name heuristic finds NOTHING —
        # the receiver is spelled `o`
        assert _old_lint_receiver_heuristic(helper_src) == []
        # outcome 2: reachability on the call graph catches it, with
        # entry-point path evidence
        g101 = [f for f in findings if f.rule == "G101"]
        assert len(g101) == 1
        f = g101[0]
        assert "helpers.py" in f.path
        assert "GoalOptimizer.optimizations" in f.message
        assert "reachable from entry point" in f.message
        assert "api.server.handle" in f.message

    def test_near_miss_facade_wrapper_is_quiet(self, tmp_path):
        files = dict(_LAUNDERED)
        # facade defines its own PUBLIC optimizations wrapper (the
        # gateway); a caller holding a facade is NOT a bypass
        files["cruise_control_tpu/facade.py"] = """
            class CruiseControl:
                def optimizations(self, **kw):
                    return None
            """
        files["cruise_control_tpu/helpers.py"] = """
            def via_facade(cc):
                return cc.optimizations()
            """
        files["cruise_control_tpu/api/server.py"] = """
            from cruise_control_tpu.helpers import via_facade


            def handle(cc):
                return via_facade(cc)
            """
        findings = build(tmp_path, files)
        assert "G101" not in rules_of(findings)

    def test_sink_in_gateway_module_is_allowed(self, tmp_path):
        files = dict(_LAUNDERED)
        del files["cruise_control_tpu/helpers.py"]
        files["cruise_control_tpu/sched/__init__.py"] = ""
        files["cruise_control_tpu/sched/scheduler.py"] = """
            from cruise_control_tpu.analyzer.optimizer import GoalOptimizer


            def dispatch(cfg, state, topo):
                o = GoalOptimizer(cfg)
                return o.optimizations(state, topo)
            """
        files["cruise_control_tpu/api/server.py"] = """
            from cruise_control_tpu.sched.scheduler import dispatch


            def handle(cfg, state, topo):
                return dispatch(cfg, state, topo)
            """
        findings = build(tmp_path, files)
        assert "G101" not in rules_of(findings)


class TestMeshAndCompileGateways:
    def test_alias_resolved_sinks_fire(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/rogue.py": """
                from jax import jit as fast
                from jax.sharding import Mesh as M


                def compile_it(fn, devices):
                    g = fast(fn)
                    return g, M(devices, ("x",))
                """,
        })
        assert {"G102", "G103"} <= rules_of(findings, "rogue.py")

    def test_gateway_modules_are_quiet(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/parallel/__init__.py": "",
            "cruise_control_tpu/parallel/progcache.py": """
                import jax


                def compile_it(fn):
                    return jax.jit(fn)
                """,
        })
        assert "G103" not in rules_of(findings)


# ----------------------------------------------------------------------
# concurrency: C201 / C202 / C203
# ----------------------------------------------------------------------

class TestLockOrderCycle:
    def test_ab_ba_cycle_fires(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/locks.py": """
                import threading


                class Foo:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def ab(self):
                        with self._a:
                            with self._b:
                                pass

                    def ba(self):
                        with self._b:
                            with self._a:
                                pass
                """,
        })
        c201 = [f for f in findings if f.rule == "C201"]
        assert c201 and "Foo._a" in c201[0].message \
            and "Foo._b" in c201[0].message

    def test_consistent_order_is_quiet(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/locks.py": """
                import threading


                class Foo:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def ab(self):
                        with self._a:
                            with self._b:
                                pass

                    def ab2(self):
                        with self._a:
                            with self._b:
                                pass
                """,
        })
        assert "C201" not in rules_of(findings)

    def test_interprocedural_cycle_fires(self, tmp_path):
        """The whole-program case per-file lint cannot see: each side
        nests through a CALL, not lexically."""
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/locks.py": """
                import threading


                class Foo:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def take_a(self):
                        with self._a:
                            pass

                    def take_b(self):
                        with self._b:
                            pass

                    def ab(self):
                        with self._a:
                            self.take_b()

                    def ba(self):
                        with self._b:
                            self.take_a()
                """,
        })
        assert "C201" in rules_of(findings)


class TestLockReentry:
    _SHAPE = """
        import threading


        class Foo:
            def __init__(self):
                self._lock = threading.{kind}()
                self.items = {{}}

            def put(self, k, v):
                with self._lock:
                    self._check(k)
                    self.items[k] = v

            def _check(self, k):
                with self._lock:
                    return k in self.items
        """

    def test_lock_reentry_fires(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/store.py":
                self._SHAPE.format(kind="Lock"),
        })
        c202 = [f for f in findings if f.rule == "C202"]
        assert c202 and "Foo._lock" in c202[0].message

    def test_rlock_reentry_is_quiet(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/store.py":
                self._SHAPE.format(kind="RLock"),
        })
        assert "C202" not in rules_of(findings)


class TestUnlockedSharedWrite:
    _SHAPE = """
        import threading


        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                self.bump()

            def bump(self):
                {body}
        """
    _API = """
        from cruise_control_tpu.worker import Worker


        def handle(w: Worker):
            w.bump()
        """

    def _run(self, tmp_path, body):
        return build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/worker.py":
                self._SHAPE.format(body=body),
            "cruise_control_tpu/api/__init__.py": "",
            "cruise_control_tpu/api/server.py": self._API,
        })

    def test_dual_reachable_unlocked_write_fires(self, tmp_path):
        findings = self._run(tmp_path, "self.count = self.count + 1")
        c203 = [f for f in findings if f.rule == "C203"]
        assert c203 and "self.count" in c203[0].message \
            and "worker.py" in c203[0].path

    def test_locked_write_is_quiet(self, tmp_path):
        body = ("with self._lock:\n"
                "            self.count = self.count + 1")
        findings = self._run(tmp_path, body)
        assert "C203" not in rules_of(findings)

    def test_condition_aliases_its_lock(self, tmp_path):
        """`with self._cond:` holds the SAME lock as `with self._lock:`
        when the Condition wraps it — no false C201/C203 pair."""
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/q.py": """
                import threading


                class Q:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._cond = threading.Condition(self._lock)
                        self.items = []

                    def put(self, v):
                        with self._cond:
                            self.items.append(v)

                    def size(self):
                        with self._lock:
                            return len(self.items)
                """,
        })
        assert not rules_of(findings) & {"C201", "C202", "C203"}


# ----------------------------------------------------------------------
# drift: config / sensors / fault sites
# ----------------------------------------------------------------------

class TestConfigDrift:
    _FILES = {
        "cruise_control_tpu/__init__.py": "",
        "cruise_control_tpu/config/__init__.py": "",
        "cruise_control_tpu/config/main_config.py": """
            def config_def(d):
                d.define("declared.key", "LONG", 1)
                d.define("undocumented.key", "LONG", 2)
                for klass in ("a", "b"):
                    d.define(f"slo.{klass}.latency.ms", "LONG", 3)
                return d
            """,
        "cruise_control_tpu/user.py": """
            def read(config):
                config.get_long("declared.key")
                config.get_long("slo.a.latency.ms")
                config.get_long("rogue.key")
            """,
        "docs/CONFIGURATION.md": """
            | name | type | default | importance | doc |
            |---|---|---|---|---|
            | declared.key | long | 1 | high | x |
            | slo.a.latency.ms | long | 3 | medium | x |
            | slo.b.latency.ms | long | 3 | medium | x |
            | stale.doc.key | long | 9 | low | x |
            """,
    }

    def test_all_three_directions(self, tmp_path):
        findings = build(tmp_path, self._FILES)
        msgs = {f.rule: f.message for f in findings}
        assert "rogue.key" in msgs["D301"]
        assert "undocumented.key" in msgs["D302"]
        assert "stale.doc.key" in msgs["D303"]
        # near-misses stay quiet: declared+documented+read keys, and
        # the f-string pattern covers the per-class expansion
        all_msgs = " ".join(f.message for f in findings)
        assert "'declared.key'" not in all_msgs
        assert "slo.a.latency.ms" not in all_msgs

    def test_non_config_dict_get_is_not_a_read(self, tmp_path):
        files = dict(self._FILES)
        files["cruise_control_tpu/user.py"] = """
            def read(config, topic_props):
                config.get_long("declared.key")
                topic_props.get("min.insync.replicas", 1)
            """
        findings = build(tmp_path, files)
        assert "D301" not in rules_of(findings)


class TestSensorDrift:
    def test_collision_and_degenerate_name(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/m.py": """
                class M:
                    def __init__(self, metrics):
                        self.metrics = metrics

                    def go(self):
                        self.metrics.counter("solve-rate")
                        self.metrics.meter("solve.rate")
                        self.metrics.counter("--")
                """,
        })
        msgs = [f.message for f in findings if f.rule == "D311"]
        assert msgs and "solve-rate" in msgs[0] \
            and "solve.rate" in msgs[0]
        assert "D310" in rules_of(findings)

    def test_forwarder_indirection_and_negative(self, tmp_path):
        """Names flowing through a first-order helper (`self._mark`)
        are collected; distinct canonical names stay quiet."""
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/m.py": """
                class M:
                    def __init__(self, metrics):
                        self.metrics = metrics

                    def _mark(self, sensor):
                        self.metrics.meter(sensor)

                    def go(self):
                        self._mark("sched-dispatches")
                        self.metrics.counter("sched.dispatches")
                """,
        })
        assert "D311" in rules_of(findings)
        findings2 = build(tmp_path / "neg", {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/m.py": """
                class M:
                    def __init__(self, metrics):
                        self.metrics = metrics

                    def go(self):
                        self.metrics.counter("solve-rate")
                        self.metrics.meter("queue-depth")
                """,
        })
        assert not rules_of(findings2) & {"D310", "D311"}

    def test_canonical_mirror_matches_real_implementation(self):
        from cruise_control_tpu.utils.metrics import canonical_sensor_name
        for raw in ("proposal-computation-timer", "REBALANCE-rate",
                    "sched.device.busy", "  x  ", "9lives", "--",
                    "cluster.kafka.prod.eu.meter"):
            assert drift_rules.canonical_sensor_name(raw) == \
                canonical_sensor_name(raw)


class TestFaultSiteDrift:
    _FILES = {
        "cruise_control_tpu/__init__.py": "",
        "cruise_control_tpu/engine.py": """
            from cruise_control_tpu.utils import faults


            def solve():
                faults.inject("engine.solve")
                faults.inject("engine.compile")
            """,
        "cruise_control_tpu/utils/__init__.py": "",
        "cruise_control_tpu/utils/faults.py": """
            def inject(site):
                pass
            """,
        "tests/test_chaos.py": """
            SITE = "engine.solve"
            """,
        "docs/OPERATIONS.md": """
            Fault sites: `engine.solve`.
            """,
    }

    def test_untested_undocumented_site_fires(self, tmp_path):
        findings = build(tmp_path, self._FILES)
        by_rule = {}
        for f in findings:
            by_rule.setdefault(f.rule, []).append(f.message)
        assert any("engine.compile" in m for m in by_rule.get("D320", []))
        assert any("engine.compile" in m for m in by_rule.get("D321", []))
        # the covered site stays quiet
        assert not any("engine.solve'" in m
                       for ms in by_rule.values() for m in ms)


# ----------------------------------------------------------------------
# flat rules: byte-compat + re-export-aware unused imports
# ----------------------------------------------------------------------

class TestFlatRules:
    def test_messages_byte_compatible_with_old_lint(self, tmp_path):
        p = tmp_path / "cruise_control_tpu" / "bad.py"
        p.parent.mkdir(parents=True)
        (tmp_path / "cruise_control_tpu" / "__init__.py").write_text("")
        p.write_text(
            "import os \n"
            "def f():\n"
            "\treturn 1\n"
            "y = " + "1" * 99 + "\n"
            "try:\n"
            "    pass\n"
            "except Exception:\n"
            "    pass")
        findings = cli.analyze(cli.collect_files([tmp_path]), tmp_path)
        rendered = {f.render() for f in findings}
        assert f"{p}:1: trailing whitespace" in rendered
        assert f"{p}:3: tab in indentation" in rendered
        assert f"{p}:4: line longer than 100 cols" in rendered
        assert f"{p}:8: missing final newline" in rendered
        assert f"{p}:1: unused import 'os'" in rendered
        assert (f"{p}:7: silent `except Exception` swallow — log it, "
                f"re-raise, or count it in a sensor") in rendered

    def test_reexport_resolution_replaces_filename_heuristic(
            self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/pkg/__init__.py": """
                from cruise_control_tpu.pkg.impl import Bar, Baz
                """,
            "cruise_control_tpu/pkg/impl.py": """
                Bar = 1
                Baz = 2
                """,
            "cruise_control_tpu/user.py": """
                from cruise_control_tpu.pkg import Bar

                USE = Bar
                """,
        })
        f006 = [f for f in findings if f.rule == "F006"]
        # Bar is re-exported (user.py imports it FROM the __init__) —
        # live; Baz is imported by nobody — the stale re-export the old
        # filename heuristic could never see
        assert len(f006) == 1 and "'Baz'" in f006[0].message

    def test_all_listing_keeps_reexport_live(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/pkg/__init__.py": """
                from cruise_control_tpu.pkg.impl import Baz

                __all__ = ["Baz"]
                """,
            "cruise_control_tpu/pkg/impl.py": """
                Baz = 2
                """,
        })
        assert "F006" not in rules_of(findings)


# ----------------------------------------------------------------------
# suppression + baseline mechanics
# ----------------------------------------------------------------------

class TestSuppressions:
    def test_justified_suppression_silences(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/mod.py":
                CC + "F004 -- generated table, clearer unwrapped\n"
                "X = " + "1" * 99 + "\n",
        })
        assert "F004" not in rules_of(findings)
        assert "F008" not in rules_of(findings)
        assert "F009" not in rules_of(findings)

    def test_bare_suppression_is_a_finding(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/mod.py":
                CC + "F004\n"
                "X = " + "1" * 99 + "\n",
        })
        assert "F008" in rules_of(findings)
        assert "F004" in rules_of(findings)   # bare disable buys nothing

    def test_unused_suppression_is_a_finding(self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/mod.py":
                CC + "F004 -- claims a long line that is not there\n"
                "X = 1\n",
        })
        assert "F009" in rules_of(findings)

    def test_multiline_justification_reaches_next_code_line(
            self, tmp_path):
        findings = build(tmp_path, {
            "cruise_control_tpu/__init__.py": "",
            "cruise_control_tpu/mod.py":
                CC + "F004 -- the justification wraps over\n"
                "# a continuation comment line\n"
                "X = " + "1" * 99 + "\n",
        })
        assert "F004" not in rules_of(findings)


class TestBaseline:
    def test_match_and_stale_detection(self):
        f = framework.Finding("C203", "cruise_control_tpu/x.py", 10,
                              "msg", symbol="x.Foo.bar")
        entries = [
            {"rule": "C203", "path": "cruise_control_tpu/x.py",
             "key": "x.Foo.bar"},
            {"rule": "C203", "path": "cruise_control_tpu/y.py",
             "key": "gone.symbol"},
        ]
        kept, baselined, stale = framework.apply_baseline([f], entries)
        assert kept == [] and baselined == [f]
        assert stale == [entries[1]]

    def test_subset_run_neither_stales_nor_prunes_out_of_scope(
            self, tmp_path):
        """Staleness is judged only against the analyzed parse set: a
        subset run must not fail on — and --prune-baseline must not
        delete — entries for files outside that set."""
        a = tmp_path / "cruise_control_tpu" / "a.py"
        b = tmp_path / "cruise_control_tpu" / "b.py"
        a.parent.mkdir(parents=True)
        a.write_text("X = " + "1" * 99 + "\n")
        b.write_text("Y = " + "1" * 99 + "\n")
        bl = tmp_path / "baseline.json"
        entries = [{"rule": "F004", "path": str(p),
                    "key": "line longer than # cols"} for p in (a, b)]
        framework.write_baseline(bl, entries)
        assert cli.main([str(a), str(b), "--baseline", str(bl)]) == 0
        # b is out of scope here: its entry is neither stale...
        assert cli.main([str(a), "--baseline", str(bl)]) == 0
        # ...nor pruned
        assert cli.main([str(a), "--baseline", str(bl),
                         "--prune-baseline"]) == 0
        assert framework.load_baseline(bl) == entries
        # pruning against an ignored baseline is a usage error (it
        # would rewrite the file empty)
        assert cli.main([str(a), "--no-baseline",
                         "--prune-baseline"]) == 2

    def test_repo_baseline_is_pinned_empty(self):
        """The empty-or-shrinking gate, strongest form: the checked-in
        baseline has NO entries, and nothing in the tooling can add one
        (--prune-baseline only removes).  New findings are fixed or
        suppressed inline with a justification."""
        data = json.loads(
            (REPO / "tools/analysis/baseline.json").read_text())
        assert data["entries"] == []


# ----------------------------------------------------------------------
# the repo itself: clean, cycle-free, inside the time budget
# ----------------------------------------------------------------------

class TestRepoInvariants:
    def test_repo_is_clean_and_fast(self):
        """Zero findings on the real tree (regression pin for every
        ISSUE-15 fix) within the < 30 s wall-clock budget."""
        roots = [REPO / p for p in cli.DEFAULT_PATHS]
        t0 = time.monotonic()
        findings = cli.analyze(cli.collect_files(roots), REPO)
        elapsed = time.monotonic() - t0
        assert findings == [], "\n".join(f.render() for f in findings)
        assert elapsed < 30.0, f"analyzer took {elapsed:.1f}s"

    def test_lock_order_graph_is_cycle_free(self):
        """Acceptance criterion: the lock-order graph over the whole
        package — sched/, parallel/health.py, fleet/registry.py,
        executor/ included — has no cycles, and stays that way."""
        project = Project.build(
            cli.collect_files([REPO / "cruise_control_tpu"]))
        cycles = concurrency_rules.lock_order_cycles(project)
        assert cycles == []
        # the graph is not trivially empty: the hot modules really do
        # contribute lock identities
        edges = concurrency_rules.lock_order_edges(project)
        owners = {owner for pair in edges for owner, _ in pair}
        assert any("sched" in o or "executor" in o or "health" in o
                   or "fleet" in o for o in owners), owners

    def test_rule_catalog_documented(self):
        doc = (REPO / "docs/ANALYSIS.md").read_text()
        for rule_id in framework.RULES:
            assert rule_id in doc, f"{rule_id} missing from ANALYSIS.md"

    def test_analyzer_self_analyzes(self):
        """tools/analysis/ is in the default parse set, its modules
        join the symbol table, and a seeded hygiene violation in a
        sibling tools file is caught (the analyzer polices itself)."""
        project = Project.build(
            cli.collect_files([REPO / "tools" / "analysis"]))
        assert "tools.analysis.project" in project.modules
        assert "tools.analysis.cli" in project.modules
        # the default invocation really includes the analyzer's own
        # files — so the repo-is-clean pin above covers them
        files = cli.collect_files([REPO / p for p in cli.DEFAULT_PATHS])
        assert REPO / "tools/analysis/cli.py" in files


# ----------------------------------------------------------------------
# regression tests for the nontrivial ISSUE-15 code fixes
# ----------------------------------------------------------------------

class TestIssue15Fixes:
    def test_cluster_admin_class_is_declared(self):
        from cruise_control_tpu.config.main_config import config_def
        keys = config_def().keys()
        assert "cluster.admin.class" in keys

    def test_device_comparators_eager_and_stable(self):
        """The lazy `_device_cmp` memo was an unlocked dual-thread
        write (C203); it is now computed at construction."""
        from cruise_control_tpu.analyzer.goals.capacity import (
            ReplicaCapacityGoal)
        from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
        opt = GoalOptimizer([ReplicaCapacityGoal()])
        assert isinstance(opt._device_cmp, tuple)
        assert opt._device_comparators() is opt._device_cmp
