"""Metrics-agent pipeline tests.

Models the reference's metrics-reporter tests
(CruiseControlMetricsReporterTest: reporter in a real broker producing to
the metrics topic; MetricsUtils/serde unit tests) — here the full
production-shaped pipeline: agent -> serialized records -> transport ->
processor -> aggregator samples -> cluster model.
"""
import conftest  # noqa: F401

import numpy as np
import pytest

from cruise_control_tpu.agent import (AgentMetric, AgentMetricsReporterSampler,
                                      InProcessMetricsTransport,
                                      MetricsReporterAgent, RawMetricType,
                                      SimulatedNodeMetricsSource,
                                      deserialize, serialize)
from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.capacity import StaticCapacityResolver
from cruise_control_tpu.model import state as S
from cruise_control_tpu.monitor.load_monitor import LoadMonitor

T = RawMetricType


class TestSerde:
    def test_roundtrip_all_scopes(self):
        for m in (
            AgentMetric(T.BROKER_CPU_UTIL, 3, 1234.0, 55.5),
            AgentMetric(T.TOPIC_BYTES_IN, 1, 99.0, 1e6, topic="t"),
            AgentMetric(T.PARTITION_SIZE, 2, 5.0, 42.0, topic="t",
                        partition=7),
        ):
            assert deserialize(serialize(m)) == m

    def test_scope_validation(self):
        with pytest.raises(ValueError):
            AgentMetric(T.TOPIC_BYTES_IN, 1, 0.0, 1.0)      # topic missing
        with pytest.raises(ValueError):
            AgentMetric(T.PARTITION_SIZE, 1, 0.0, 1.0, topic="t")


def make_sim(num_brokers=4, partitions=8, rf=2):
    sim = SimulatedCluster()
    for b in range(num_brokers):
        sim.add_broker(b, rack=f"r{b % 2}")
    assignments = [[(p + i) % num_brokers for i in range(rf)]
                   for p in range(partitions)]
    sim.create_topic("t0", assignments, size_bytes=1e4)
    for p in range(partitions):
        sim.set_partition_load(TopicPartition("t0", p), leader_cpu=2.0,
                               nw_in=100.0, nw_out=300.0)
    return sim


class TestAgentPipeline:
    def test_agents_report_and_processor_builds_samples(self):
        sim = make_sim()
        transport = InProcessMetricsTransport()
        clock = {"now": 10_000.0}
        agents = [MetricsReporterAgent(
            SimulatedNodeMetricsSource(sim, b), transport,
            time_fn=lambda: clock["now"]) for b in range(4)]
        for a in agents:
            assert a.report_once() > 0
        sampler = AgentMetricsReporterSampler(transport)
        snapshot = sim.describe_cluster()
        samples = sampler.get_samples(
            snapshot, {p.tp for p in snapshot.partitions}, 0.0, 20_000e3)
        assert len(samples.broker_samples) == 4
        # every partition got a sample from its leader's agent
        assert len(samples.partition_samples) == 8
        # per-partition bytes share: topic bytes-in split across leaders'
        # partitions; each leader leads 2 of its topic partitions
        from cruise_control_tpu.monitor import metricdef as MD
        cdef = MD.common_metric_def()
        nw_id = cdef.metric_id(MD.LEADER_BYTES_IN)
        for s in samples.partition_samples:
            assert s.values[nw_id] == pytest.approx(100.0)

    def test_pipeline_feeds_cluster_model(self):
        sim = make_sim()
        transport = InProcessMetricsTransport()
        clock = {"now": 10_000.0}
        agents = [MetricsReporterAgent(
            SimulatedNodeMetricsSource(sim, b), transport,
            time_fn=lambda: clock["now"]) for b in range(4)]
        monitor = LoadMonitor(
            sim, AgentMetricsReporterSampler(transport),
            StaticCapacityResolver(), num_windows=3, window_ms=10_000,
            min_samples_per_window=1, sampling_interval_ms=5_000,
            time_fn=lambda: clock["now"])
        monitor.start_up(do_sampling=False)
        for _ in range(8):
            for a in agents:
                a.report_once()
            monitor.task_runner.sample_once()
            clock["now"] += 10.0
        state, topo = monitor.cluster_model()
        assert state.num_brokers == 4
        assert int(np.asarray(state.replica_valid).sum()) == 16
        load = np.asarray(S.broker_load(state))
        # leaders carry NW_OUT; followers add replication NW_IN
        assert load[:, Resource.NW_OUT].sum() == pytest.approx(
            8 * 300.0, rel=1e-3)
        monitor.shutdown()

    def test_corrupt_records_dropped(self):
        transport = InProcessMetricsTransport()
        transport.produce([b"garbage", serialize(
            AgentMetric(T.BROKER_CPU_UTIL, 0, 1.0, 10.0))])
        sampler = AgentMetricsReporterSampler(transport)
        sim = make_sim(num_brokers=1, partitions=1, rf=1)
        snapshot = sim.describe_cluster()
        samples = sampler.get_samples(snapshot, set(), 0.0, 1e9)
        assert len(samples.broker_samples) == 1

    def test_background_reporting_thread(self):
        sim = make_sim()
        transport = InProcessMetricsTransport()
        agent = MetricsReporterAgent(
            SimulatedNodeMetricsSource(sim, 0), transport,
            reporting_interval_s=0.05)
        agent.start()
        import time
        deadline = time.time() + 5.0
        while time.time() < deadline and not transport.poll(1):
            time.sleep(0.05)
        agent.shutdown()
        assert agent._thread is None
