"""End-to-end tests for the first goal kernel: ResourceDistributionGoal
(analog of the reference's DeterministicClusterTest over distribution goals
plus self-healing fixtures)."""
import numpy as np

import pytest

from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                 OptimizationOptions,
                                                 make_context,
                                                 make_round_cache)
from cruise_control_tpu.analyzer.goals.resource_distribution import (
    DiskUsageDistributionGoal, NetworkOutboundUsageDistributionGoal)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.common.resources import Resource as R
from cruise_control_tpu.model import state as S
from cruise_control_tpu.testing import fixtures
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)
from cruise_control_tpu.testing.verifier import run_and_verify


from cruise_control_tpu.testing.fixtures import util_spread as _util_spread


@pytest.mark.slow
def test_disk_distribution_on_unbalanced():
    state, topo = fixtures.unbalanced_cluster()
    before = _util_spread(state, R.DISK)
    opt = GoalOptimizer([DiskUsageDistributionGoal()])
    result = run_and_verify(opt, state, topo)
    after = _util_spread(result.final_state, R.DISK)
    assert after < before, f"disk spread did not improve: {before} -> {after}"
    assert result.proposals, "expected at least one proposal"
    # the optimizer must not invent or destroy replicas
    assert int(np.asarray(result.final_state.replica_valid).sum()) == 12


@pytest.mark.slow
def test_nw_out_distribution_uses_leadership_moves():
    state, topo = fixtures.unbalanced_cluster()
    before = _util_spread(state, R.NW_OUT)
    opt = GoalOptimizer([NetworkOutboundUsageDistributionGoal()])
    result = run_and_verify(opt, state, topo)
    after = _util_spread(result.final_state, R.NW_OUT)
    assert after < before
    # leadership moved off broker 0 (it led all 6 partitions)
    leaders = np.asarray(S.broker_leader_count(result.final_state))
    assert leaders[0] < 6


@pytest.mark.slow
def test_self_healing_dead_broker():
    state, topo = fixtures.dead_broker_cluster()
    opt = GoalOptimizer([DiskUsageDistributionGoal()])
    result = run_and_verify(opt, state, topo)
    broker = np.asarray(result.final_state.replica_broker)
    assert not (broker == 2).any(), "dead broker still hosts replicas"


def test_proposals_have_valid_shape():
    state, topo = fixtures.unbalanced_cluster()
    opt = GoalOptimizer([DiskUsageDistributionGoal()])
    result = run_and_verify(opt, state, topo)
    for p in result.proposals:
        assert p.old_leader in [0, 1, 2]
        assert len(p.new_replicas) == len(p.old_replicas)
        json = p.to_json()
        assert json["topicPartition"]["topic"] == p.partition.topic


@pytest.mark.slow
def test_random_cluster_disk_distribution():
    spec = RandomClusterSpec(num_brokers=24, num_partitions=400,
                             replication_factor=3, num_racks=4,
                             num_topics=10, seed=11, skew_fraction=0.4)
    state, topo = random_cluster(spec)
    before = _util_spread(state, R.DISK)
    opt = GoalOptimizer([DiskUsageDistributionGoal(max_rounds=128)])
    result = run_and_verify(opt, state, topo)
    after = _util_spread(result.final_state, R.DISK)
    assert after <= before
    # every alive broker within threshold bounds (soft goal should converge
    # on this easy instance)
    final = result.final_state
    ctx = make_context(final, BalancingConstraint(), OptimizationOptions(),
                       topo)
    cache = make_round_cache(final)
    violated = np.asarray(
        DiskUsageDistributionGoal().violated_brokers(final, ctx, cache))
    assert violated.sum() <= spec.num_brokers * 0.15, (
        f"{violated.sum()} brokers still out of disk balance")


def test_excluded_topics_never_move():
    state, topo = fixtures.unbalanced_cluster()
    options = OptimizationOptions(excluded_topics=frozenset(["T1"]))
    opt = GoalOptimizer([DiskUsageDistributionGoal()])
    result = opt.optimizations(state, topo, options)
    # T1 is the only topic → nothing can move
    assert result.proposals == []


def test_dead_broker_with_excluded_topics_still_heals():
    # reference semantics: excluded topics still move off dead brokers?
    # The reference keeps excluded-topic replicas in place EXCEPT offline
    # ones (GoalUtils filters exclude offline replicas from exclusion).
    state, topo = fixtures.dead_broker_cluster()
    options = OptimizationOptions(excluded_topics=frozenset(["T1", "T2"]))
    opt = GoalOptimizer([DiskUsageDistributionGoal()])
    result = opt.optimizations(state, topo, options)
    broker = np.asarray(result.final_state.replica_broker)
    valid = np.asarray(result.final_state.replica_valid)
    assert not (valid & (broker == 2)).any()
