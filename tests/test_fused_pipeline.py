"""Device-resident goal pipeline: fusion, transfer, and abort semantics.

Pins the PR-1 tentpole contract (analyzer/optimizer.py):

* O(1) host round-trips per solve — no device→host transfer between the
  first goal's dispatch and the single end-of-solve instrument fetch
  (asserted with jax's transfer guard + a device_get call counter);
* the fused path (per-goal epilogues — stats, violated counts,
  non-regression flags, hard-goal predicate — inside the goal programs,
  instruments fetched once) reproduces the PRE-FUSION evaluation order:
  an eager per-goal reference driver built from the same goal SPI, with
  a host fetch after every goal, must agree on violated_broker_counts,
  rounds_by_goal, regression flags, and the final proposals on the
  config-1 differential fixture;
* hard-goal abort: deferred (default) and eager (opt-in) modes both
  raise OptimizationFailure for an unsatisfiable hard goal;
* profile mode (CC_TPU_PROFILE=1) re-segments per goal and reports the
  same instruments.
"""
import numpy as np

import conftest  # noqa: F401

import jax
import jax.numpy as jnp
import pytest

from cruise_control_tpu.analyzer.context import (OptimizationOptions,
                                                 make_context)
from cruise_control_tpu.analyzer.goals.base import (Goal,
                                                    OptimizationFailure)
from cruise_control_tpu.analyzer.goals.registry import default_goals
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.testing import fixtures

#: mixed subset exercising forced moves (hard), capacity (hard), count
#: distribution, usage distribution, and both leadership paths — small
#: enough to compile quickly on the CI CPU, wide enough that every
#: epilogue variety (traceable comparators, hard predicates, leadership
#: sweeps) appears in the fused programs
GOAL_SUBSET = [
    "RackAwareGoal", "DiskCapacityGoal", "ReplicaDistributionGoal",
    "DiskUsageDistributionGoal", "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
]


def test_no_host_transfers_between_dispatch_and_fetch(monkeypatch):
    """The solve performs EXACTLY ONE device_get (the end-of-solve
    instrument fetch), and no device→host transfer escapes the two
    sanctioned allow-regions — asserted by running the whole solve under
    jax.transfer_guard_device_to_host("disallow")."""
    state, topo = fixtures.small_cluster()
    opt = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                        pipeline_segment_size=2)

    calls = []
    real_device_get = jax.device_get

    def counting_device_get(x):
        calls.append(1)
        return real_device_get(x)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    with jax.transfer_guard_device_to_host("disallow"):
        result = opt.optimizations(state, topo, OptimizationOptions(),
                                   check_sanity=False)
    # exactly TWO device_get calls, both in the sanctioned tail: the
    # end-of-solve instrument fetch, then diff_proposals' one batched
    # placement fetch (round-5 diff economics).  O(1) per solve — the
    # pre-fusion path paid one per goal epilogue on top.
    assert len(calls) == 2, (
        f"expected instrument fetch + diff fetch, saw "
        f"{len(calls)} device_get calls")
    # the one fetch populated every instrument
    assert set(result.violated_broker_counts) == set(GOAL_SUBSET)
    assert set(result.rounds_by_goal) >= set(GOAL_SUBSET)
    assert result.stats_before is not None
    assert result.proposals  # the fixture's forced rack move


def _unfused_reference_solve(opt, state, topo, options):
    """Pre-fusion reference driver: the SAME goal SPI and pre program,
    but every goal's epilogue evaluated EAGERLY — a device_get after
    each goal for stats/violated counts and a host-side regression
    comparison — replicating the pipeline's exact cadence (float
    aggregates refreshed at segment entry, cache threaded goal to goal,
    table re-ensured at segment exit)."""
    from cruise_control_tpu.analyzer.context import (
        ensure_full_cache, refresh_float_aggregates)
    from cruise_control_tpu.analyzer.goals import base as goals_base
    from cruise_control_tpu.model.stats import (compute_stats,
                                                compute_stats_fresh_loads)

    goals = list(opt.goals)
    ctx = make_context(state, opt.constraint, options, topo)
    initial = state
    stats_before = jax.device_get(jax.jit(compute_stats)(state))
    (_, vb_dev, state, cache, _, _, _, pre_rounds, _) = jax.jit(
        opt._pre_fn())(initial, state, ctx)
    vb = np.asarray(jax.device_get(vb_dev))

    def goal_step(i):
        def fn(st, ca, cx):
            sink = []
            goals_base.set_round_sink(sink)
            try:
                st, ca = goals[i].optimize_cached(st, cx, goals[:i], ca)
            finally:
                goals_base.set_round_sink(None)
            rounds, _ = goals_base.collapse_sink(sink)
            return st, ca, rounds
        return jax.jit(fn)

    own, rounds, regressed = {}, {}, []
    prev_stats = stats_before
    # the SAME segment plan as the fused pipeline (fusion megaprograms
    # included): the float-refresh cadence at segment entry is part of
    # the numerics being pinned
    for start, stop in opt._plan_segments():
        cache = jax.jit(refresh_float_aggregates)(state, cache)
        for i in range(start, stop):
            state, cache, r_dev = goal_step(i)(state, cache, ctx)
            rounds[goals[i].name] = int(jax.device_get(r_dev))
            goal_stats = jax.device_get(
                jax.jit(compute_stats_fresh_loads)(state, cache))
            own[goals[i].name] = int(jax.device_get(jax.jit(
                lambda st, ca, cx, i=i: goals[i].violated_brokers(
                    st, cx, ca).sum(dtype=jnp.int32))(state, cache, ctx)))
            if not goals[i].stats_not_worse(prev_stats, goal_stats):
                regressed.append(goals[i].name)
            prev_stats = goal_stats
        cache = jax.jit(ensure_full_cache)(state, ctx, cache)
    va = np.asarray(jax.device_get(jax.jit(opt._post_fn())(
        state, cache, ctx)))
    pre_rounds_h = int(jax.device_get(pre_rounds))
    if pre_rounds_h:
        rounds["__prebalance__"] = pre_rounds_h

    from cruise_control_tpu.analyzer.proposals import diff_proposals
    proposals = diff_proposals(initial, state, topo,
                               np.asarray(ctx.partition_replicas))
    counts = {g.name: (int(b), own[g.name], int(a))
              for g, b, a in zip(goals, vb, va)}
    return dict(counts=counts, rounds=rounds, regressed=regressed,
                proposals=proposals, final_state=state)


def test_fused_reproduces_prefusion_path_on_config1():
    """Equivalence pin (config-1 differential fixture): the fused
    single-fetch pipeline and the eager pre-fusion driver agree on every
    instrument and on the proposal set."""
    state, topo = fixtures.small_cluster()
    options = OptimizationOptions()
    opt = GoalOptimizer(default_goals(max_rounds=24, names=GOAL_SUBSET),
                        pipeline_segment_size=2)
    fused = opt.optimizations(state, topo, options, check_sanity=False)
    ref = _unfused_reference_solve(opt, state, topo, options)

    assert fused.violated_broker_counts == ref["counts"]
    assert fused.rounds_by_goal == ref["rounds"]
    assert fused.regressed_goals == ref["regressed"]
    # proposals bitwise: same partitions, same placements, same leaders
    def key(p):
        return (p.partition.topic, p.partition.partition,
                tuple(r.broker_id for r in p.old_replicas),
                tuple(r.broker_id for r in p.new_replicas))
    assert sorted(map(key, fused.proposals)) == sorted(
        map(key, ref["proposals"]))
    assert np.array_equal(
        np.asarray(fused.final_state.replica_broker),
        np.asarray(ref["final_state"].replica_broker))


class _UnsatisfiableHardGoal(Goal):
    """Hard goal that never converges: every alive broker stays
    violated, its optimize is a no-op."""

    name = "UnsatisfiableHardGoal"
    is_hard = True

    def optimize_cached(self, state, ctx, prev_goals, cache=None):
        return state, cache

    def violated_brokers(self, state, ctx, cache):
        return state.broker_alive


def test_hard_goal_abort_deferred_and_eager():
    state, topo = fixtures.small_cluster()
    # deferred (default): the abort predicate is read from the single
    # end-of-solve fetch
    opt = GoalOptimizer([_UnsatisfiableHardGoal()])
    with pytest.raises(OptimizationFailure, match="still violated"):
        opt.optimizations(state, topo, check_sanity=False)
    # eager (opt-in): per-segment sync raises at the failing segment
    opt_eager = GoalOptimizer([_UnsatisfiableHardGoal()],
                              eager_hard_abort=True)
    with pytest.raises(OptimizationFailure, match="eager abort"):
        opt_eager.optimizations(state, topo, check_sanity=False)
    # per-call override beats the constructor default
    with pytest.raises(OptimizationFailure, match="eager abort"):
        opt.optimizations(state, topo, check_sanity=False,
                          eager_hard_abort=True)


def test_profile_mode_reports_same_instruments(monkeypatch):
    """CC_TPU_PROFILE=1 re-segments the pipeline per goal with sync
    points; instruments must match the fused run and the profiler must
    attribute every pipeline phase."""
    from cruise_control_tpu.utils import profiling

    state, topo = fixtures.small_cluster()
    names = ["RackAwareGoal", "DiskUsageDistributionGoal",
             "LeaderReplicaDistributionGoal"]
    fused = GoalOptimizer(default_goals(max_rounds=16, names=names),
                          pipeline_segment_size=2).optimizations(
        state, topo, check_sanity=False)

    monkeypatch.setenv(profiling.PROFILE_ENV, "1")
    prof = profiling.install()
    try:
        profiled = GoalOptimizer(
            default_goals(max_rounds=16, names=names),
            pipeline_segment_size=2).optimizations(
            state, topo, check_sanity=False)
    finally:
        profiling.uninstall()

    assert profiled.violated_broker_counts == fused.violated_broker_counts
    assert profiled.rounds_by_goal == fused.rounds_by_goal
    assert ([(p.partition.topic, p.partition.partition)
             for p in profiled.proposals]
            == [(p.partition.topic, p.partition.partition)
                for p in fused.proposals])

    cats = {r.category for r in prof.records}
    assert {"prebalance", "rounds", "leadership", "stats",
            "transfer", "diff"} <= cats
    names_recorded = {r.name for r in prof.records}
    for n in names:
        assert f"goal:{n}:rounds" in names_recorded
        assert f"goal:{n}:stats" in names_recorded
    table = prof.table()
    assert "total rounds" in table and "instrument fetch" in table
