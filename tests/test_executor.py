"""Executor plane tests against the simulated cluster.

Models the reference's ExecutorTest (reference cruise-control/src/test/...
/executor/ExecutorTest.java, 517 LoC, run against embedded Kafka+ZK):
task lifecycle, phased execution, concurrency caps, dead-destination
handling, and stop semantics — here against the in-process SimulatedCluster
with a virtual clock driven by the executor's own sleeps.
"""
import conftest  # noqa: F401

from cruise_control_tpu.analyzer.proposals import (ExecutionProposal,
                                                   ReplicaPlacement)
from cruise_control_tpu.cluster.simulated import SimulatedCluster
from cruise_control_tpu.cluster.types import TopicPartition
from cruise_control_tpu.executor import (
    Executor, ExecutionTaskPlanner, ExecutorPhase,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeSmallReplicaMovementStrategy, TaskState, TaskType,
    strategy_from_names)
from cruise_control_tpu.model.builder import PartitionId


def _proposal(topic, part, old, new, old_leader=None, size=0.0,
              logdirs_old=None, logdirs_new=None):
    olds = tuple(ReplicaPlacement(b, (logdirs_old or {}).get(b))
                 for b in old)
    news = tuple(ReplicaPlacement(b, (logdirs_new or {}).get(b))
                 for b in new)
    return ExecutionProposal(
        partition=PartitionId(topic, part),
        old_leader=old_leader if old_leader is not None else old[0],
        old_replicas=olds, new_replicas=news, partition_size=size)


def _sim(num_brokers=4, logdirs=("/d0",)):
    sim = SimulatedCluster()  # virtual clock
    for b in range(num_brokers):
        sim.add_broker(b, rack=f"r{b % 2}", logdirs=logdirs)
    return sim


def _executor(sim, **kw):
    kw.setdefault("progress_check_interval_s", 1.0)
    return Executor(sim, time_fn=lambda: sim.now_ms() / 1000.0,
                    sleep_fn=sim.advance, **kw)


class TestPlanner:
    def test_task_decomposition(self):
        planner = ExecutionTaskPlanner()
        planner.add_proposals([
            _proposal("t", 0, [0, 1], [2, 1]),            # replica move
            _proposal("t", 1, [0, 1], [1, 0]),            # pure leader move
            _proposal("t", 2, [0, 1], [0, 1],             # logdir move
                      logdirs_old={0: "/d0"}, logdirs_new={0: "/d1"}),
        ])
        assert len(planner.remaining_inter_broker_tasks) == 1
        assert len(planner.remaining_leadership_tasks) == 2  # t-0 and t-1
        assert len(planner.remaining_intra_broker_tasks) == 1

    def test_replica_move_with_leader_change_gets_both_tasks(self):
        planner = ExecutionTaskPlanner()
        planner.add_proposals([_proposal("t", 0, [0, 1], [2, 1],
                                         old_leader=0)])
        assert len(planner.remaining_inter_broker_tasks) == 1
        assert len(planner.remaining_leadership_tasks) == 1

    def test_concurrency_slots(self):
        planner = ExecutionTaskPlanner()
        planner.add_proposals([
            _proposal("t", 0, [0], [1]),
            _proposal("t", 1, [0], [1]),
            _proposal("t", 2, [2], [3]),
        ])
        # 1 slot per broker: t-0 takes brokers {0,1}; t-1 blocked; t-2 free
        picked = planner.pop_inter_broker_tasks({0: 1, 1: 1, 2: 1, 3: 1})
        tps = {t.proposal.partition.partition for t in picked}
        assert tps == {0, 2}


class TestStrategies:
    def test_ordering(self):
        planner_small = ExecutionTaskPlanner(
            PrioritizeSmallReplicaMovementStrategy())
        planner_small.add_proposals([
            _proposal("t", 0, [0], [1], size=100.0),
            _proposal("t", 1, [0], [1], size=1.0),
        ])
        order = [t.proposal.partition.partition
                 for t in planner_small.remaining_inter_broker_tasks]
        assert order == [1, 0]

        planner_large = ExecutionTaskPlanner(
            PrioritizeLargeReplicaMovementStrategy())
        planner_large.add_proposals([
            _proposal("t", 0, [0], [1], size=100.0),
            _proposal("t", 1, [0], [1], size=1.0),
        ])
        order = [t.proposal.partition.partition
                 for t in planner_large.remaining_inter_broker_tasks]
        assert order == [0, 1]

    def test_strategy_from_names(self):
        s = strategy_from_names(["PrioritizeSmallReplicaMovementStrategy"])
        assert s.name() == "PrioritizeSmallReplicaMovementStrategy"


class TestExecutionEndToEnd:
    def test_replica_and_leader_movement(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1], [1, 2]], size_bytes=50e6)
        ex = _executor(sim)
        proposals = [
            _proposal("t", 0, [0, 1], [2, 1], old_leader=0, size=50e6),
            _proposal("t", 1, [1, 2], [2, 1], old_leader=1, size=50e6),
        ]
        ex.execute_proposals(proposals, reason="test", wait=True)
        snap = sim.describe_cluster()
        p0 = snap.partition(TopicPartition("t", 0))
        p1 = snap.partition(TopicPartition("t", 1))
        assert set(p0.replicas) == {1, 2} and p0.leader == 2
        assert set(p1.replicas) == {1, 2} and p1.leader == 2
        assert ex.state.phase == ExecutorPhase.NO_TASK_IN_PROGRESS
        assert not ex.has_ongoing_execution

    def test_progress_counters_and_notifier(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=10e6)
        finished = []

        class Notifier:
            def on_execution_finished(self, uuid, ok, msg):
                finished.append((uuid, ok, msg))

        ex = _executor(sim, notifier=Notifier())
        uuid = ex.execute_proposals(
            [_proposal("t", 0, [0, 1], [2, 1], size=10e6)], wait=True)
        assert finished == [(uuid, True, "execution completed")]

    def test_dead_destination_broker_kills_task(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=10e6)
        sim.kill_broker(3)
        ex = _executor(sim)
        ex.execute_proposals(
            [_proposal("t", 0, [0, 1], [3, 1], size=10e6)], wait=True)
        snap = sim.describe_cluster()
        # task should be DEAD, replicas unchanged
        assert set(snap.partition(TopicPartition("t", 0)).replicas) == {0, 1}

    def test_concurrent_execution_rejected(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=1e12)  # slow move
        ex = _executor(sim)
        ex.execute_proposals([_proposal("t", 0, [0, 1], [2, 1], size=1e12)])
        try:
            import pytest
            with pytest.raises(RuntimeError):
                ex.execute_proposals(
                    [_proposal("t", 0, [0, 1], [3, 1], size=1e12)])
        finally:
            ex.stop_execution(force=True)
            assert ex.await_completion(timeout=30.0)

    def test_force_stop_cancels_reassignment(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=1e12)
        ex = _executor(sim)
        # trip the stop from inside the executor's own sleep so the test is
        # deterministic under the virtual clock
        calls = []
        orig_sleep = ex._sleep

        def stopping_sleep(s):
            calls.append(s)
            if len(calls) == 1:
                ex.stop_execution(force=True)
            orig_sleep(s)
        ex._sleep = stopping_sleep
        ex.execute_proposals([_proposal("t", 0, [0, 1], [2, 1], size=1e12)],
                             wait=True)
        assert sim.list_partition_reassignments() == []
        snap = sim.describe_cluster()
        assert set(snap.partition(TopicPartition("t", 0)).replicas) == {0, 1}
        assert ex.state.phase == ExecutorPhase.NO_TASK_IN_PROGRESS

    def test_throttle_applied_and_cleared(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=100e6)
        ex = _executor(sim, replication_throttle_bytes_per_s=10e6)
        ex.execute_proposals([_proposal("t", 0, [0, 1], [2, 1], size=100e6)],
                             wait=True)
        # finished despite throttle; throttles cleared afterwards
        snap = sim.describe_cluster()
        assert set(snap.partition(TopicPartition("t", 0)).replicas) == {1, 2}
        assert all(b.throttle is None for b in sim._brokers.values())

    def test_intra_broker_logdir_move(self):
        sim = _sim(logdirs=("/d0", "/d1"))
        sim.create_topic("t", [[0, 1]], size_bytes=10e6)
        ex = _executor(sim)
        ex.execute_proposals([
            _proposal("t", 0, [0, 1], [0, 1],
                      logdirs_old={0: "/d0"}, logdirs_new={0: "/d1"},
                      size=10e6)], wait=True)
        snap = sim.describe_cluster()
        assert snap.partition(
            TopicPartition("t", 0)).logdir_by_broker[0] == "/d1"

    def test_removal_history(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=1e6)
        ex = _executor(sim)
        ex.execute_proposals([_proposal("t", 0, [0, 1], [2, 1], size=1e6)],
                             removed_brokers=[0], demoted_brokers=[1],
                             wait=True)
        assert ex.recently_removed_brokers() == {0}
        assert ex.recently_demoted_brokers() == {1}
        ex.drop_recently_removed_brokers([0])
        assert ex.recently_removed_brokers() == set()


class TestTaskStateMachine:
    def test_illegal_transition_raises(self):
        import pytest
        from cruise_control_tpu.executor.task import ExecutionTask
        t = ExecutionTask(ExecutionTask.next_id(),
                          _proposal("t", 0, [0], [1]),
                          TaskType.INTER_BROKER_REPLICA_ACTION)
        with pytest.raises(ValueError):
            t.completed(0.0)  # PENDING -> COMPLETED is illegal
        t.in_progress(0.0)
        t.completed(1.0)
        assert t.done and t.state == TaskState.COMPLETED


class TestReviewRegressions:
    def test_slow_transfer_completes_without_reexecution(self):
        # transfer takes far longer than the idle budget: the executor must
        # wait it out, not reset progress by re-submitting
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=100e6)
        sim._move_rate = 1e6   # 100 s transfer
        ex = _executor(sim, max_task_execution_idle_s=5.0)
        ex.execute_proposals([_proposal("t", 0, [0, 1], [2, 1], size=100e6)],
                             wait=True)
        snap = sim.describe_cluster()
        assert set(snap.partition(TopicPartition("t", 0)).replicas) == {1, 2}
        task = [t for t in ex._manager._planner.all_tasks()
                if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION][0]
        assert task.reexecution_count == 0

    def test_lost_reassignment_is_reexecuted(self):
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=100e6)
        sim._move_rate = 10e6
        ex = _executor(sim)
        # cancel the reassignment out from under the executor once,
        # from inside its own sleep (deterministic under virtual time)
        cancelled = []
        orig_sleep = ex._sleep

        def sabotaging_sleep(s):
            orig_sleep(s)
            if not cancelled and sim.list_partition_reassignments():
                sim.alter_partition_reassignments(
                    {TopicPartition("t", 0): None})
                cancelled.append(True)
        ex._sleep = sabotaging_sleep
        ex.execute_proposals([_proposal("t", 0, [0, 1], [2, 1], size=100e6)],
                             wait=True)
        snap = sim.describe_cluster()
        assert set(snap.partition(TopicPartition("t", 0)).replicas) == {1, 2}
        task = [t for t in ex._manager._planner.all_tasks()
                if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION][0]
        assert task.reexecution_count >= 1


class TestFaultInjection:
    """Executor behavior under scripted admin-client failures
    (utils/faults.py harness, sites `executor.admin.<op>`): progress
    polls tolerate transient faults, stuck-task re-execution survives a
    failed re-submit, and a dead election path lands on the
    leader-movement timeout instead of wedging or crashing."""

    def test_poll_survives_transient_describe_faults(self):
        from cruise_control_tpu.utils import faults
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=50e6)
        sim._move_rate = 10e6   # several poll intervals to finish
        ex = _executor(sim)
        # calls 3-4 of describe_cluster are the first progress polls
        # (call 1: execute_proposals snapshot, call 2: the submit-path
        # alive-broker check, which stays fail-fast by design)
        plan = faults.FaultPlan().fail_nth(
            "executor.admin.describe_cluster", (3, 4))
        with faults.injected(plan):
            ex.execute_proposals(
                [_proposal("t", 0, [0, 1], [2, 1], size=50e6)], wait=True)
        snap = sim.describe_cluster()
        assert set(snap.partition(TopicPartition("t", 0)).replicas) == {1, 2}
        assert ex.num_poll_failures_tolerated >= 1

    def test_stuck_task_reexecution_survives_failed_resubmit(self):
        from cruise_control_tpu.utils import faults
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=100e6)
        sim._move_rate = 10e6
        ex = _executor(sim)
        # cancel the reassignment out from under the executor once (the
        # stuck-task condition), from inside its own sleep
        cancelled = []
        orig_sleep = ex._sleep

        def sabotaging_sleep(s):
            orig_sleep(s)
            if not cancelled and sim.list_partition_reassignments():
                sim.alter_partition_reassignments(
                    {TopicPartition("t", 0): None})
                cancelled.append(True)
        ex._sleep = sabotaging_sleep
        # the FIRST re-submit attempt (alter call 2: call 1 is the
        # original submission) also fails — the poll must tolerate it
        # and re-execute on a later poll instead of failing the run
        plan = faults.FaultPlan().fail_nth(
            "executor.admin.alter_partition_reassignments", 2)
        with faults.injected(plan):
            ex.execute_proposals(
                [_proposal("t", 0, [0, 1], [2, 1], size=100e6)], wait=True)
        snap = sim.describe_cluster()
        assert set(snap.partition(TopicPartition("t", 0)).replicas) == {1, 2}
        task = [t for t in ex._manager._planner.all_tasks()
                if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION][0]
        assert task.state == TaskState.COMPLETED
        assert task.reexecution_count >= 1
        assert ex.num_poll_failures_tolerated >= 1

    def test_leader_movement_timeout_under_election_faults(self):
        from cruise_control_tpu.utils import faults
        sim = _sim()
        sim.create_topic("t", [[0, 1]], size_bytes=1e6)
        ex = _executor(sim, leader_movement_timeout_s=5.0)
        finished = []

        class Notifier:
            def on_execution_finished(self, uuid, ok, msg):
                finished.append((ok, msg))

        ex._notifier = Notifier()
        # every election request fails: leadership can never move, so
        # the leader-movement timeout must mark the tasks DEAD and the
        # execution must still complete (not crash, not hang)
        plan = faults.FaultPlan().fail_always(
            "executor.admin.elect_preferred_leaders")
        with faults.injected(plan):
            ex.execute_proposals(
                [_proposal("t", 0, [0, 1], [1, 0], old_leader=0)],
                wait=True)
        snap = sim.describe_cluster()
        assert snap.partition(TopicPartition("t", 0)).leader == 0
        leader_tasks = [t for t in ex._manager._planner.all_tasks()
                        if t.task_type == TaskType.LEADER_ACTION]
        assert leader_tasks and all(t.state == TaskState.DEAD
                                    for t in leader_tasks)
        assert finished == [(True, "execution completed")]
        assert ex.num_poll_failures_tolerated >= 1
