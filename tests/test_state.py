"""Tests for the tensorized cluster model: load accounting, mutation ops,
sanity invariants (mirrors what the reference asserts via
ClusterModel.sanityCheck and its model unit tests)."""
import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource as R
from cruise_control_tpu.model import state as S
from cruise_control_tpu.model.sanity import sanity_check
from cruise_control_tpu.model.stats import compute_stats
from cruise_control_tpu.testing import fixtures
from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                       random_cluster)


def test_small_cluster_broker_loads():
    state, topo = fixtures.small_cluster()
    sanity_check(state)
    load = np.asarray(S.broker_load(state))
    # broker 0: leader T1-0 (NW_OUT 130) + follower T2-0 (NW_OUT 0)
    assert load[0, R.NW_OUT] == pytest.approx(130.0)
    assert load[1, R.NW_OUT] == pytest.approx(110.0)
    assert load[2, R.NW_OUT] == pytest.approx(80.0)
    # disk: current-role load is same for leader/follower
    assert load[0, R.DISK] == pytest.approx(75.0 + 45.0)
    assert load[1, R.DISK] == pytest.approx(55.0 + 75.0)
    assert load[2, R.DISK] == pytest.approx(45.0 + 55.0)
    # NW_IN is replicated: every replica carries the partition bytes-in
    assert load[0, R.NW_IN] == pytest.approx(100.0 + 60.0)


def test_counts_and_topology_queries():
    state, topo = fixtures.small_cluster()
    assert np.asarray(S.broker_replica_count(state)).tolist() == [2, 2, 2]
    assert np.asarray(S.broker_leader_count(state)).tolist() == [1, 1, 1]
    prc = np.asarray(S.partition_rack_count(state))
    # T1-0 on b0,b1 → both rack A(0)
    assert prc[0, 0] == 2 and prc[0, 1] == 0
    rf = np.asarray(S.partition_replication_factor(state))
    assert rf.tolist() == [2, 2, 2]
    leaders = np.asarray(S.partition_leader_replica(state))
    assert (leaders >= 0).all()


def test_move_replica_conserves_load():
    state, _ = fixtures.small_cluster()
    total_before = np.asarray(S.cluster_load(state))
    # move follower of T2-0 (replica on broker 0) to broker 1
    r = 4  # T2-0 leader is index 4? find follower on broker 0
    broker = np.asarray(state.replica_broker)
    part = np.asarray(state.replica_partition)
    lead = np.asarray(state.replica_is_leader)
    idx = int(np.nonzero((part == 2) & ~lead)[0][0])
    import jax.numpy as jnp
    state2 = S.move_replica(state, jnp.asarray(idx), jnp.asarray(1))
    sanity_check(state2)
    total_after = np.asarray(S.cluster_load(state2))
    np.testing.assert_allclose(total_before, total_after, rtol=1e-6)
    assert int(np.asarray(state2.replica_broker)[idx]) == 1


def test_leadership_transfer_moves_bonus():
    state, _ = fixtures.small_cluster()
    import jax.numpy as jnp
    part = np.asarray(state.replica_partition)
    lead = np.asarray(state.replica_is_leader)
    src = int(np.nonzero((part == 0) & lead)[0][0])
    dst = int(np.nonzero((part == 0) & ~lead)[0][0])
    src_broker = int(np.asarray(state.replica_broker)[src])
    dst_broker = int(np.asarray(state.replica_broker)[dst])
    before = np.asarray(S.broker_load(state))
    state2 = S.transfer_leadership(state, jnp.asarray(src), jnp.asarray(dst))
    sanity_check(state2)
    after = np.asarray(S.broker_load(state2))
    # NW_OUT of the partition (130) moved between brokers
    assert after[src_broker, R.NW_OUT] == pytest.approx(
        before[src_broker, R.NW_OUT] - 130.0)
    assert after[dst_broker, R.NW_OUT] == pytest.approx(
        before[dst_broker, R.NW_OUT] + 130.0)
    # totals conserved
    np.testing.assert_allclose(before.sum(0), after.sum(0), rtol=1e-6)


def test_dead_broker_marks_offline():
    state, _ = fixtures.dead_broker_cluster()
    sanity_check(state)
    offline = np.asarray(S.self_healing_eligible(state))
    broker = np.asarray(state.replica_broker)
    assert (offline == (broker == 2)).all()


def test_kill_broker_dynamically():
    state, _ = fixtures.small_cluster()
    state2 = S.set_broker_state(state, 1, alive=False)
    sanity_check(state2)
    offline = np.asarray(S.self_healing_eligible(state2))
    broker = np.asarray(state2.replica_broker)
    assert (offline == (broker == 1)).all()


def test_jbod_disk_loads_and_dead_disk():
    state, topo = fixtures.jbod_cluster()
    sanity_check(state)
    dl = np.asarray(S.disk_load(state))
    assert dl.sum() == pytest.approx(800.0)  # 4 replicas x 200
    # broker 0's /d1 is broken (capacity -1): flagged bad_disks
    assert bool(np.asarray(state.broker_bad_disks)[0])
    # break broker 1's /d1 (disk index 3)
    d1_idx = topo.disk_names.index((1, "/d1"))
    state2 = S.mark_disk_dead(state, d1_idx)
    offline = np.asarray(state2.replica_offline)
    on_disk = np.asarray(state2.replica_disk) == d1_idx
    assert (offline >= on_disk).all() and on_disk.sum() == 1


def test_stats_and_utilization_matrix():
    state, _ = fixtures.unbalanced_cluster()
    stats = compute_stats(state)
    util = np.asarray(S.utilization_matrix(state))
    assert util.shape == (4, 3)
    # broker 0 leads everything → max NW_OUT util is broker 0's
    assert float(stats.util_max[R.NW_OUT]) == pytest.approx(util[R.NW_OUT, 0])
    assert float(stats.util_std[R.NW_OUT]) > 0
    assert int(stats.num_replicas) == 12
    assert int(stats.num_alive_brokers) == 3


def test_batched_moves_noop_rows():
    state, _ = fixtures.small_cluster()
    import jax.numpy as jnp
    before = np.asarray(state.replica_broker).copy()
    # one real move (replica 0 -> broker 2 would duplicate? T1-0 is on b0,b1;
    # move to b2 is safe), one masked-out row
    state2 = S.apply_moves(state, jnp.asarray([0, 1]), jnp.asarray([2, 2]),
                           jnp.asarray([True, False]))
    after = np.asarray(state2.replica_broker)
    assert after[0] == 2
    assert after[1] == before[1]
    sanity_check(state2)


def test_random_cluster_generation_and_sanity():
    spec = RandomClusterSpec(num_brokers=20, num_partitions=200,
                             replication_factor=3, num_racks=4,
                             num_topics=8, seed=7)
    state, topo = random_cluster(spec)
    sanity_check(state)
    assert state.num_replicas == 600
    # every partition has rf distinct brokers
    pbc = np.asarray(S.partition_broker_count(state))
    assert pbc.max() == 1
    # capacity sized so average utilization ≈ 1/margin
    avg_util = np.asarray(S.average_utilization_percentage(state))
    assert 0.3 < avg_util[R.NW_IN] < 0.7


def test_random_cluster_dead_and_new_brokers():
    spec = RandomClusterSpec(num_brokers=20, num_partitions=100,
                             dead_brokers=2, new_brokers=3, seed=3)
    state, _ = random_cluster(spec)
    sanity_check(state)
    assert int(np.asarray(state.broker_alive).sum()) == 21
    assert int(np.asarray(state.broker_new).sum()) == 3
    # new brokers hold nothing
    counts = np.asarray(S.broker_replica_count(state))
    assert (counts[20:] == 0).all()
