"""Benchmark: full multi-goal rebalance proposal generation.

North-star config (BASELINE.json): 2,600 brokers / 200K partitions, full
default goal stack, target < 5 s wall-clock on TPU — ≥30× the reference's
CPU GoalOptimizer.  Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}
`vs_baseline` is target_seconds / measured_seconds (>1 beats the 5 s
north-star target).

BENCH_CONFIG selects a BASELINE.json eval config:
  north (default)  2600b/200Kp, full default goal stack
  1                3-broker/30-partition deterministic fixture
  2                200b/20Kp, resource-distribution goals only
  3                1000b/80Kp, full hard+soft stack
  4                2600b/200Kp add-broker + remove-broker operations
  5                2600b JBOD (4 logdirs/broker, broken disks) with
                   DiskUsageDistributionGoal + offline-replica self-healing
  scenario         batched what-if engine (scenario/engine.py): solves
                   K = BENCH_SCENARIO_BATCHES (default 1,8,32) scenario
                   variants per vmapped program and records per-batch
                   compile + solve latency, so the one-compile-amortized-
                   over-K claim is MEASURED (the output JSON carries a
                   "scenario" block; value = per-scenario solve seconds
                   at the largest K, vs_baseline = K=1-per-scenario /
                   largest-K-per-scenario, >1 = batching wins)
  portfolio        device-parallel portfolio search (portfolio/): for
                   each K in BENCH_PORTFOLIO_KS (default 1,8,32) builds
                   a seeded K-candidate perturbation portfolio over the
                   greedy solve's goal stack (mutate.py) and solves ALL
                   lanes in one batched FUSED pass (engine.py), vs the
                   single greedy GoalOptimizer solve on the same pinned
                   48b/1.5Kp fixture.  EXITS 1 unless the K=1 portfolio
                   is byte-identical to greedy (the identity pin), the
                   winner is never worse than greedy at every K, and
                   the errors are clean (the output JSON carries a
                   "portfolio" block; value = best balancedness gain
                   over greedy at K>=8, vs_baseline = winner fitness /
                   greedy fitness at the largest K, >1 = the
                   population beats the single solver).  Knobs:
                   BENCH_PORTFOLIO_SEED, BENCH_PORTFOLIO_WEIGHT
                   (movement-cost weight), BENCH_PORTFOLIO_PROGRAMS
                   (max distinct goal orders per portfolio)
  fleet            shape-bucketed fleet serving (fleet/buckets.py):
                   K = BENCH_FLEET_TENANTS (default 1,4,16) tenants with
                   DIFFERENT broker counts inside one power-of-two
                   bucket solve through ONE shared goal stack, bucketed
                   (every tenant padded to the bucket -> one compiled
                   program set) vs the 16-separate-facades baseline
                   (each raw shape compiles its own programs); records
                   per-solve latency and COMPILE COUNT per mode (the
                   output JSON carries a "fleet" block; value = bucketed
                   warm per-solve seconds at the largest K, vs_baseline
                   = unbucketed compile count / bucketed compile count,
                   >1 = program sharing is sublinear in tenants), and
                   verifies per-tenant proposals are identical bucketed
                   vs raw
  mesh             mesh-scaled full-stack solve (parallel/mesh.py): the
                   north-star model solved over 1/2/4/8 devices
                   (BENCH_MESH_DEVICES, clipped to the visible device
                   count) through the SAME production pipeline, each
                   mesh size AOT-warmed then measured, with per-segment
                   profiler category attribution recorded per mesh size
                   (the output JSON carries a "mesh" block; value =
                   solve seconds at the largest mesh, vs_baseline =
                   mesh1 / largest-mesh, >1 = the mesh wins)
  sched            device-time scheduler (sched/): N concurrent mixed
                   clients (N = BENCH_SCHED_CLIENTS, default 1,8,32;
                   USER_INTERACTIVE / PRECOMPUTE round-robin with
                   repeated identical requests in the mix) submit
                   BENCH_SCHED_REQUESTS solves each, scheduled vs the
                   unscheduled free-for-all; records p50/p99 end-to-end
                   latency + device occupancy per N (the output JSON
                   carries a "sched" block; value = scheduled p99 at the
                   largest N, vs_baseline = unscheduled p99 / scheduled
                   p99, >1 = the scheduler wins via coalescing +
                   ordering)

  incremental      device-resident incremental workload model
                   (model/store.py + monitor/deltas.py): one live
                   facade stack serves a BENCH_INCR_DELTAS-long
                   (default 64) interactive delta stream (single-broker
                   capacity changes + hot-partition load updates), each
                   delta followed by a USER_INTERACTIVE rebalance —
                   store-served, warm-started, dirty-region-restricted
                   — vs a twin facade with incremental.enabled=false
                   paying the full re-materialize + full-sweep per
                   request.  Records p50/p99 per path, store
                   hit/fallback/delta-apply counts and dirty sizes.
                   EXITS 1 unless (a) the single-broker-delta p50 is
                   >= 5x faster through the store than the full path
                   and (b) the delta-applied resident model is
                   byte-identical to a from-scratch rebuild after the
                   whole stream (the output JSON carries an
                   "incremental" block; value = incremental p50
                   seconds, vs_baseline = full p50 / incremental p50)

  meshchaos        elastic mesh recovery (parallel/health.py): a live
                   facade stack on the 8-device mesh takes an injected
                   collective HANG mid-solve; the watchdog must release
                   the dispatch thread within mesh.watchdog.ms
                   (BENCH_MESHCHAOS_WATCHDOG_MS, default 2000), the
                   supervisor shrinks the span 8->4, the re-queued
                   solve completes on the survivor span, and probe
                   recovery climbs back to 8.  Records wedge ->
                   first-good-solve latency and the watchdog release
                   time.  EXITS 1 if the dispatch thread ever blocked
                   past the deadline (2x grace), the solve failed, or
                   the span did not recover (the output JSON carries a
                   "meshchaos" block; value = wedge-to-first-good-solve
                   seconds, vs_baseline = clean solve / recovery, the
                   recovery tax)

  coldstart        persistent-program-cache cold start
                   (parallel/progcache.py): measures cold-process
                   time-to-first-proposal twice in FRESH subprocesses —
                   first with an EMPTY program cache (compiles + stores),
                   then with the warm cache (hydrates) — and reports
                   per-run warmup/solve seconds, progcache
                   hit/miss/store counts and bytes, plus a
                   proposal-digest equality check.  The warm run MUST
                   perform zero source-program compiles
                   (fresh_compiles == 0) and produce byte-identical
                   proposals, or the bench exits 1 (the output JSON
                   carries a "coldstart" block; value = warm
                   time-to-first-proposal seconds, vs_baseline =
                   cold / warm, >1 = the cache wins)

  soak             trace-replay load harness + SLO gate
                   (cruise_control_tpu/loadgen/ + tools/slo_gate.py):
                   serves an in-process demo rig and replays the
                   seeded `soak-mixed` profile (diurnal mixed-class
                   traffic: interactive rebalances, scenario sweeps,
                   precompute churn, heal storms, model-delta streams)
                   through the REST surface for BENCH_SOAK_SECONDS
                   (default 20) at BENCH_SOAK_RPS (default 3), seed
                   BENCH_SOAK_SEED; emits the run ARTIFACT (per-class
                   p50/p99/p99.9 + queue-wait vs device-time
                   decomposition from real span trees + 429/occupancy/
                   coalesce counts + sloStatus) to
                   BENCH_SOAK_ARTIFACT (default .soak/artifact.json),
                   self-baselines it, and EXITS 1 unless
                   tools/slo_gate.py passes the clean run against its
                   own baseline AND fails a second run with an
                   injected sched.dispatch latency fault
                   (BENCH_SOAK_FAULT_S, default 2.0) — proving the
                   gate actually gates (the output JSON carries a
                   "soak" block; value = clean USER_INTERACTIVE p99
                   seconds, vs_baseline = faulted p99 / clean p99, the
                   regression the gate caught)

Other knobs: BENCH_BROKERS, BENCH_PARTITIONS, BENCH_RF, BENCH_ROUNDS,
BENCH_GOALS (comma list), BENCH_SEGMENT, BENCH_SKIP_WARMUP.

Dispatch-budget knobs (ISSUE 16): BENCH_FUSION=1 fuses same-group goal
programs into megaprograms (analyzer/fusion.py — 15-goal default stack:
3 segment programs instead of 8 at BENCH_SEGMENT=2), BENCH_HOST_SKIP=1
elides whole segment dispatches whose member goals all report no work,
BENCH_PRECISION=bfloat16 narrows the float load/capacity tables
(analyzer/precision.py) and gates the result against an f32 baseline
solve (exit 1 on gate failure; BENCH_PRECISION_EPS /
BENCH_PRECISION_OVERLAP tune the gate).  The headline JSON reports
`device_dispatches`, `dispatches_by_program`, `solver_goals_skipped`
and `converged_at_by_goal` either way.

BENCH_PROGCACHE governs the persistent program cache for the headline
run: unset = ".progcache" next to this file, a path = that directory,
"0"/"off" = disabled.  The headline JSON reports `warmup_s` and
`progcache_hits` either way, so the ~300s cold-start number is tracked
per round instead of living only in the log tail.

BENCH_MESH governs the headline device topology: unset/auto = solve
over ALL visible devices when the backend is not CPU (the v5e-8 path;
CPU multi-device = the virtual test rig, which stays single-chip),
"0"/"off" = force single-chip, N = clip the mesh to the first N
devices (works on the CPU rig too, for local checks).  The headline
JSON reports `n_devices` + `mesh` shape either way, so BENCH_r06+ is
attributable to the topology that produced it.

The headline bench FAILS LOUDLY (stderr ERROR + "goal_self_regressions"
in the JSON + exit code 1) when any goal's own pass worsened its own
violated-broker count (after-own > at-entry) — the silent
LeaderBytesInDistributionGoal drift of BENCH_r04/r05.

CC_TPU_PROFILE=1 (or legacy BENCH_PROFILE=1) enables the segment-level
profiler: per-goal programs with explicit sync points, emitting the
per-segment attribution table (prebalance / per-goal rounds / stats
epilogues / leadership / diff / transfer) on stderr — see
cruise_control_tpu/utils/profiling.py and tools/profile_segments.py.
"""
import json
import os
import sys
import time

TARGET_SECONDS = 5.0


def _pct(values, q):
    """Nearest-rank percentile (shared by the sched and incremental
    latency benches)."""
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1,
                       int(round(q * (len(ordered) - 1))))]


def _with_trace_summary(out: dict, cluster=None) -> dict:
    """Attach the run's per-phase trace attribution (obs/ flight
    recorder: slowest + median request broken into span durations) to a
    bench artifact, so every BENCH_r* round carries WHERE the time went,
    not just totals.  `cluster` restricts to one facade's traces (the
    incremental bench runs a baseline twin whose full re-solves must
    not pose as the measured path's slowest request).  Never fails the
    bench."""
    try:
        from cruise_control_tpu.obs import recorder as obs_recorder
        traces = obs_recorder.get_recorder().snapshot()
        if cluster is not None:
            traces = [t for t in traces
                      if t.get("tags", {}).get("cluster") == cluster]
        out["trace_summary"] = obs_recorder.phase_summary(traces)
    except Exception as exc:  # noqa: BLE001 - attribution is a bonus
        print(f"# trace summary unavailable: {exc}", file=sys.stderr)
    return out


def _reset_traces():
    """Drop every trace recorded so far (warmup / compile passes /
    baseline runs) so the artifact's trace_summary attributes ONLY the
    measured pass that follows."""
    from cruise_control_tpu.obs import recorder as obs_recorder
    obs_recorder.install()


# persistent compile cache: segment programs at 2.6K-broker scale take
# minutes to compile; retries and re-runs must not pay that twice
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def _configure_progcache():
    """Wire the persistent program cache from BENCH_PROGCACHE (see the
    module docstring); returns the cache (disabled cache when off)."""
    from cruise_control_tpu.parallel import progcache
    raw = os.environ.get("BENCH_PROGCACHE", "").strip()
    if raw.lower() in ("0", "off", "false", "none"):
        progcache.configure(enabled=False)
        return progcache.get_cache()
    path = raw or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".progcache")
    return progcache.configure(enabled=True, cache_dir=path)


def _resolve_mesh(jax, raw=None):
    """Headline solve mesh from BENCH_MESH (None = single-chip):
    auto = all visible devices on non-CPU backends, N = first N devices
    (any backend), 0/off = disabled.  See the module docstring."""
    from cruise_control_tpu.parallel.mesh import make_mesh
    raw = (os.environ.get("BENCH_MESH", "") if raw is None
           else raw).strip().lower()
    devices = jax.devices()
    if raw in ("0", "1", "off", "false", "none"):
        return None
    if raw in ("", "auto"):
        if devices[0].platform == "cpu" or len(devices) < 2:
            return None
        return make_mesh(devices)
    n = min(int(raw), len(devices))
    return make_mesh(devices[:n]) if n >= 2 else None


def _self_regressions(results):
    """{goal: {entry, own, before}} for every goal whose OWN pass
    worsened its own violated-broker count (after-own > at-entry) in
    any measured result — the loud-failure food."""
    out = {}
    for r in results:
        entries = getattr(r, "entry_broker_counts", {}) or {}
        for g, (b, own, _a) in r.violated_broker_counts.items():
            e = entries.get(g, b)
            if own > e:
                out[g] = {"entry": int(e), "own": int(own),
                          "before": int(b)}
    return out


def _build(config, num_b, num_p, rf, seed=4):
    from cruise_control_tpu.testing.fixtures import small_cluster
    from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                           random_cluster)
    if config == "1":
        return small_cluster()
    kwargs = {}
    if config == "4":
        kwargs["new_brokers"] = max(1, num_b // 20)
    if config == "5":
        kwargs.update(jbod_disks=4, dead_disks=max(1, num_b // 50))
    return random_cluster(RandomClusterSpec(
        num_brokers=num_b, num_partitions=num_p, replication_factor=rf,
        num_racks=max(8, num_b // 100), num_topics=max(8, num_p // 2000),
        seed=seed, skew_fraction=0.2, **kwargs))


def main() -> None:
    t_import = time.time()
    import jax

    # a platform hook (sitecustomize) may have imported jax BEFORE this
    # process set the cache env vars above, in which case they were never
    # read — apply the config directly (backends initialize lazily, so
    # this still takes effect)
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.model import state as S

    config = os.environ.get("BENCH_CONFIG", "north")
    if config == "soak":
        return _soak_bench()
    if config == "scenario":
        return _scenario_bench()
    if config == "portfolio":
        return _portfolio_bench()
    if config == "sched":
        return _sched_bench()
    if config == "fleet":
        return _fleet_bench()
    if config == "mesh":
        return _mesh_bench()
    if config == "coldstart":
        return _coldstart_bench()
    if config == "meshchaos":
        return _meshchaos_bench()
    if config == "incremental":
        return _incremental_bench()
    presets = {  # (brokers, partitions, goal subset, metric label)
        "north": (2600, 200_000, None, "full-stack proposal generation"),
        "1": (3, 30, None, "deterministic fixture"),
        "2": (200, 20_000, ["DiskUsageDistributionGoal",
                            "NetworkInboundUsageDistributionGoal",
                            "NetworkOutboundUsageDistributionGoal",
                            "CpuUsageDistributionGoal"],
              "resource-distribution goals"),
        "3": (1000, 80_000, None, "full-stack proposal generation"),
        "4": (2600, 200_000, None, "add-broker + remove-broker"),
        "5": (2600, 200_000, ["DiskCapacityGoal",
                              "DiskUsageDistributionGoal"],
              "JBOD self-healing + disk distribution"),
    }
    if config not in presets:
        sys.exit(f"unknown BENCH_CONFIG={config!r}; "
                 f"valid: {sorted(presets)}")
    d_b, d_p, d_goals, label = presets[config]
    num_b = int(os.environ.get("BENCH_BROKERS", d_b))
    num_p = int(os.environ.get("BENCH_PARTITIONS", d_p))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 192))
    goal_names = os.environ.get("BENCH_GOALS")
    names = goal_names.split(",") if goal_names else d_goals

    backend = jax.devices()[0].platform
    print(f"# config={config} backend={backend} devices={jax.devices()} "
          f"(import+init {time.time()-t_import:.1f}s)", file=sys.stderr)

    t0 = time.time()
    state, topo = _build(config, num_b, num_p, rf)
    print(f"# model built: B={state.num_brokers} P={state.num_partitions} "
          f"R={state.num_replicas} ({time.time()-t0:.1f}s)", file=sys.stderr)

    goals = default_goals(max_rounds=rounds, names=names)
    segment = int(os.environ.get("BENCH_SEGMENT", 2))
    # dispatch-budget knobs (ISSUE 16): BENCH_FUSION=1 fuses same-group
    # goal programs into megaprograms (analyzer/fusion.py), BENCH_HOST_SKIP=1
    # elides no-work segment dispatches host-side, BENCH_PRECISION=bfloat16
    # narrows the float load/capacity tables (analyzer/precision.py) —
    # a bf16 run ALSO solves the f32 baseline and must pass the
    # proposals-equivalence gate or the bench exits 1
    fused = os.environ.get("BENCH_FUSION", "") not in ("", "0")
    host_skip = os.environ.get("BENCH_HOST_SKIP", "") not in ("", "0")
    precision = os.environ.get("BENCH_PRECISION", "float32") or "float32"
    optimizer = GoalOptimizer(goals, pipeline_segment_size=segment,
                              fused_segments=fused,
                              host_side_skip=host_skip)
    state_f32 = state
    if precision != "float32":
        from cruise_control_tpu.analyzer.precision import cast_state_tables
        state = cast_state_tables(state, precision)
    progcache = _configure_progcache()
    print(f"# progcache: {progcache.stats()['dir'] or 'disabled'}",
          file=sys.stderr)
    mesh = _resolve_mesh(jax)
    n_devices = mesh.size if mesh is not None else 1
    print(f"# solve mesh: {n_devices} device(s)"
          + (f" over ('replica',) [{mesh.devices.flat[0].platform}]"
             if mesh is not None else " (single-chip)"), file=sys.stderr)
    profiler = None
    from cruise_control_tpu.utils import profiling
    if (os.environ.get("BENCH_PROFILE", "") not in ("", "0")
            or profiling.enabled()):
        # segment-level profiling (CC_TPU_PROFILE=1 / legacy
        # BENCH_PROFILE=1; "0" disables either, matching
        # profiling.enabled()): per-goal programs with explicit sync
        # points and a per-segment attribution table on stderr after the
        # measured run.  Sync points cost transport latency and profile
        # mode re-segments the pipeline, so the measured number is NOT
        # comparable to an unprofiled run.
        os.environ[profiling.PROFILE_ENV] = "1"
        import logging
        logging.basicConfig(stream=sys.stderr, level=logging.INFO,
                            format="# %(message)s")
        optimizer.profile_segments = True
        profiler = profiling.install()

    def run_once(st, topo, options):
        # each measured solve runs under its own trace (obs/): the
        # instrument-fetch span (and, under CC_TPU_PROFILE, every
        # profiler segment) lands in the flight recorder, which the
        # trace_summary block of the output JSON aggregates
        from cruise_control_tpu.obs import trace as obs_trace
        with obs_trace.solve_trace("bench.solve", config=config):
            return optimizer.optimizations(st, topo, options,
                                           check_sanity=False, mesh=mesh)

    def run_config(st, topo):
        """One measured pass; config 4 chains add-broker then
        remove-broker (drain via self-healing) operations."""
        results = []
        if config == "4":
            # add-broker: rebalance onto the empty new brokers only
            results.append(run_once(st, topo, OptimizationOptions()))
            # remove-broker: kill 1% of brokers, drain via self-healing
            drained = results[-1].final_state
            kill = list(range(0, st.num_brokers, 100))
            for b in kill:
                drained = S.set_broker_state(drained, b, alive=False)
            results.append(run_once(drained, topo, OptimizationOptions()))
        else:
            results.append(run_once(st, topo, OptimizationOptions()))
        return results

    def run_with_retry(tag):
        # the remote-compile/device transport can drop long requests;
        # compiled segments persist, so a retry resumes where it failed
        for attempt in range(4):
            try:
                return run_config(state, topo)
            except jax.errors.JaxRuntimeError as exc:
                print(f"# {tag} attempt {attempt} hit transport error: "
                      f"{str(exc).splitlines()[0][:120]}", file=sys.stderr)
                time.sleep(10.0)
        return run_config(state, topo)

    # warm-up compiles every goal program for these shapes — in parallel
    # via AOT lowering (GoalOptimizer.warmup), seeding the persistent
    # cache; the measured run then pays only cache lookups (the JVM
    # reference likewise amortizes JIT warmup outside its
    # proposal-computation timer).  A first run-through also executes once
    # so one-off host work (weak-type promotions, transfer setup) is out
    # of the measured pass.
    warmup_total_s = 0.0
    if not os.environ.get("BENCH_SKIP_WARMUP"):
        t0 = time.time()
        warm_s = optimizer.warmup(state, topo, OptimizationOptions(),
                                  mesh=mesh)
        print(f"# warmup (cache-first parallel AOT) {warm_s:.1f}s "
              f"[progcache hits={progcache.hits} "
              f"fresh={progcache.fresh_compiles}]", file=sys.stderr)
        run_with_retry("warmup")
        warmup_total_s = time.time() - t0
        print(f"# warmup (compile+first run) {warmup_total_s:.1f}s",
              file=sys.stderr)

    if profiler is not None:
        # drop warmup-run records so the table attributes the MEASURED run
        profiler.reset()
    # likewise drop warmup traces: trace_summary must attribute the
    # measured run, not the compile-laden warmup pass
    _reset_traces()
    # device-dispatch budget: watched_call invocations during the
    # measured pass (parallel/health.py; warmup above hydrated the
    # programs, so the measured run goes through the watched gateway)
    from cruise_control_tpu.parallel import health as _health
    disp0 = _health.dispatch_count()
    disp_by0 = _health.dispatches_by_program()
    t0 = time.time()
    results = run_config(state, topo)
    elapsed = time.time() - t0
    dispatches = _health.dispatch_count() - disp0
    disp_by = {k: v - disp_by0.get(k, 0)
               for k, v in _health.dispatches_by_program().items()
               if v - disp_by0.get(k, 0)}

    if profiler is not None:
        print("# segment profile (CC_TPU_PROFILE: sync points inserted; "
              "wall-clock not comparable to an unprofiled run)",
              file=sys.stderr)
        for line in profiler.table().splitlines():
            print(f"# {line}", file=sys.stderr)

    total_props = sum(len(r.proposals) for r in results)
    print(f"# proposals={total_props} "
          f"replica_moves={sum(r.num_replica_movements for r in results)} "
          f"violated_after={len(results[-1].violated_goals_after)} "
          f"balancedness={results[-1].balancedness_score():.1f}",
          file=sys.stderr)
    counts = results[-1].violated_broker_counts
    entries = results[-1].entry_broker_counts
    nonzero = {g: c for g, c in counts.items() if any(c)}
    print("# violated broker counts (before->at-entry->after-own->"
          "after-all): "
          + (", ".join(f"{g}={b}->{entries.get(g, b)}->{o}->{a}"
                       for g, (b, o, a) in nonzero.items())
             or "none"), file=sys.stderr)
    conv = getattr(results[-1], "converged_at_by_goal", {}) or {}
    skipped = sorted({g for r in results
                      for g in (getattr(r, "skipped_goals", []) or [])})
    # rounds = the while_loop trip budget the goal consumed; converged-at
    # = the round its own convergence predicate first held (0 = never,
    # i.e. the round budget is the binding constraint) — a goal
    # converging at round 3 of 146 reports 3/146, not 146
    print("# rounds by goal (converged-at/rounds): "
          + (", ".join(f"{g}={conv.get(g, 0)}/{r}" for g, r in
                       results[-1].rounds_by_goal.items()) or "n/a"),
          file=sys.stderr)
    print(f"# dispatches={dispatches} (watched device programs in the "
          f"measured pass) goals_skipped={len(skipped)}"
          + (f" {skipped}" if skipped else ""), file=sys.stderr)
    # vs_baseline is a TARGET ratio (5 s north star / measured), not a
    # measured-reference comparison: no JVM exists in this environment to
    # run the reference GoalOptimizer (see BASELINE.md "measurement
    # status").  > 1 beats the target.
    print(f"# vs_baseline below = target_ratio ({TARGET_SECONDS:g}s "
          f"north-star / measured); reference CPU baseline unmeasured "
          f"(no JVM), see BASELINE.md", file=sys.stderr)
    regressions = _self_regressions(results)
    out = {
        "metric": (f"{label} {state.num_brokers}b/"
                   f"{state.num_partitions/1000:g}Kp rf{rf} [{backend}]"),
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / elapsed, 3),
        # topology attribution: which device layout produced this number
        "n_devices": n_devices,
        "mesh": ({"devices": n_devices, "axis": "replica"}
                 if mesh is not None else {"devices": 1, "axis": None}),
        # cold-start attribution: the warmup cost that preceded the
        # measured solve, and how much of it the persistent program
        # cache served (tracked per round — the ~300s number used to
        # live only in the log tail)
        "warmup_s": round(warmup_total_s, 3),
        "progcache_hits": progcache.hits,
        "progcache_fresh_compiles": progcache.fresh_compiles,
        # dispatch-budget attribution (ISSUE 16): how many device
        # programs the measured pass dispatched, which ones, how many
        # goal dispatches the host-side skip elided, and the round at
        # which each goal's convergence predicate first held
        "fusion": fused,
        "host_skip": host_skip,
        "precision": precision,
        "device_dispatches": dispatches,
        "dispatches_by_program": dict(sorted(disp_by.items())),
        "solver_goals_skipped": len(skipped),
        "skipped_goals": skipped,
        "converged_at_by_goal": {g: int(c) for g, c in conv.items()},
        "rounds_by_goal": {g: int(r) for g, r in
                           results[-1].rounds_by_goal.items()},
    }
    if precision != "float32":
        # tolerance gate: a reduced-precision headline only counts if
        # the f32 baseline agrees (analyzer/precision.py) — solve the
        # same model at f32 and compare
        from cruise_control_tpu.analyzer.precision import (
            proposals_equivalent)
        print("# precision gate: solving f32 baseline for comparison",
              file=sys.stderr)
        baseline = run_once(state_f32, topo, OptimizationOptions())
        gate_ok, gate = proposals_equivalent(
            baseline, results[-1],
            balancedness_eps=float(
                os.environ.get("BENCH_PRECISION_EPS", 0.5)),
            min_move_overlap=float(
                os.environ.get("BENCH_PRECISION_OVERLAP", 0.90)))
        out["precision_gate"] = gate
        print(f"# precision gate {'PASS' if gate_ok else 'FAIL'}: "
              f"{gate}", file=sys.stderr)
        if not gate_ok:
            print(json.dumps(_with_trace_summary(out)))
            print(f"# ERROR: {precision} solve failed the proposals-"
                  f"equivalence gate vs the f32 baseline", file=sys.stderr)
            sys.exit(1)
    if regressions:
        out["goal_self_regressions"] = regressions
        print("# ERROR: goal self-regression — these goals' OWN passes "
              "worsened their own violated-broker counts "
              f"(at-entry -> after-own): {regressions}", file=sys.stderr)
    print(json.dumps(_with_trace_summary(out)))
    if regressions:
        sys.exit(1)


def _incremental_bench() -> None:
    """BENCH_CONFIG=incremental: MEASURE the device-resident
    incremental workload model (see the module docstring block).  Two
    facades over byte-identical simulated clusters serve the SAME
    interactive delta stream; the only difference is
    incremental.enabled.  Gates (exit 1): single-broker-delta p50
    speedup >= 5x, and store-resident-model == from-scratch-rebuild
    byte equality after the stream."""
    import dataclasses

    import jax
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.cluster.simulated import SimulatedCluster
    from cruise_control_tpu.cluster.types import TopicPartition
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor.deltas import (ModelDelta,
                                                   PartitionLoadUpdate)
    from cruise_control_tpu.monitor.sampling.sampler import (
        SimulatedClusterSampler)

    num_b = int(os.environ.get("BENCH_BROKERS", 64))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 6000))
    rf = int(os.environ.get("BENCH_RF", 2))
    rounds = int(os.environ.get("BENCH_ROUNDS", 64))
    n_deltas = int(os.environ.get("BENCH_INCR_DELTAS", 64))
    goal_names = os.environ.get("BENCH_GOALS")
    names = (goal_names.split(",") if goal_names
             else ["RackAwareGoal", "DiskCapacityGoal",
                   "ReplicaDistributionGoal",
                   "DiskUsageDistributionGoal"])
    backend = jax.devices()[0].platform

    def build_stack(incremental: bool):
        sim = SimulatedCluster()
        clock = {"now": 10_000.0}
        for b in range(num_b):
            sim.add_broker(b, rack=f"rack{b % 4}")
        assignments = [[(p + i) % num_b for i in range(rf)]
                       for p in range(num_p)]
        # sized so total disk load stays well under the static capacity
        # (64 brokers x 1e6 x 0.8 threshold): the stream must measure
        # latency, not manufacture capacity infeasibility
        sim.create_topic("t0", assignments, size_bytes=1e3)
        for p in range(num_p):
            sim.set_partition_load(
                TopicPartition("t0", p), leader_cpu=2.0 + (p % 7) * 0.2,
                nw_in=100.0 + p % 13, nw_out=300.0)
        cc = CruiseControl(
            sim, SimulatedClusterSampler(sim),
            time_fn=lambda: clock["now"],
            sleep_fn=lambda s: (sim.advance(s), clock.__setitem__(
                "now", clock["now"] + s)),
            monitor_kwargs=dict(num_windows=3, window_ms=10_000,
                                min_samples_per_window=1,
                                sampling_interval_ms=5_000),
            executor_kwargs=dict(progress_check_interval_s=1.0),
            auto_warmup=False, goal_names=names,
            max_optimization_rounds=rounds,
            incremental_enabled=incremental)
        cc.start_up(do_sampling=False, start_detection=False)
        for _ in range(4):
            cc.load_monitor.task_runner.sample_once()
            sim.advance(5)
            clock["now"] += 5
        return cc

    print(f"# incremental bench: B={num_b} P={num_p} rf={rf} "
          f"goals={names} deltas={n_deltas} [{backend}]",
          file=sys.stderr)
    inc = build_stack(True)
    base = build_stack(False)
    # warm both: programs compile, proposal cache + warm seed prime
    t0 = time.time()
    inc.optimizations()
    base.optimizations()
    print(f"# warm solves done ({time.time()-t0:.1f}s)", file=sys.stderr)
    # the measured delta stream starts here: its facade-minted traces
    # (not the compile-laden warm solves above) feed trace_summary
    _reset_traces()

    rng = np.random.default_rng(11)

    def delta_for(i: int):
        """Alternate single-broker capacity tweaks and hot-partition
        load updates (the two dominant production delta kinds)."""
        if i % 2 == 0:
            # jitter UP from the static default (1e6): a capacity delta
            # must change the model, not starve it into infeasibility
            b = int(rng.integers(0, num_b))
            return ModelDelta(capacity_overrides={
                b: {"disk": float(1e6 * (1.05 + 0.05 * (i % 5)))}}), "cap"
        p = int(rng.integers(0, num_p))
        return ModelDelta(load_updates=(PartitionLoadUpdate(
            "t0", p, (3.0 + i % 3, 120.0, 320.0,
                      1e4 * (1.0 + 0.2 * (i % 4)))),)), "load"

    lat = {"inc": [], "base": []}
    lat_cap = {"inc": [], "base": []}
    for i in range(n_deltas):
        delta, kind = delta_for(i)
        for tag, cc in (("inc", inc), ("base", base)):
            cc.load_monitor.apply_model_delta(delta)
            t0 = time.time()
            cc.optimizations()
            dt = time.time() - t0
            lat[tag].append(dt)
            if kind == "cap":
                lat_cap[tag].append(dt)

    store = inc._model_store
    store_json = store.to_json()
    speedup_p50 = (_pct(lat["base"], 0.5) / _pct(lat["inc"], 0.5)
                   if lat["inc"] else 0.0)
    speedup_cap = (_pct(lat_cap["base"], 0.5) / _pct(lat_cap["inc"], 0.5)
                   if lat_cap["inc"] else 0.0)
    hit_rate = (store.hits / (store.hits + store.misses)
                if store.hits + store.misses else 0.0)

    # byte-equality gate: the delta-fast-forwarded resident model must
    # equal a from-scratch rebuild of the same generation
    resident = store._state
    gen_ok = store.generation == inc.load_monitor.model_generation()
    rebuilt, _ = inc.load_monitor.cluster_model()
    byte_identical = bool(gen_ok and resident is not None)
    if byte_identical:
        for f in dataclasses.fields(type(resident)):
            a, b = getattr(resident, f.name), getattr(rebuilt, f.name)
            if hasattr(a, "shape"):
                if not (np.asarray(a).shape == np.asarray(b).shape
                        and np.array_equal(np.asarray(a),
                                           np.asarray(b))):
                    byte_identical = False
                    print(f"# BYTE MISMATCH in {f.name}",
                          file=sys.stderr)
                    break
            elif a != b:
                byte_identical = False
                break

    result = {
        "p50_s": round(_pct(lat["inc"], 0.5), 4),
        "p99_s": round(_pct(lat["inc"], 0.99), 4),
        "full_p50_s": round(_pct(lat["base"], 0.5), 4),
        "full_p99_s": round(_pct(lat["base"], 0.99), 4),
        "single_broker_delta_speedup_p50": round(speedup_cap, 2),
        "stream_speedup_p50": round(speedup_p50, 2),
        "store_hit_rate": round(hit_rate, 4),
        "store_hits": store.hits,
        "store_misses": store.misses,
        "store_fallbacks": store.fallbacks,
        "store_delta_applies": store.delta_applies,
        "incremental_solve_fallbacks": int(inc.metrics.meter(
            "incremental-solve-fallbacks").to_json()["count"]),
        "last_dirty_brokers": store.last_dirty_brokers,
        "byte_identical_after_stream": byte_identical,
    }
    print(f"# incremental p50/p99 {result['p50_s']}/{result['p99_s']}s "
          f"vs full {result['full_p50_s']}/{result['full_p99_s']}s; "
          f"cap-delta speedup {speedup_cap:.1f}x, hit rate "
          f"{hit_rate:.2f}, fallbacks {store.fallbacks}, "
          f"byte_identical={byte_identical}", file=sys.stderr)
    inc.shutdown()
    base.shutdown()

    print(json.dumps(_with_trace_summary({
        "metric": (f"incremental {n_deltas}-delta interactive stream "
                   f"{num_b}b/{num_p/1000:g}Kp rf{rf} [{backend}]"),
        "value": result["p50_s"],
        "unit": "s",
        "vs_baseline": result["stream_speedup_p50"],
        "incremental": result,
    }, cluster=inc._coalesce_scope)))
    if not byte_identical:
        print("ERROR: delta-applied resident model != from-scratch "
              "rebuild", file=sys.stderr)
        sys.exit(1)
    if speedup_cap < 5.0:
        print(f"ERROR: single-broker delta solve speedup "
              f"{speedup_cap:.2f}x < 5x gate", file=sys.stderr)
        sys.exit(1)


def _coldstart_bench() -> None:
    """BENCH_CONFIG=coldstart: cold-PROCESS time-to-first-proposal with
    an empty vs warm persistent program cache (parallel/progcache.py).

    Two fresh subprocesses share one temp cache (program cache + the
    XLA persistent compilation cache as the lower tier): the first sees
    an EMPTY cache (traces, compiles, stores), the second hydrates.
    The warm run must perform ZERO source-program compiles
    (fresh_compiles == 0, pinned via the gateway compile-count
    instrumentation) and its proposals must be byte-identical to the
    cold run's (sha256 digest) — any violation exits 1.  Geometry via
    BENCH_BROKERS/BENCH_PARTITIONS/BENCH_GOALS; single-chip by design
    (the mesh sweep is BENCH_CONFIG=mesh)."""
    import shutil
    import subprocess
    import tempfile

    if os.environ.get("BENCH_COLDSTART_CHILD"):
        return _coldstart_child()
    base = tempfile.mkdtemp(prefix="cc-coldstart-")
    env = dict(os.environ)
    env.update(BENCH_COLDSTART_CHILD="1",
               BENCH_PROGCACHE=os.path.join(base, "progcache"),
               JAX_COMPILATION_CACHE_DIR=os.path.join(base, "xla"),
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0.5")
    runs = {}
    try:
        for label in ("cold", "warm"):
            t0 = time.time()
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True)
            sys.stderr.write(proc.stderr)
            if proc.returncode != 0 or not proc.stdout.strip():
                sys.exit(f"coldstart {label} child failed "
                         f"(rc={proc.returncode})")
            runs[label] = json.loads(
                proc.stdout.strip().splitlines()[-1])
            runs[label]["process_s"] = round(time.time() - t0, 3)
            print(f"# {label}: ttfp {runs[label]['ttfp_s']}s (warmup "
                  f"{runs[label]['warmup_s']}s), compiles "
                  f"{runs[label]['fresh_compiles']}, hits "
                  f"{runs[label]['hits']}", file=sys.stderr)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    cold, warm = runs["cold"], runs["warm"]
    zero_compiles = warm["fresh_compiles"] == 0
    identical = warm["proposals_digest"] == cold["proposals_digest"]
    if not zero_compiles:
        print(f"# ERROR: warm run paid {warm['fresh_compiles']} source "
              f"compiles (must be 0)", file=sys.stderr)
    if not identical:
        print("# ERROR: warm proposals differ from cold proposals",
              file=sys.stderr)
    print(json.dumps(_with_trace_summary({
        "metric": (f"cold-process time-to-first-proposal "
                   f"{cold['brokers']}b/{cold['partitions'] / 1000:g}Kp "
                   f"warm progcache"),
        "value": warm["ttfp_s"],
        "unit": "s",
        "vs_baseline": round(cold["ttfp_s"] / max(warm["ttfp_s"], 1e-9),
                             3),
        "coldstart": {
            "cold": cold,
            "warm": warm,
            "warm_zero_compiles": zero_compiles,
            "proposals_identical": identical,
        },
    })))
    if not (zero_compiles and identical):
        sys.exit(1)


def _coldstart_child() -> None:
    """One cold-process measurement (see _coldstart_bench): build the
    model, cache-first warmup, ONE solve; emit the run's JSON line."""
    import hashlib

    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer

    progcache = _configure_progcache()
    num_b = int(os.environ.get("BENCH_BROKERS", 64))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 2000))
    rf = int(os.environ.get("BENCH_RF", 3))
    goal_names = os.environ.get("BENCH_GOALS")
    t_start = time.time()
    state, topo = _build("coldstart", num_b, num_p, rf)
    goals = default_goals(
        max_rounds=int(os.environ.get("BENCH_ROUNDS", 64)),
        names=goal_names.split(",") if goal_names else None)
    optimizer = GoalOptimizer(
        goals,
        pipeline_segment_size=int(os.environ.get("BENCH_SEGMENT", 4)))
    t0 = time.time()
    optimizer.warmup(state, topo, OptimizationOptions())
    warmup_s = time.time() - t0
    t0 = time.time()
    result = optimizer.optimizations(state, topo, OptimizationOptions(),
                                     check_sanity=False)
    solve_s = time.time() - t0
    ttfp_s = time.time() - t_start
    digest = hashlib.sha256(repr(sorted(
        (p.partition.topic, p.partition.partition,
         tuple(p.new_replicas), p.new_leader)
        for p in result.proposals)).encode()).hexdigest()
    stats = progcache.stats()
    print(json.dumps(_with_trace_summary({
        "brokers": state.num_brokers,
        "partitions": state.num_partitions,
        "ttfp_s": round(ttfp_s, 3),
        "warmup_s": round(warmup_s, 3),
        "solve_s": round(solve_s, 3),
        "proposals": len(result.proposals),
        "proposals_digest": digest,
        "fresh_compiles": stats["freshCompiles"],
        "hits": stats["hits"],
        "misses": stats["misses"],
        "stores": stats["stores"],
        "cache_bytes": sum(
            e.size_bytes
            for e in progcache.entries(all_fingerprints=True)),
    })))


def _mesh_bench() -> None:
    """BENCH_CONFIG=mesh: full-stack solve latency + per-segment
    profiler attribution at mesh=1/2/4/8 (BENCH_MESH_DEVICES, clipped
    to the visible device count), same model, same goal stack, same
    pipeline — ONLY the device topology varies.  Each mesh size is
    AOT-warmed (GoalOptimizer.warmup(mesh=...)) and run once unmeasured
    before the measured pass, so the numbers compare steady-state solve
    latency, not compile luck.  The profiled pass runs SEPARATELY after
    the measured one (profiling re-segments the pipeline and inserts
    sync points, so its wall-clock is attribution-only).

    vs_baseline = mesh1 solve seconds / largest-mesh solve seconds
    (>1 = the mesh wins); the acceptance criterion for BENCH_r06 is
    monotone improvement mesh=1 -> mesh=8 on TPU."""
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.utils import profiling

    num_b = int(os.environ.get("BENCH_BROKERS", 2600))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 200_000))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 192))
    goal_names = os.environ.get("BENCH_GOALS")
    names = goal_names.split(",") if goal_names else None
    segment = int(os.environ.get("BENCH_SEGMENT", 2))
    visible = len(jax.devices())
    sizes = sorted({min(int(n), visible) for n in os.environ.get(
        "BENCH_MESH_DEVICES", "1,2,4,8").split(",") if n.strip()})
    if 1 not in sizes:
        # vs_baseline is DEFINED as mesh1 / largest-mesh: always measure
        # the single-chip baseline rather than silently substituting the
        # smallest requested mesh (same rule as the scenario bench's K=1)
        sizes = [1] + sizes
    profile = os.environ.get("BENCH_MESH_PROFILE", "1") not in ("", "0")

    backend = jax.devices()[0].platform
    state, topo = _build("north", num_b, num_p, rf)
    print(f"# mesh bench: B={state.num_brokers} P={state.num_partitions} "
          f"R={state.num_replicas} mesh sizes {sizes} of {visible} "
          f"visible [{backend}]", file=sys.stderr)

    optimizer = GoalOptimizer(default_goals(max_rounds=rounds,
                                            names=names),
                              pipeline_segment_size=segment)
    results = {}
    for n in sizes:
        mesh = _resolve_mesh(jax, raw=str(n))
        if n > 1 and mesh is None:
            print(f"# mesh={n}: not enough devices, skipped",
                  file=sys.stderr)
            continue

        def solve(traced=False):
            # only the MEASURED pass runs under a trace: warmup and the
            # profile pass would otherwise dominate trace_summary's
            # "slowest" with non-comparable wall-clocks
            if traced:
                from cruise_control_tpu.obs import trace as obs_trace
                with obs_trace.solve_trace("bench.mesh-solve",
                                           meshDevices=n):
                    return optimizer.optimizations(
                        state, topo, OptimizationOptions(),
                        check_sanity=False, mesh=mesh)
            return optimizer.optimizations(state, topo,
                                           OptimizationOptions(),
                                           check_sanity=False, mesh=mesh)

        t0 = time.time()
        warm_s = optimizer.warmup(state, topo, OptimizationOptions(),
                                  mesh=mesh)
        solve()                                   # first-run host costs
        warm_total = time.time() - t0
        t0 = time.time()
        r = solve(traced=True)                    # the measured pass
        solve_s = time.time() - t0
        entry = {
            "warmup_s": round(warm_total, 3),
            "warmup_compile_s": round(warm_s, 3),
            "solve_s": round(solve_s, 3),
            "n_devices": r.mesh_devices,
            "proposals": len(r.proposals),
            "balancedness": round(r.balancedness_score(), 2),
        }
        if profile:
            # attribution pass: per-goal programs + sync points; its
            # wall-clock is NOT comparable to solve_s above
            os.environ[profiling.PROFILE_ENV] = "1"
            prof = profiling.install()
            optimizer.profile_segments = True
            try:
                solve()
                entry["profile_category_s"] = {
                    c: round(s, 3)
                    for c, s in sorted(prof.category_totals().items())}
            finally:
                optimizer.profile_segments = False
                profiling.uninstall()
                os.environ[profiling.PROFILE_ENV] = "0"
        results[str(n)] = entry
        print(f"# mesh={n}: warm {entry['warmup_s']}s, solve "
              f"{entry['solve_s']}s, proposals {entry['proposals']}"
              + (f", attribution {entry.get('profile_category_s')}"
                 if profile else ""), file=sys.stderr)

    n_max = str(max(int(k) for k in results))
    base = results.get("1", results[min(results, key=int)])
    top = results[n_max]
    print(json.dumps(_with_trace_summary({
        "metric": (f"mesh-scaled full-stack {state.num_brokers}b/"
                   f"{state.num_partitions/1000:g}Kp rf{rf} "
                   f"mesh={n_max} [{backend}]"),
        "value": top["solve_s"],
        "unit": "s",
        # mesh scaling factor: single-chip solve / largest-mesh solve
        "vs_baseline": (round(base["solve_s"] / top["solve_s"], 3)
                        if top["solve_s"] else 0.0),
        "n_devices": top["n_devices"],
        "mesh": results,
    })))


def _meshchaos_bench() -> None:
    """BENCH_CONFIG=meshchaos: MEASURE elastic mesh recovery (see the
    module docstring block).  A live facade stack on the 8-device mesh
    takes an injected collective hang on its first warm mesh-8
    dispatch; records the wedge -> first-good-solve latency and the
    watchdog release time.  Gates (exit 1): the dispatch thread never
    blocked past mesh.watchdog.ms x 2, the solve completed on the
    shrunk span, and probe recovery climbed back to the full span."""
    import threading
    import jax

    from cruise_control_tpu.parallel import health
    if jax.default_backend() == "cpu" and len(jax.devices()) < 8:
        sys.exit("meshchaos needs >= 8 devices; run under the virtual "
                 "rig (XLA_FLAGS=--xla_force_host_platform_device_"
                 "count=8) or on multi-chip hardware")

    from cruise_control_tpu.cluster.simulated import SimulatedCluster
    from cruise_control_tpu.cluster.types import TopicPartition
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor.sampling.sampler import (
        SimulatedClusterSampler)
    from cruise_control_tpu.utils import faults

    num_b = int(os.environ.get("BENCH_BROKERS", 8))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 64))
    rf = int(os.environ.get("BENCH_RF", 2))
    watchdog_ms = float(os.environ.get("BENCH_MESHCHAOS_WATCHDOG_MS",
                                       2000))
    goal_names = os.environ.get("BENCH_GOALS")
    names = (goal_names.split(",") if goal_names
             else ["RackAwareGoal", "DiskCapacityGoal",
                   "ReplicaDistributionGoal"])
    backend = jax.devices()[0].platform

    sim = SimulatedCluster()
    clock = {"now": 10_000.0}
    for b in range(num_b):
        sim.add_broker(b, rack=f"rack{b % 4}")
    # everything parked on two brokers: the solve must MOVE replicas,
    # so an empty-proposal result can never fake a recovery
    assignments = [[i % 2 for i in range(rf)] for _ in range(num_p)]
    sim.create_topic("t0", assignments, size_bytes=1e4)
    for p in range(num_p):
        sim.set_partition_load(TopicPartition("t0", p), leader_cpu=2.0,
                               nw_in=100.0, nw_out=300.0)
    cc = CruiseControl(
        sim, SimulatedClusterSampler(sim),
        time_fn=lambda: clock["now"],
        sleep_fn=lambda s: (sim.advance(s),
                            clock.__setitem__("now", clock["now"] + s)),
        monitor_kwargs=dict(num_windows=3, window_ms=10_000,
                            min_samples_per_window=1,
                            sampling_interval_ms=5_000),
        auto_warmup=True, goal_names=names,
        mesh_enabled=True, mesh_watchdog_ms=watchdog_ms,
        mesh_probe_interval_ms=1e12)
    _reset_traces()
    for _ in range(8):
        cc.load_monitor.task_runner.sample_once()
        clock["now"] += 10.0
    print(f"# meshchaos: B={num_b} P={num_p} goals={names} watchdog="
          f"{watchdog_ms:.0f}ms [{backend}]", file=sys.stderr)

    # clean warm pass: AOT-warms the mesh-8 programs and baselines the
    # solve latency the recovery tax is measured against
    t0 = time.time()
    clean = cc.optimizations()
    clean_s = time.time() - t0
    sup = cc.mesh_supervisor
    full_span = sup.span

    # wedge: the next mesh dispatch hangs until released (it never is —
    # the watchdog must do the releasing)
    release = threading.Event()
    plan = faults.FaultPlan().hang_nth("mesh.dispatch", 1, release)
    t0 = time.time()
    with faults.injected(plan):
        recovered = cc.optimizations(ignore_proposal_cache=True)
    recovery_s = time.time() - t0
    release.set()
    release_ms = health.last_fire_wait_s() * 1000.0
    blocked_past_deadline = release_ms > watchdog_ms * 2
    shrunk_span = sup.span
    shrunk_ok = (shrunk_span < full_span
                 and recovered.mesh_devices == shrunk_span
                 and len(recovered.proposals) > 0)

    # probe recovery: chips are healthy, one cycle climbs back
    sup.probe_interval_ms = 0.0
    clock["now"] += 60.0
    again = cc.optimizations(ignore_proposal_cache=True)
    recovered_span = sup.span
    health.clear_quarantine()
    cc.shutdown()

    ok = shrunk_ok and not blocked_past_deadline \
        and recovered_span == full_span and again.mesh_devices == full_span
    out = {
        "metric": (f"meshchaos wedge->first-good-solve {num_b}b/"
                   f"{num_p}p span {full_span}->{shrunk_span}->"
                   f"{recovered_span} [{backend}]"),
        "value": round(recovery_s, 3),
        "unit": "s",
        # the recovery tax relative to a clean solve (<1 always; how
        # much of the wedge window the watchdog + requeue gave back)
        "vs_baseline": (round(clean_s / recovery_s, 3)
                        if recovery_s else 0.0),
        "meshchaos": {
            "clean_solve_s": round(clean_s, 3),
            "recovery_s": round(recovery_s, 3),
            "watchdog_ms": watchdog_ms,
            "watchdog_release_ms": round(release_ms, 1),
            "watchdog_fires": health.watchdog_fires(),
            "dispatch_blocked_past_deadline": blocked_past_deadline,
            "shrinks": sup.shrinks,
            "recoveries": sup.recoveries,
            "span_shrunk": shrunk_span,
            "span_recovered": recovered_span,
        },
    }
    print(json.dumps(_with_trace_summary(out)))
    if not ok:
        print("# ERROR: meshchaos gate failed — "
              + ("dispatch thread blocked past the watchdog deadline; "
                 if blocked_past_deadline else "")
              + ("solve did not complete on a shrunk span; "
                 if not shrunk_ok else "")
              + (f"span did not recover (at {recovered_span}, want "
                 f"{full_span})" if recovered_span != full_span else ""),
              file=sys.stderr)
        sys.exit(1)


def _scenario_bench() -> None:
    """BENCH_CONFIG=scenario: measure the batched what-if engine at
    K = BENCH_SCENARIO_BATCHES scenarios per program (default 1,8,32).

    Per batch size the engine runs TWICE: the first pass pays the
    vmapped-program compile (recorded), the second measures the warm
    solve — per-scenario latency is warm-solve / K.  The amortization
    verdict (vs_baseline) compares per-scenario latency at the largest K
    against the K=1 batch — same model, same goal list."""
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.analyzer.context import BalancingConstraint
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.scenario.engine import ScenarioEngine
    from cruise_control_tpu.scenario.spec import ScenarioSpec

    num_b = int(os.environ.get("BENCH_BROKERS", 200))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 20_000))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 64))
    goal_names = os.environ.get("BENCH_GOALS")
    names = (goal_names.split(",") if goal_names
             else ["RackAwareGoal", "DiskCapacityGoal",
                   "ReplicaDistributionGoal", "DiskUsageDistributionGoal"])
    batches = [int(k) for k in os.environ.get(
        "BENCH_SCENARIO_BATCHES", "1,8,32").split(",") if k.strip()]
    if 1 not in batches:
        # vs_baseline is defined as K=1-per-scenario / largest-K: always
        # measure the K=1 baseline rather than silently substituting the
        # smallest requested batch
        batches = [1] + batches

    backend = jax.devices()[0].platform
    state, topo = _build("2", num_b, num_p, rf)
    print(f"# scenario bench: B={state.num_brokers} "
          f"P={state.num_partitions} R={state.num_replicas} goals={names} "
          f"batches={batches} [{backend}]", file=sys.stderr)

    constraint = BalancingConstraint()
    optimizer = GoalOptimizer(
        default_goals(max_rounds=rounds, names=names), constraint,
        pipeline_segment_size=int(os.environ.get("BENCH_SEGMENT", 2)))
    engine = ScenarioEngine(
        lambda g: optimizer if g is None else GoalOptimizer(
            default_goals(max_rounds=rounds, names=g), constraint),
        constraint, max_batch_size=max(batches))

    def specs_for(k: int):
        # base + distinct load-scale variants: different solves, one shape
        out = [ScenarioSpec(name="base")]
        for i in range(1, k):
            out.append(ScenarioSpec(
                name=f"grow-{i}",
                load_scale={"disk": 1.0 + 0.05 * i,
                            "nw_in": 1.0 + 0.03 * i}))
        return out

    results = {}
    for k in batches:
        specs = specs_for(k)
        cold = engine.evaluate(state, topo, specs,
                               include_proposals=False)
        from cruise_control_tpu.obs import trace as obs_trace
        with obs_trace.solve_trace("bench.scenario-batch", k=k):
            warm = engine.evaluate(state, topo, specs,
                                   include_proposals=False)
        infeasible = sum(1 for o in warm.outcomes if not o.feasible)
        results[str(k)] = {
            "compile_s": round(cold.compile_s, 3),
            "cold_solve_s": round(cold.solve_s, 3),
            "warm_solve_s": round(warm.solve_s, 3),
            "per_scenario_s": round(warm.solve_s / k, 4),
            "oom_halvings": cold.oom_halvings + warm.oom_halvings,
            "rung": warm.rung,
            "infeasible": infeasible,
        }
        print(f"# K={k}: compile {results[str(k)]['compile_s']}s, warm "
              f"solve {results[str(k)]['warm_solve_s']}s "
              f"({results[str(k)]['per_scenario_s']}s/scenario), "
              f"rung={warm.rung}", file=sys.stderr)

    k_max = str(max(batches))
    per_max = results[k_max]["per_scenario_s"]
    per_one = results["1"]["per_scenario_s"]
    print(json.dumps(_with_trace_summary({
        "metric": (f"scenario what-if batch K={k_max} "
                   f"{state.num_brokers}b/{state.num_partitions/1000:g}Kp "
                   f"rf{rf} [{backend}]"),
        "value": per_max,
        "unit": "s",
        # amortization factor: K=1 per-scenario latency / largest-K
        # per-scenario latency (>1 = batching wins)
        "vs_baseline": round(per_one / per_max, 3) if per_max else 0.0,
        "scenario": results,
    })))


def _portfolio_bench() -> None:
    """BENCH_CONFIG=portfolio: MEASURE the population-of-solvers claim
    (ISSUE 19) — K perturbed solver configs batched into one vmapped
    solve vs the single greedy ladder, on the pinned bench fixture.

    Per width K (BENCH_PORTFOLIO_KS, default 1,8,32) the portfolio runs
    TWICE (cold pays the per-trace-group compiles, warm is the measured
    pass) and records the winner's balancedness, movement cost and
    fitness against the greedy baseline solve.  EXITS 1 when
    (a) any portfolio winner's fitness is below greedy's — the
    winner-never-worse invariant — or (b) the K=1 identity candidate is
    not byte-identical to the greedy solve (same proposals, same
    balancedness, same movement counts)."""
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    import numpy as np

    from cruise_control_tpu.analyzer.context import (BalancingConstraint,
                                                     OptimizationOptions)
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.portfolio.engine import PortfolioEngine
    from cruise_control_tpu.portfolio.mutate import make_portfolio
    from cruise_control_tpu.scenario.engine import ScenarioEngine

    num_b = int(os.environ.get("BENCH_BROKERS", 48))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 1500))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 48))
    seed = int(os.environ.get("BENCH_PORTFOLIO_SEED", 19))
    weight = float(os.environ.get("BENCH_PORTFOLIO_WEIGHT", "1.0"))
    max_programs = int(os.environ.get("BENCH_PORTFOLIO_PROGRAMS", 4))
    widths = [int(k) for k in os.environ.get(
        "BENCH_PORTFOLIO_KS", "1,8,32").split(",") if k.strip()]
    goal_env = os.environ.get("BENCH_GOALS")
    names = goal_env.split(",") if goal_env else None

    backend = jax.devices()[0].platform
    state, topo = _build("portfolio", num_b, num_p, rf)
    segment = int(os.environ.get("BENCH_SEGMENT", 2))
    constraint = BalancingConstraint()
    goals = default_goals(max_rounds=rounds, names=names)
    base_order = [g.name for g in goals]
    optimizer = GoalOptimizer(goals, constraint,
                              pipeline_segment_size=segment)

    def factory(g):
        if g is None or list(g) == base_order:
            return optimizer
        return GoalOptimizer(default_goals(max_rounds=rounds,
                                           names=list(g)),
                             constraint, pipeline_segment_size=segment)

    scenario = ScenarioEngine(factory, constraint,
                              max_batch_size=max(widths))
    engine = PortfolioEngine(scenario, factory, constraint=constraint,
                             movement_cost_weight=weight)

    print(f"# portfolio bench: B={state.num_brokers} "
          f"P={state.num_partitions} R={state.num_replicas} "
          f"goals={len(base_order)} widths={widths} seed={seed} "
          f"weight={weight} max_programs={max_programs} [{backend}]",
          file=sys.stderr)

    t0 = time.time()
    greedy = optimizer.optimizations(state, topo, OptimizationOptions(),
                                     check_sanity=False)
    greedy_s = time.time() - t0
    with jax.transfer_guard_device_to_host("allow"):
        num_replicas = int(np.asarray(state.replica_valid).sum())
    greedy_bal = greedy.balancedness_score()
    greedy_fit = engine.greedy_fitness(greedy, num_replicas)
    greedy_moves = (greedy.num_replica_movements,
                    greedy.num_leadership_movements)
    print(f"# greedy: balancedness {greedy_bal:.4f} fitness "
          f"{greedy_fit:.4f} moves {greedy_moves} ({greedy_s:.1f}s, "
          f"includes compile)", file=sys.stderr)

    errors = []
    results = {}
    k1_identical = None
    for k in widths:
        cands = make_portfolio(base_order, seed, k,
                               max_programs=max_programs)
        t0 = time.time()
        engine.search(state, topo, cands, seed,
                      options=OptimizationOptions())
        cold_s = time.time() - t0
        from cruise_control_tpu.obs import trace as obs_trace
        with obs_trace.solve_trace("bench.portfolio", k=k):
            t0 = time.time()
            res = engine.search(state, topo, cands, seed,
                                options=OptimizationOptions())
            warm_s = time.time() - t0
        w = res.winner
        if w is None or not w.feasible:
            errors.append(f"K={k}: no feasible portfolio winner")
            continue
        w_out = w.outcome
        w_bal = (w_out.balancedness if w_out is not None
                 else w.result.balancedness_score())
        # count moves by the proposal definitions (same as the greedy
        # OptimizerResult properties), not the device move epilogue —
        # apples to apples with greedy_moves
        w_props = (w_out.proposals if w_out is not None
                   else w.result.proposals)
        w_moves = (sum(len(p.replicas_to_add) for p in w_props),
                   sum(1 for p in w_props
                       if p.has_leader_action
                       and not p.has_replica_action))
        if w.fitness < greedy_fit - 1e-9:
            errors.append(f"K={k}: winner fitness {w.fitness:.4f} worse "
                          f"than greedy {greedy_fit:.4f}")
        if k == 1:
            # the identity candidate must reproduce the greedy solve
            # byte for byte: same balancedness, moves, proposals
            same_props = ([repr(p) for p in w_props]
                          == [repr(p) for p in greedy.proposals])
            k1_identical = (abs(w_bal - greedy_bal) < 1e-9
                            and w_moves == greedy_moves and same_props)
            if not k1_identical:
                errors.append(
                    f"K=1 identity not byte-identical: balancedness "
                    f"{w_bal:.6f} vs {greedy_bal:.6f}, moves {w_moves} "
                    f"vs {greedy_moves}, proposals_equal={same_props}")
        results[str(k)] = {
            "rung": res.rung,
            "cold_search_s": round(cold_s, 3),
            "warm_search_s": round(warm_s, 3),
            "per_candidate_s": round(warm_s / k, 4),
            "winner_index": w.candidate.index,
            "winner_perturbation": w.candidate.description,
            "winner_balancedness": round(w_bal, 4),
            "winner_fitness": round(w.fitness, 4),
            "winner_moves": list(w_moves),
            "balancedness_gain": round(w_bal - greedy_bal, 4),
            "fitness_gain": round(w.fitness - greedy_fit, 4),
        }
        print(f"# K={k}: winner idx {w.candidate.index} balancedness "
              f"{w_bal:.4f} (greedy {greedy_bal:.4f}) fitness "
              f"{w.fitness:.4f} moves {w_moves} rung={res.rung} warm "
              f"{warm_s:.1f}s", file=sys.stderr)

    wide = [results[str(k)] for k in widths
            if k >= 8 and str(k) in results]
    improved_at_8plus = bool(wide) and any(
        e["balancedness_gain"] > 0 for e in wide)
    best_gain = max((e["balancedness_gain"] for e in wide), default=0.0)
    k_max = str(max(widths))
    print(json.dumps(_with_trace_summary({
        "metric": (f"portfolio best-vs-greedy balancedness gain "
                   f"K={k_max} {state.num_brokers}b/"
                   f"{state.num_partitions/1000:g}Kp rf{rf} [{backend}]"),
        "value": best_gain,
        "unit": "balancedness",
        # the plateau metric: winner fitness / greedy fitness at the
        # widest portfolio (>1 = the population beat the single ladder)
        "vs_baseline": (round(results[k_max]["winner_fitness"]
                              / greedy_fit, 4)
                        if k_max in results and greedy_fit else 0.0),
        "config": (f"BENCH_CONFIG=portfolio {state.num_brokers}b/"
                   f"{state.num_partitions/1000:g}Kp rf{rf} "
                   f"rounds={rounds} seed={seed} weight={weight} "
                   f"max_programs={max_programs}"),
        "greedy": {"balancedness": round(greedy_bal, 4),
                   "fitness": round(greedy_fit, 4),
                   "moves": list(greedy_moves),
                   "solve_s": round(greedy_s, 3)},
        "portfolio": results,
        "k1_identical": k1_identical,
        "never_worse": not any("worse" in e for e in errors),
        "improved_at_k8plus": improved_at_8plus,
        "engine": engine.to_json(),
    })))
    if errors:
        for e in errors:
            print(f"# ERROR: {e}", file=sys.stderr)
        sys.exit(1)


def _fleet_bench() -> None:
    """BENCH_CONFIG=fleet: MEASURE the shared-bucket-program claim.

    K tenants (BENCH_FLEET_TENANTS, default 1,4,16) get K different
    broker counts that all land in ONE power-of-two shape bucket.  Two
    modes per K:

    * bucketed — every tenant's state pads to the bucket
      (fleet/buckets.py) before solving, so the process-wide program
      cache (analyzer/optimizer._SHARED_PROGRAMS) serves every tenant
      from the FIRST tenant's compile;
    * unbucketed — the 16-separate-facades baseline: each tenant solves
      at its raw shape, compiling its own program set.

    Compile count per mode = the number of shape-specialized
    executables across the shared pipeline programs (each jitted
    pre/segment/post program compiles once per distinct argument
    shape — `jit._cache_size()` sums them).  vs_baseline = unbucketed
    compiles / bucketed compiles at the largest K (>1 = compile count
    sublinear in tenant count); per-tenant results are checked identical
    bucketed vs raw (dead-row padding invariant).
    """
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.analyzer import optimizer as opt_mod
    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.fleet.buckets import BucketIndex
    from cruise_control_tpu.testing.random_cluster import (
        RandomClusterSpec, random_cluster)

    num_b = int(os.environ.get("BENCH_BROKERS", 48))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 2400))
    rf = int(os.environ.get("BENCH_RF", 2))
    rounds = int(os.environ.get("BENCH_ROUNDS", 32))
    goal_names = os.environ.get("BENCH_GOALS")
    names = (goal_names.split(",") if goal_names
             else ["RackAwareGoal", "DiskCapacityGoal",
                   "ReplicaDistributionGoal"])
    tenant_counts = [int(k) for k in os.environ.get(
        "BENCH_FLEET_TENANTS", "1,4,16").split(",") if k.strip()]
    k_max = max(tenant_counts)

    backend = jax.devices()[0].platform
    optimizer = GoalOptimizer(
        default_goals(max_rounds=rounds, names=names),
        pipeline_segment_size=int(os.environ.get("BENCH_SEGMENT", 2)))
    buckets = BucketIndex(floor=8)

    def tenant_model(i: int):
        # i DISTINCT broker counts inside one bucket: num_b - i stays
        # above the previous power of two for every i < k_max
        return random_cluster(RandomClusterSpec(
            num_brokers=num_b - i, num_partitions=num_p,
            replication_factor=rf, num_racks=8,
            num_topics=max(4, num_p // 1000), seed=100 + i,
            skew_fraction=0.3))

    models = [tenant_model(i) for i in range(k_max)]
    bucket = buckets.bucket_for(models[0][0])
    print(f"# fleet bench: {k_max} tenants, brokers "
          f"{num_b - k_max + 1}..{num_b} -> bucket {bucket.brokers}b/"
          f"{bucket.replicas}r, goals={names} [{backend}]",
          file=sys.stderr)

    def solve(state, topo, traced=False):
        # only WARM measured solves carry a trace (cold solves are
        # compile-dominated and would skew trace_summary's "slowest")
        if traced:
            from cruise_control_tpu.obs import trace as obs_trace
            with obs_trace.solve_trace("bench.fleet-solve"):
                return optimizer.optimizations(state, topo,
                                               OptimizationOptions(),
                                               check_sanity=False)
        return optimizer.optimizations(state, topo,
                                       OptimizationOptions(),
                                       check_sanity=False)

    def compiled_executables() -> int:
        """Shape-specialized executables across the shared pipeline
        programs: what a tenant of a NEW shape actually pays."""
        with opt_mod._SHARED_LOCK:
            progs = list(opt_mod._SHARED_PROGRAMS.values())
        total = 0
        for prog in progs:
            size = getattr(prog, "_cache_size", None)
            total += size() if callable(size) else 1
        return total

    def run_mode(k: int, bucketed: bool):
        # each (K, mode) measures from a cold program cache so compile
        # counts are per-run absolutes, not cross-run deltas (the
        # persistent disk cache keeps the re-compiles themselves cheap)
        with opt_mod._SHARED_LOCK:
            opt_mod._SHARED_PROGRAMS.clear()
            opt_mod._SHARED_LRU.clear()
        jax.clear_caches()
        cold, warm = [], []
        for state, topo in models[:k]:
            if bucketed:
                state = buckets.pad(state)
            t0 = time.time()
            solve(state, topo)
            cold.append(time.time() - t0)
            t0 = time.time()
            result = solve(state, topo, traced=True)
            warm.append(time.time() - t0)
        return compiled_executables(), cold, warm, result

    def key(p):
        return (p.partition.topic, p.partition.partition,
                tuple(r.broker_id for r in p.old_replicas),
                tuple(r.broker_id for r in p.new_replicas))

    # per-tenant correctness: bucketed == raw proposals (tenant k_max-1,
    # the smallest -> maximum padding)
    state, topo = models[-1]
    raw = solve(state, topo)
    padded = solve(buckets.pad(state), topo)
    identical = sorted(map(key, raw.proposals)) == \
        sorted(map(key, padded.proposals))
    print(f"# per-tenant results identical bucketed vs raw: {identical}",
          file=sys.stderr)

    results = {}
    for k in tenant_counts:
        b_compiles, b_cold, b_warm, _ = run_mode(k, bucketed=True)
        u_compiles, u_cold, u_warm, _ = run_mode(k, bucketed=False)
        results[str(k)] = {
            "bucketed_compiled_programs": b_compiles,
            "unbucketed_compiled_programs": u_compiles,
            "bucketed_first_solve_s": round(sum(b_cold) / k, 4),
            "bucketed_warm_solve_s": round(sum(b_warm) / k, 4),
            "unbucketed_first_solve_s": round(sum(u_cold) / k, 4),
            "unbucketed_warm_solve_s": round(sum(u_warm) / k, 4),
        }
        print(f"# K={k}: compiled programs bucketed={b_compiles} "
              f"unbucketed={u_compiles}, warm solve "
              f"{results[str(k)]['bucketed_warm_solve_s']}s vs "
              f"{results[str(k)]['unbucketed_warm_solve_s']}s",
              file=sys.stderr)

    top = results[str(k_max)]
    b, u = (top["bucketed_compiled_programs"],
            top["unbucketed_compiled_programs"])
    print(json.dumps(_with_trace_summary({
        "metric": (f"fleet {k_max} tenants {num_b}b/"
                   f"{num_p/1000:g}Kp rf{rf} bucket={bucket.brokers}b "
                   f"[{backend}]"),
        "value": top["bucketed_warm_solve_s"],
        "unit": "s",
        # compile-sharing factor at the largest K: unbucketed compiles /
        # bucketed compiles (>1 = compile count sublinear in tenants)
        "vs_baseline": round(u / b, 3) if b else 0.0,
        "results_identical": identical,
        "fleet": results,
    })))


def _sched_bench() -> None:
    """BENCH_CONFIG=sched: end-to-end request latency under concurrent
    mixed solve traffic, scheduled (sched/DeviceTimeScheduler: priority
    admission + single-flight coalescing) vs the unscheduled baseline
    (every client thread calls the optimizer directly — the pre-PR-4
    free-for-all).

    Per client count N (BENCH_SCHED_CLIENTS, default 1,8,32): N threads
    each issue BENCH_SCHED_REQUESTS (default 4) requests.  The mix
    mirrors production traffic: every client's requests alternate
    USER_INTERACTIVE and PRECOMPUTE class, and half the interactive
    requests are IDENTICAL across clients (same goal list, same model —
    the dashboard-rebalance stampede) so single-flight coalescing is
    measured, not just queueing.  Records per-N p50/p99 latency and the
    scheduler's device occupancy; vs_baseline = unscheduled p99 /
    scheduled p99 at the largest N (>1 = the scheduler wins)."""
    import threading

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.sched import (DeviceTimeScheduler,
                                          SchedulerClass, SchedulerPolicy,
                                          SolveJob)

    num_b = int(os.environ.get("BENCH_BROKERS", 200))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 20_000))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 64))
    goal_names = os.environ.get("BENCH_GOALS")
    names = (goal_names.split(",") if goal_names
             else ["RackAwareGoal", "DiskCapacityGoal",
                   "ReplicaDistributionGoal", "DiskUsageDistributionGoal"])
    clients = [int(k) for k in os.environ.get(
        "BENCH_SCHED_CLIENTS", "1,8,32").split(",") if k.strip()]
    per_client = int(os.environ.get("BENCH_SCHED_REQUESTS", 4))

    backend = jax.devices()[0].platform
    state, topo = _build("2", num_b, num_p, rf)
    optimizer = GoalOptimizer(
        default_goals(max_rounds=rounds, names=names),
        pipeline_segment_size=int(os.environ.get("BENCH_SEGMENT", 2)))
    print(f"# sched bench: B={state.num_brokers} P={state.num_partitions} "
          f"goals={names} clients={clients} x{per_client} req [{backend}]",
          file=sys.stderr)
    # warm the programs so the measured passes compare scheduling, not
    # first-compile luck
    optimizer.optimizations(state, topo, OptimizationOptions(),
                            check_sanity=False)

    def solve(variant: int):
        # distinct variants exclude different (nonexistent) topics: same
        # shapes -> compiled programs are reused, but the requests are
        # NOT identical so they cannot coalesce; variant 0 is the shared
        # identical request
        options = (OptimizationOptions() if variant == 0 else
                   OptimizationOptions(
                       excluded_topics=frozenset({f"__bench_{variant}__"})))
        return optimizer.optimizations(state, topo, options,
                                       check_sanity=False)

    def run_load(n_clients: int, scheduler):
        """Returns per-request latencies; scheduler=None = unscheduled
        baseline (direct concurrent calls)."""
        latencies = []
        lat_lock = threading.Lock()
        barrier = threading.Barrier(n_clients)

        def client(ci: int):
            for r in range(per_client):
                if r == 0:
                    barrier.wait()
                # mix: even requests interactive (half of them the
                # SHARED variant 0), odd requests precompute-class
                interactive = r % 2 == 0
                # globally unique per (client, request): nominally
                # distinct requests must never share a coalesce key, or
                # the scheduled run gets coalescing wins the unscheduled
                # baseline cannot and vs_baseline overstates the benefit
                variant = 0 if (interactive and ci % 2 == 0) \
                    else 1 + ci * per_client + r
                t0 = time.time()
                if scheduler is None:
                    solve(variant)
                else:
                    # each scheduled request is its own trace, so
                    # trace_summary decomposes p99 into queue-wait vs
                    # device time (the ROADMAP-5 tuning signal)
                    from cruise_control_tpu.obs import trace as obs_trace
                    with obs_trace.solve_trace("bench.request",
                                               variant=variant):
                        scheduler.submit(SolveJob(
                            klass=(SchedulerClass.USER_INTERACTIVE
                                   if interactive
                                   else SchedulerClass.PRECOMPUTE),
                            run=lambda v=variant: solve(v),
                            coalesce_key=("bench", variant),
                            label=f"bench-{ci}-{r}",
                            trace=obs_trace.current_context()))
                with lat_lock:
                    latencies.append(time.time() - t0)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return latencies

    results = {}
    for n in clients:
        base_lat = run_load(n, None)
        policy = SchedulerPolicy.from_lists(
            queue_caps=[max(64, n * per_client)] * 4)
        sched = DeviceTimeScheduler(policy)
        # attribute the LAST scheduled load (largest N by convention):
        # drop the unscheduled baseline's / smaller Ns' traces
        _reset_traces()
        t0 = time.time()
        sched_lat = run_load(n, sched)
        wall = time.time() - t0
        occupancy = min(1.0, sched.stats.busy_s / wall) if wall else 0.0
        coalesced = sched.stats.coalesced
        sched.stop()
        results[str(n)] = {
            "unsched_p50_s": round(_pct(base_lat, 0.50), 4),
            "unsched_p99_s": round(_pct(base_lat, 0.99), 4),
            "sched_p50_s": round(_pct(sched_lat, 0.50), 4),
            "sched_p99_s": round(_pct(sched_lat, 0.99), 4),
            "device_occupancy": round(occupancy, 4),
            "coalesced": coalesced,
        }
        print(f"# N={n}: unsched p50/p99 "
              f"{results[str(n)]['unsched_p50_s']}/"
              f"{results[str(n)]['unsched_p99_s']}s, sched p50/p99 "
              f"{results[str(n)]['sched_p50_s']}/"
              f"{results[str(n)]['sched_p99_s']}s, occupancy "
              f"{results[str(n)]['device_occupancy']}, "
              f"coalesced {coalesced}", file=sys.stderr)

    n_max = str(max(clients))
    p99_sched = results[n_max]["sched_p99_s"]
    p99_unsched = results[n_max]["unsched_p99_s"]
    print(json.dumps(_with_trace_summary({
        "metric": (f"sched {n_max} concurrent mixed clients "
                   f"{state.num_brokers}b/{state.num_partitions/1000:g}Kp "
                   f"rf{rf} [{backend}]"),
        "value": p99_sched,
        "unit": "s",
        # scheduling win at the largest client count: unscheduled p99 /
        # scheduled p99 (>1 = priority order + coalescing beat the
        # free-for-all)
        "vs_baseline": (round(p99_unsched / p99_sched, 3)
                        if p99_sched else 0.0),
        "sched": results,
    })))


def _soak_bench():
    """BENCH_CONFIG=soak: the trace-replay soak rig + SLO gate (see
    the module docstring).  Two runs against fresh in-process demo
    rigs: a CLEAN run whose artifact self-baselines and must pass
    tools/slo_gate.py, then a FAULTED run (PR-2 harness:
    hang_always('sched.dispatch', BENCH_SOAK_FAULT_S) inflates every
    dispatch) that must FAIL the gate against the clean baseline —
    the bench proves the gate gates, not just that the harness runs."""
    import importlib.util

    from cruise_control_tpu.loadgen import (LoadHarness, builtin_profile,
                                            validate_artifact)
    from cruise_control_tpu.loadgen.rig import build_demo_rig
    from cruise_control_tpu.utils import faults

    gate_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools", "slo_gate.py")
    spec = importlib.util.spec_from_file_location("cc_slo_gate",
                                                  gate_path)
    slo_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(slo_gate)

    duration = float(os.environ.get("BENCH_SOAK_SECONDS", 20.0))
    rps = float(os.environ.get("BENCH_SOAK_RPS", 3.0))
    seed = int(os.environ.get("BENCH_SOAK_SEED", 1))
    fault_s = float(os.environ.get("BENCH_SOAK_FAULT_S", 2.0))
    out_dir = os.environ.get(
        "BENCH_SOAK_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".soak"))
    os.makedirs(out_dir, exist_ok=True)
    profile = builtin_profile("soak-mixed", duration_s=duration,
                              rps=rps, seed=seed)
    print(f"# soak: profile={profile.name} seed={seed} "
          f"duration={duration}s rps={rps} clients={profile.clients} "
          f"fault={fault_s}s", file=sys.stderr)

    def one_run(tag: str, fault_plan=None) -> dict:
        _reset_traces()
        # build_demo_rig(warm=True) pre-compiles every program shape
        # BEFORE measuring (and before any fault installs): the soak
        # measures serving, not first-compile luck
        rig = build_demo_rig()
        try:
            harness = LoadHarness(rig.base_url, profile, rig=rig.rig)
            if fault_plan is not None:
                with faults.injected(fault_plan):
                    artifact = harness.run()
            else:
                artifact = harness.run()
        finally:
            rig.shutdown()
        path = os.path.join(out_dir, f"artifact-{tag}.json")
        with open(path, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"# soak: {tag} artifact -> {path} "
              f"({artifact['requests']['total']} requests, "
              f"{artifact['requests']['rejected']} rejected)",
              file=sys.stderr)
        return artifact

    clean = one_run("clean")
    problems = validate_artifact(clean)
    clean_path = os.path.join(out_dir, "artifact-clean.json")
    baseline_path = os.path.join(out_dir, "baseline.json")
    rc_baseline = slo_gate.main(["--artifact", clean_path,
                                 "--write-baseline", baseline_path])
    rc_clean = slo_gate.main(["--artifact", clean_path,
                              "--baseline", baseline_path])

    plan = faults.FaultPlan()
    plan.hang_always("sched.dispatch", fault_s)
    faulted = one_run("faulted", fault_plan=plan)
    rc_faulted = slo_gate.main(
        ["--artifact", os.path.join(out_dir, "artifact-faulted.json"),
         "--baseline", baseline_path])

    clean_p99 = (clean.get("latency", {})
                 .get("USER_INTERACTIVE", {}).get("p99Ms", 0.0)) / 1e3
    fault_p99 = (faulted.get("latency", {})
                 .get("USER_INTERACTIVE", {}).get("p99Ms", 0.0)) / 1e3
    failures = []
    if problems:
        failures.append(f"artifact invalid: {problems}")
    if rc_baseline != 0:
        failures.append("baseline write failed")
    if rc_clean != 0:
        failures.append("gate FAILED the clean run (must pass)")
    if rc_faulted == 0:
        failures.append(f"gate PASSED the faulted run (a {fault_s}s "
                        f"injected dispatch latency must breach)")
    if not clean.get("decomposition"):
        failures.append("per-class decomposition is empty (no span "
                        "trees reached the artifact)")
    print(json.dumps(_with_trace_summary({
        "metric": (f"soak {profile.clients} clients {duration:g}s "
                   f"mixed-class replay + SLO gate"),
        "value": round(clean_p99, 4),
        "unit": "s",
        # the regression the gate caught: faulted p99 / clean p99
        "vs_baseline": (round(fault_p99 / clean_p99, 3)
                        if clean_p99 else 0.0),
        "soak": {
            "seed": seed,
            "planDigest": clean.get("planDigest"),
            "requests": clean.get("requests"),
            "latency": clean.get("latency"),
            "decomposition": clean.get("decomposition"),
            "slo": {"clean": clean.get("slo", {}).get("status"),
                    "faulted": faulted.get("slo", {}).get("status")},
            "gate": {"clean_rc": rc_clean, "faulted_rc": rc_faulted},
            "artifacts": out_dir,
            **({"failures": failures} if failures else {}),
        },
    })))
    if failures:
        for f in failures:
            print(f"# soak ERROR: {f}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()

