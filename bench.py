"""Benchmark: full multi-goal rebalance proposal generation.

North-star config (BASELINE.json): 2,600 brokers / 200K partitions, full
default goal stack, target < 5 s wall-clock on TPU — ≥30× the reference's
CPU GoalOptimizer.  Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}
`vs_baseline` is target_seconds / measured_seconds (>1 beats the 5 s
north-star target).

BENCH_CONFIG selects a BASELINE.json eval config:
  north (default)  2600b/200Kp, full default goal stack
  1                3-broker/30-partition deterministic fixture
  2                200b/20Kp, resource-distribution goals only
  3                1000b/80Kp, full hard+soft stack
  4                2600b/200Kp add-broker + remove-broker operations
  5                2600b JBOD (4 logdirs/broker, broken disks) with
                   DiskUsageDistributionGoal + offline-replica self-healing
  scenario         batched what-if engine (scenario/engine.py): solves
                   K = BENCH_SCENARIO_BATCHES (default 1,8,32) scenario
                   variants per vmapped program and records per-batch
                   compile + solve latency, so the one-compile-amortized-
                   over-K claim is MEASURED (the output JSON carries a
                   "scenario" block; value = per-scenario solve seconds
                   at the largest K, vs_baseline = K=1-per-scenario /
                   largest-K-per-scenario, >1 = batching wins)

Other knobs: BENCH_BROKERS, BENCH_PARTITIONS, BENCH_RF, BENCH_ROUNDS,
BENCH_GOALS (comma list), BENCH_SEGMENT, BENCH_SKIP_WARMUP.

CC_TPU_PROFILE=1 (or legacy BENCH_PROFILE=1) enables the segment-level
profiler: per-goal programs with explicit sync points, emitting the
per-segment attribution table (prebalance / per-goal rounds / stats
epilogues / leadership / diff / transfer) on stderr — see
cruise_control_tpu/utils/profiling.py and tools/profile_segments.py.
"""
import json
import os
import sys
import time

TARGET_SECONDS = 5.0

# persistent compile cache: segment programs at 2.6K-broker scale take
# minutes to compile; retries and re-runs must not pay that twice
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def _build(config, num_b, num_p, rf, seed=4):
    from cruise_control_tpu.testing.fixtures import small_cluster
    from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                           random_cluster)
    if config == "1":
        return small_cluster()
    kwargs = {}
    if config == "4":
        kwargs["new_brokers"] = max(1, num_b // 20)
    if config == "5":
        kwargs.update(jbod_disks=4, dead_disks=max(1, num_b // 50))
    return random_cluster(RandomClusterSpec(
        num_brokers=num_b, num_partitions=num_p, replication_factor=rf,
        num_racks=max(8, num_b // 100), num_topics=max(8, num_p // 2000),
        seed=seed, skew_fraction=0.2, **kwargs))


def main() -> None:
    t_import = time.time()
    import jax

    # a platform hook (sitecustomize) may have imported jax BEFORE this
    # process set the cache env vars above, in which case they were never
    # read — apply the config directly (backends initialize lazily, so
    # this still takes effect)
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.model import state as S

    config = os.environ.get("BENCH_CONFIG", "north")
    if config == "scenario":
        return _scenario_bench()
    presets = {  # (brokers, partitions, goal subset, metric label)
        "north": (2600, 200_000, None, "full-stack proposal generation"),
        "1": (3, 30, None, "deterministic fixture"),
        "2": (200, 20_000, ["DiskUsageDistributionGoal",
                            "NetworkInboundUsageDistributionGoal",
                            "NetworkOutboundUsageDistributionGoal",
                            "CpuUsageDistributionGoal"],
              "resource-distribution goals"),
        "3": (1000, 80_000, None, "full-stack proposal generation"),
        "4": (2600, 200_000, None, "add-broker + remove-broker"),
        "5": (2600, 200_000, ["DiskCapacityGoal",
                              "DiskUsageDistributionGoal"],
              "JBOD self-healing + disk distribution"),
    }
    if config not in presets:
        sys.exit(f"unknown BENCH_CONFIG={config!r}; "
                 f"valid: {sorted(presets)}")
    d_b, d_p, d_goals, label = presets[config]
    num_b = int(os.environ.get("BENCH_BROKERS", d_b))
    num_p = int(os.environ.get("BENCH_PARTITIONS", d_p))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 192))
    goal_names = os.environ.get("BENCH_GOALS")
    names = goal_names.split(",") if goal_names else d_goals

    backend = jax.devices()[0].platform
    print(f"# config={config} backend={backend} devices={jax.devices()} "
          f"(import+init {time.time()-t_import:.1f}s)", file=sys.stderr)

    t0 = time.time()
    state, topo = _build(config, num_b, num_p, rf)
    print(f"# model built: B={state.num_brokers} P={state.num_partitions} "
          f"R={state.num_replicas} ({time.time()-t0:.1f}s)", file=sys.stderr)

    goals = default_goals(max_rounds=rounds, names=names)
    segment = int(os.environ.get("BENCH_SEGMENT", 2))
    optimizer = GoalOptimizer(goals, pipeline_segment_size=segment)
    profiler = None
    from cruise_control_tpu.utils import profiling
    if (os.environ.get("BENCH_PROFILE", "") not in ("", "0")
            or profiling.enabled()):
        # segment-level profiling (CC_TPU_PROFILE=1 / legacy
        # BENCH_PROFILE=1; "0" disables either, matching
        # profiling.enabled()): per-goal programs with explicit sync
        # points and a per-segment attribution table on stderr after the
        # measured run.  Sync points cost transport latency and profile
        # mode re-segments the pipeline, so the measured number is NOT
        # comparable to an unprofiled run.
        os.environ[profiling.PROFILE_ENV] = "1"
        import logging
        logging.basicConfig(stream=sys.stderr, level=logging.INFO,
                            format="# %(message)s")
        optimizer.profile_segments = True
        profiler = profiling.install()

    def run_once(st, topo, options):
        return optimizer.optimizations(st, topo, options, check_sanity=False)

    def run_config(st, topo):
        """One measured pass; config 4 chains add-broker then
        remove-broker (drain via self-healing) operations."""
        results = []
        if config == "4":
            # add-broker: rebalance onto the empty new brokers only
            results.append(run_once(st, topo, OptimizationOptions()))
            # remove-broker: kill 1% of brokers, drain via self-healing
            drained = results[-1].final_state
            kill = list(range(0, st.num_brokers, 100))
            for b in kill:
                drained = S.set_broker_state(drained, b, alive=False)
            results.append(run_once(drained, topo, OptimizationOptions()))
        else:
            results.append(run_once(st, topo, OptimizationOptions()))
        return results

    def run_with_retry(tag):
        # the remote-compile/device transport can drop long requests;
        # compiled segments persist, so a retry resumes where it failed
        for attempt in range(4):
            try:
                return run_config(state, topo)
            except jax.errors.JaxRuntimeError as exc:
                print(f"# {tag} attempt {attempt} hit transport error: "
                      f"{str(exc).splitlines()[0][:120]}", file=sys.stderr)
                time.sleep(10.0)
        return run_config(state, topo)

    # warm-up compiles every goal program for these shapes — in parallel
    # via AOT lowering (GoalOptimizer.warmup), seeding the persistent
    # cache; the measured run then pays only cache lookups (the JVM
    # reference likewise amortizes JIT warmup outside its
    # proposal-computation timer).  A first run-through also executes once
    # so one-off host work (weak-type promotions, transfer setup) is out
    # of the measured pass.
    if not os.environ.get("BENCH_SKIP_WARMUP"):
        t0 = time.time()
        warm_s = optimizer.warmup(state, topo, OptimizationOptions())
        print(f"# warmup (parallel AOT compile) {warm_s:.1f}s",
              file=sys.stderr)
        run_with_retry("warmup")
        print(f"# warmup (compile+first run) {time.time()-t0:.1f}s",
              file=sys.stderr)

    if profiler is not None:
        # drop warmup-run records so the table attributes the MEASURED run
        profiler.reset()
    t0 = time.time()
    results = run_config(state, topo)
    elapsed = time.time() - t0

    if profiler is not None:
        print("# segment profile (CC_TPU_PROFILE: sync points inserted; "
              "wall-clock not comparable to an unprofiled run)",
              file=sys.stderr)
        for line in profiler.table().splitlines():
            print(f"# {line}", file=sys.stderr)

    total_props = sum(len(r.proposals) for r in results)
    print(f"# proposals={total_props} "
          f"replica_moves={sum(r.num_replica_movements for r in results)} "
          f"violated_after={len(results[-1].violated_goals_after)} "
          f"balancedness={results[-1].balancedness_score():.1f}",
          file=sys.stderr)
    counts = results[-1].violated_broker_counts
    nonzero = {g: c for g, c in counts.items() if any(c)}
    print("# violated broker counts (before->after-own->after-all): "
          + (", ".join(f"{g}={b}->{o}->{a}"
                       for g, (b, o, a) in nonzero.items())
             or "none"), file=sys.stderr)
    print("# rounds by goal: "
          + (", ".join(f"{g}={r}" for g, r in
                       results[-1].rounds_by_goal.items()) or "n/a"),
          file=sys.stderr)
    # vs_baseline is a TARGET ratio (5 s north star / measured), not a
    # measured-reference comparison: no JVM exists in this environment to
    # run the reference GoalOptimizer (see BASELINE.md "measurement
    # status").  > 1 beats the target.
    print(f"# vs_baseline below = target_ratio ({TARGET_SECONDS:g}s "
          f"north-star / measured); reference CPU baseline unmeasured "
          f"(no JVM), see BASELINE.md", file=sys.stderr)
    print(json.dumps({
        "metric": (f"{label} {state.num_brokers}b/"
                   f"{state.num_partitions/1000:g}Kp rf{rf} [{backend}]"),
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / elapsed, 3),
    }))


def _scenario_bench() -> None:
    """BENCH_CONFIG=scenario: measure the batched what-if engine at
    K = BENCH_SCENARIO_BATCHES scenarios per program (default 1,8,32).

    Per batch size the engine runs TWICE: the first pass pays the
    vmapped-program compile (recorded), the second measures the warm
    solve — per-scenario latency is warm-solve / K.  The amortization
    verdict (vs_baseline) compares per-scenario latency at the largest K
    against the K=1 batch — same model, same goal list."""
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ[
                          "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

    from cruise_control_tpu.analyzer.context import BalancingConstraint
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.scenario.engine import ScenarioEngine
    from cruise_control_tpu.scenario.spec import ScenarioSpec

    num_b = int(os.environ.get("BENCH_BROKERS", 200))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 20_000))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 64))
    goal_names = os.environ.get("BENCH_GOALS")
    names = (goal_names.split(",") if goal_names
             else ["RackAwareGoal", "DiskCapacityGoal",
                   "ReplicaDistributionGoal", "DiskUsageDistributionGoal"])
    batches = [int(k) for k in os.environ.get(
        "BENCH_SCENARIO_BATCHES", "1,8,32").split(",") if k.strip()]
    if 1 not in batches:
        # vs_baseline is defined as K=1-per-scenario / largest-K: always
        # measure the K=1 baseline rather than silently substituting the
        # smallest requested batch
        batches = [1] + batches

    backend = jax.devices()[0].platform
    state, topo = _build("2", num_b, num_p, rf)
    print(f"# scenario bench: B={state.num_brokers} "
          f"P={state.num_partitions} R={state.num_replicas} goals={names} "
          f"batches={batches} [{backend}]", file=sys.stderr)

    constraint = BalancingConstraint()
    optimizer = GoalOptimizer(
        default_goals(max_rounds=rounds, names=names), constraint,
        pipeline_segment_size=int(os.environ.get("BENCH_SEGMENT", 2)))
    engine = ScenarioEngine(
        lambda g: optimizer if g is None else GoalOptimizer(
            default_goals(max_rounds=rounds, names=g), constraint),
        constraint, max_batch_size=max(batches))

    def specs_for(k: int):
        # base + distinct load-scale variants: different solves, one shape
        out = [ScenarioSpec(name="base")]
        for i in range(1, k):
            out.append(ScenarioSpec(
                name=f"grow-{i}",
                load_scale={"disk": 1.0 + 0.05 * i,
                            "nw_in": 1.0 + 0.03 * i}))
        return out

    results = {}
    for k in batches:
        specs = specs_for(k)
        cold = engine.evaluate(state, topo, specs,
                               include_proposals=False)
        warm = engine.evaluate(state, topo, specs,
                               include_proposals=False)
        infeasible = sum(1 for o in warm.outcomes if not o.feasible)
        results[str(k)] = {
            "compile_s": round(cold.compile_s, 3),
            "cold_solve_s": round(cold.solve_s, 3),
            "warm_solve_s": round(warm.solve_s, 3),
            "per_scenario_s": round(warm.solve_s / k, 4),
            "oom_halvings": cold.oom_halvings + warm.oom_halvings,
            "rung": warm.rung,
            "infeasible": infeasible,
        }
        print(f"# K={k}: compile {results[str(k)]['compile_s']}s, warm "
              f"solve {results[str(k)]['warm_solve_s']}s "
              f"({results[str(k)]['per_scenario_s']}s/scenario), "
              f"rung={warm.rung}", file=sys.stderr)

    k_max = str(max(batches))
    per_max = results[k_max]["per_scenario_s"]
    per_one = results["1"]["per_scenario_s"]
    print(json.dumps({
        "metric": (f"scenario what-if batch K={k_max} "
                   f"{state.num_brokers}b/{state.num_partitions/1000:g}Kp "
                   f"rf{rf} [{backend}]"),
        "value": per_max,
        "unit": "s",
        # amortization factor: K=1 per-scenario latency / largest-K
        # per-scenario latency (>1 = batching wins)
        "vs_baseline": round(per_one / per_max, 3) if per_max else 0.0,
        "scenario": results,
    }))


if __name__ == "__main__":
    main()

