"""Benchmark: full multi-goal rebalance proposal generation.

North-star config (BASELINE.json): 2,600 brokers / 200K partitions, full
default goal stack, target < 5 s wall-clock on TPU — ≥30× the reference's
CPU GoalOptimizer.  Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}
`vs_baseline` is target_seconds / measured_seconds (>1 beats the 5 s
north-star target).

Env knobs: BENCH_BROKERS, BENCH_PARTITIONS, BENCH_RF, BENCH_ROUNDS,
BENCH_GOALS (comma list), BENCH_SKIP_WARMUP.
"""
import json
import os
import sys
import time

TARGET_SECONDS = 5.0

# persistent compile cache: segment programs at 2.6K-broker scale take
# minutes to compile; retries and re-runs must not pay that twice
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def main() -> None:
    t_import = time.time()
    import jax
    import numpy as np

    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                           random_cluster)

    num_b = int(os.environ.get("BENCH_BROKERS", 2600))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 200_000))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 128))
    goal_names = os.environ.get("BENCH_GOALS")
    names = goal_names.split(",") if goal_names else None

    backend = jax.devices()[0].platform
    print(f"# backend={backend} devices={jax.devices()} "
          f"(import+init {time.time()-t_import:.1f}s)", file=sys.stderr)

    t0 = time.time()
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=num_b, num_partitions=num_p, replication_factor=rf,
        num_racks=max(8, num_b // 100), num_topics=max(8, num_p // 2000),
        seed=4, skew_fraction=0.2))
    print(f"# model built: B={num_b} P={num_p} R={num_p*rf} "
          f"({time.time()-t0:.1f}s)", file=sys.stderr)

    goals = default_goals(max_rounds=rounds, names=names)
    segment = int(os.environ.get("BENCH_SEGMENT", 2))
    optimizer = GoalOptimizer(goals, pipeline_segment_size=segment)

    def run_with_retry(tag):
        # the remote-compile/device transport can drop long requests;
        # compiled segments persist, so a retry resumes where it failed
        for attempt in range(4):
            try:
                return optimizer.optimizations(
                    state, topo, OptimizationOptions(), check_sanity=False)
            except jax.errors.JaxRuntimeError as exc:
                print(f"# {tag} attempt {attempt} hit transport error: "
                      f"{str(exc).splitlines()[0][:120]}", file=sys.stderr)
                time.sleep(10.0)
        return optimizer.optimizations(state, topo, OptimizationOptions(),
                                       check_sanity=False)

    # warm-up run compiles every goal kernel for these shapes; the measured
    # run reuses the compile cache (the JVM reference likewise amortizes
    # JIT warmup outside its proposal-computation timer)
    if not os.environ.get("BENCH_SKIP_WARMUP"):
        t0 = time.time()
        run_with_retry("warmup")
        print(f"# warmup (compile) {time.time()-t0:.1f}s", file=sys.stderr)

    t0 = time.time()
    result = optimizer.optimizations(state, topo, OptimizationOptions(),
                                     check_sanity=False)
    elapsed = time.time() - t0

    print(f"# proposals={len(result.proposals)} "
          f"replica_moves={result.num_replica_movements} "
          f"violated_after={len(result.violated_goals_after)} "
          f"balancedness={result.balancedness_score():.1f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": (f"full-stack proposal generation "
                   f"{num_b}b/{num_p//1000}Kp rf{rf} [{backend}]"),
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_SECONDS / elapsed, 3),
    }))


if __name__ == "__main__":
    main()
