"""Virtual-mesh scaling of the hot round path.

Measures steady-state round wall-clock of the flagship goal kernels at a
fixed model size while the device count grows (1 → N virtual CPU
devices), with the broker-table planes sharded via
parallel.mesh.solver_mesh.  CPU collectives are memcpys, so the numbers
are a LAYOUT check (does the sharded program partition the work and
execute, and does per-round time not explode with device count), not an
ICI-bandwidth projection — real multi-chip hardware is unavailable here
(see PARITY.md §multi-chip scaling for the recorded table).

Usage: python tools/bench_mesh_scaling.py [replicas] [devices...]
"""
import os
import sys
import time

DEVICES = [int(d) for d in sys.argv[2:]] or [1, 2, 4, 8]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

# a platform hook (sitecustomize) may have imported jax already with the
# axon TPU backend registered — env vars alone are then a no-op and the
# "virtual mesh" would silently target the one real TPU chip (and fight
# any concurrent bench for it).  force_cpu_devices applies jax.config
# updates that still take effect pre-computation.
from cruise_control_tpu.testing.virtual_mesh import (  # noqa: E402
    force_cpu_devices)

force_cpu_devices(max(DEVICES))

import jax  # noqa: E402

from cruise_control_tpu.analyzer.context import (  # noqa: E402
    BalancingConstraint, OptimizationOptions, make_context)
from cruise_control_tpu.analyzer.goals.registry import (  # noqa: E402
    default_goals)
from cruise_control_tpu.parallel.mesh import (  # noqa: E402
    make_mesh, shard_state, solver_mesh, state_shardings)
from cruise_control_tpu.testing.random_cluster import (  # noqa: E402
    RandomClusterSpec, random_cluster)


def main() -> None:
    num_r = int(sys.argv[1]) if len(sys.argv) > 1 else 60_000
    num_p = num_r // 3
    num_b = max(16, num_r // 230)
    state0, topo = random_cluster(RandomClusterSpec(
        num_brokers=num_b, num_partitions=num_p, replication_factor=3,
        num_racks=8, num_topics=12, seed=7, skew_fraction=0.2))
    rounds = int(os.environ.get("SCALING_ROUNDS", "24"))
    goals = default_goals(max_rounds=rounds, names=[
        "DiskUsageDistributionGoal", "CpuUsageDistributionGoal",
        "LeaderReplicaDistributionGoal"])

    def step(st, c):
        for i, goal in enumerate(goals):
            st = goal.optimize(st, c, tuple(goals[:i]))
        return st

    print(f"# model: B={num_b} P={num_p} R={state0.num_replicas} "
          f"goals={[g.name for g in goals]} rounds<={rounds}")
    base_s = None
    for n in DEVICES:
        mesh = make_mesh(jax.devices()[:n])
        sharded = shard_state(state0, mesh)
        ctx = make_context(sharded, BalancingConstraint(),
                           OptimizationOptions(), topo)
        with solver_mesh(mesh):
            fn = jax.jit(step, in_shardings=(
                state_shardings(sharded, mesh), None))
            with mesh:
                t0 = time.time()
                out = fn(sharded, ctx)
                jax.block_until_ready(out.replica_broker)
                compile_s = time.time() - t0
                best = float("inf")
                for _ in range(2):
                    t0 = time.time()
                    out = fn(sharded, ctx)
                    jax.block_until_ready(out.replica_broker)
                    best = min(best, time.time() - t0)
        base_s = base_s or best
        print(f"devices={n}: run={best:.2f}s (compile+first {compile_s:.1f}s)"
              f" speedup_vs_1dev={base_s / best:.2f}x")


if __name__ == "__main__":
    main()
