"""Operator CLI: submit a what-if scenario sweep, poll the async user
task, print the ranked report.

Drives a RUNNING cruise-control-tpu REST server through the SCENARIOS
endpoint (the spec list rides in the JSON request body; see
docs/SCENARIOS.md for the format).  The sweep is dry-run by
construction — the engine ranks hypotheticals, it never executes them.

Usage:
    python tools/scenario_sweep.py --spec-file sweep.json \
        [--address http://127.0.0.1:9090/kafkacruisecontrol] \
        [--goals G1,G2] [--verbose] [--json] [--timeout 600]

`sweep.json` is either the full request body ({"scenarios": [...]}) or
a bare scenario list.  Exit code 0 when every scenario solved (feasible
or a clean infeasibility verdict), 1 on transport or engine errors.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from cruise_control_tpu.client.client import (CruiseControlClient,  # noqa: E402
                                              CruiseControlClientError)
from cruise_control_tpu.scenario.spec import (ScenarioSpec,  # noqa: E402
                                              ScenarioSpecError)


def _load_payload(path: str) -> dict:
    with open(path) as fh:
        payload = json.load(fh)
    if isinstance(payload, list):
        payload = {"scenarios": payload}
    # validate CLIENT-side before paying a round trip: the same parser
    # the server runs (scenario/spec.py), so errors read identically
    for s in payload.get("scenarios", []):
        ScenarioSpec.from_json(s)
    return payload


def _print_report(report: dict) -> None:
    batch = report.get("batch", {})
    print(f"# batch: {batch.get('numScenarios')} scenarios, "
          f"rung={batch.get('rung')}, "
          f"oom_halvings={batch.get('oomHalvings')}, "
          f"device_batches={batch.get('deviceBatchSizes')}, "
          f"compile={batch.get('compileS')}s "
          f"solve={batch.get('solveS')}s")
    base = report.get("base")
    if base:
        print(f"# base solve: balancedness={base.get('balancedness')} "
              f"moves={base.get('numReplicaMoves')} "
              f"violated_after={base.get('violatedGoalsAfter')}")
    header = (f"{'rank':>4}  {'scenario':<28} {'feasible':<9} "
              f"{'balance':>8} {'moves':>7} {'data MB':>10}  vs base")
    print(header)
    print("-" * len(header))
    for i, s in enumerate(report.get("scenarios", []), 1):
        vs = s.get("vsBase") or {}
        delta = vs.get("balancednessDelta")
        note = (f"{delta:+.2f}" if delta is not None else "-")
        if not s.get("feasible"):
            note = s.get("reason", "infeasible")[:48]
        print(f"{i:>4}  {s['name']:<28} {str(s['feasible']):<9} "
              f"{s.get('balancedness', 0):>8.2f} "
              f"{s.get('numReplicaMoves', 0):>7} "
              f"{s.get('dataToMoveMB', 0):>10.2f}  {note}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="scenario_sweep",
        description="Submit a what-if scenario sweep and print the "
                    "ranked report")
    parser.add_argument("--spec-file", required=True,
                        help="JSON request body or bare scenario list")
    parser.add_argument("-a", "--address",
                        default="http://127.0.0.1:9090/kafkacruisecontrol")
    parser.add_argument("--goals", help="CSV goal-list override")
    parser.add_argument("--no-base", action="store_true",
                        help="skip the implicit base solve")
    parser.add_argument("--verbose", action="store_true",
                        help="per-goal counts + proposals in the report")
    parser.add_argument("--json", action="store_true",
                        help="print the raw report JSON instead of the "
                             "table")
    parser.add_argument("--timeout", type=float, default=600.0,
                        help="seconds to poll the async user task")
    parser.add_argument("--user", help="basic-auth user:password")
    args = parser.parse_args(argv)

    try:
        payload = _load_payload(args.spec_file)
    except (OSError, json.JSONDecodeError, ScenarioSpecError) as exc:
        print(f"error: bad spec file: {exc}", file=sys.stderr)
        return 1

    auth = None
    if args.user:
        import base64
        auth = "Basic " + base64.b64encode(args.user.encode()).decode()
    client = CruiseControlClient(args.address, auth_header=auth,
                                 timeout_s=args.timeout)
    goals = (args.goals.split(",") if args.goals
             else payload.get("goals"))
    try:
        report = client.scenarios(
            payload.get("scenarios", []), goals=goals,
            include_base=(not args.no_base
                          and payload.get("includeBase", True)),
            verbose=args.verbose)
    except CruiseControlClientError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        _print_report(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
