"""`make bench-smoke`: a minutes-not-hours dispatch-budget gate.

Runs the fused goal pipeline (solver.fusion.enabled semantics:
analyzer/fusion.py megaprograms + the device-side convergence
early-exit) on a tiny CPU-sized cluster, then ASSERTS the ISSUE 16
dispatch economics on the warm solve:

  * watched device dispatches per solve <= len(fusion plan) + 2
    (pre + one per megaprogram + post — parallel/health.py counter);
  * at least 2x below the eager per-goal driver's 2 + 2G budget;
  * every fused `__seg_{start}_{stop}__` program actually dispatched;
  * the fused result carries the converged-at instrument for every goal.

Also runs the ISSUE 19 portfolio gate: a width-3 seeded portfolio over
a tiny 3-goal stack must solve FUSED in one batched pass, produce a
feasible winner never below the identity lane, and replay bit-for-bit
across two searches.

Exit 0 = all gates hold (one JSON summary line on stdout); exit 1 with
the violated gate on stderr otherwise.  Geometry via SMOKE_BROKERS /
SMOKE_PARTITIONS / SMOKE_ROUNDS; default is small enough for a CI CPU
(~a minute of compiles, seconds of solve).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    t_start = time.time()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax  # noqa: F401  (initialize before the package imports)

    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.parallel import health
    from cruise_control_tpu.testing.random_cluster import (
        RandomClusterSpec, random_cluster)

    num_b = int(os.environ.get("SMOKE_BROKERS", 12))
    num_p = int(os.environ.get("SMOKE_PARTITIONS", 240))
    rounds = int(os.environ.get("SMOKE_ROUNDS", 24))
    names = ["RackAwareGoal", "DiskCapacityGoal",
             "ReplicaDistributionGoal", "DiskUsageDistributionGoal",
             "LeaderReplicaDistributionGoal",
             "LeaderBytesInDistributionGoal"]
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=num_b, num_partitions=num_p, replication_factor=2,
        num_racks=4, num_topics=4, seed=7, skew_fraction=0.2))
    opt = GoalOptimizer(default_goals(max_rounds=rounds, names=names),
                        pipeline_segment_size=2, fused_segments=True)
    plan = opt._plan_segments()
    options = OptimizationOptions()

    t0 = time.time()
    opt.warmup(state, topo, options)
    opt.optimizations(state, topo, options, check_sanity=False)
    warm_s = time.time() - t0

    before = health.dispatch_count()
    t0 = time.time()
    result = opt.optimizations(state, topo, options, check_sanity=False)
    solve_s = time.time() - t0
    used = health.dispatch_count() - before
    budget = len(plan) + 2
    eager_cost = 2 + 2 * len(names)
    by_prog = health.dispatches_by_program()

    failures = []
    if not 0 < used <= budget:
        failures.append(f"dispatches {used} outside (0, {budget}] "
                        f"(plan {plan})")
    if eager_cost < 2 * used:
        failures.append(f"dispatches {used} not >=2x below the eager "
                        f"driver's {eager_cost}")
    for start, stop in plan:
        if by_prog.get(f"__seg_{start}_{stop}__", 0) < 1:
            failures.append(f"megaprogram __seg_{start}_{stop}__ never "
                            f"dispatched")
    conv = getattr(result, "converged_at_by_goal", {}) or {}
    if set(conv) != set(names):
        failures.append(f"converged-at instrument incomplete: "
                        f"{sorted(conv)} != {sorted(names)}")

    # portfolio gate (ISSUE 19): width-3 seeded portfolio, 3-goal stack,
    # max_programs=1 so all lanes share ONE batched program
    from cruise_control_tpu.analyzer.context import BalancingConstraint
    from cruise_control_tpu.portfolio.engine import PortfolioEngine
    from cruise_control_tpu.portfolio.mutate import make_portfolio
    from cruise_control_tpu.scenario.engine import ScenarioEngine

    p_names = ["RackAwareGoal", "DiskCapacityGoal",
               "ReplicaDistributionGoal"]
    constraint = BalancingConstraint()
    p_opt = GoalOptimizer(default_goals(max_rounds=rounds, names=p_names),
                          constraint, pipeline_segment_size=2)

    def p_factory(g):
        if g is None or list(g) == p_names:
            return p_opt
        return GoalOptimizer(default_goals(max_rounds=rounds,
                                           names=list(g)), constraint)

    engine = PortfolioEngine(ScenarioEngine(p_factory, constraint),
                             p_factory, constraint=constraint)
    cands = make_portfolio(p_names, seed=19, width=3, max_programs=1)
    t0 = time.time()
    p1 = engine.search(state, topo, cands, 19, options=options)
    p2 = engine.search(state, topo, cands, 19, options=options)
    portfolio_s = time.time() - t0
    ident = next(c for c in p1.candidates if c.candidate.index == 0)
    if p1.rung != "FUSED":
        failures.append(f"portfolio smoke did not run FUSED: {p1.rung}")
    if p1.winner is None or not p1.winner.feasible:
        failures.append("portfolio smoke found no feasible winner")
    elif ident.feasible and p1.winner.fitness < ident.fitness - 1e-9:
        failures.append(
            f"portfolio winner {p1.winner.fitness:.4f} worse than the "
            f"identity lane {ident.fitness:.4f}")

    def _fits(r):
        return [(c.candidate.index, round(c.fitness, 6))
                for c in r.candidates]

    if _fits(p1) != _fits(p2):
        failures.append("portfolio smoke not deterministic across runs")

    print(json.dumps({
        "metric": f"bench-smoke dispatch budget {num_b}b/{num_p}p",
        "dispatches": used,
        "budget": budget,
        "eager_dispatches": eager_cost,
        "plan": [list(p) for p in plan],
        "warmup_s": round(warm_s, 2),
        "solve_s": round(solve_s, 3),
        "total_s": round(time.time() - t_start, 2),
        "converged_at_by_goal": {g: int(c) for g, c in conv.items()},
        "portfolio": {
            "width": len(cands),
            "rung": p1.rung,
            "winner_index": (p1.winner.candidate.index
                             if p1.winner is not None else None),
            "winner_fitness": (round(p1.winner.fitness, 4)
                               if p1.winner is not None else None),
            "identity_fitness": (round(ident.fitness, 4)
                                 if ident.feasible else None),
            "search_s": round(portfolio_s, 2),
        },
        "ok": not failures,
    }))
    for f in failures:
        print(f"# bench-smoke GATE FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
