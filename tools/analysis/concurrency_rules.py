"""Concurrency lint on the project call graph.

Three rules over the lock facts `project.py` extracts (lock-attribute
identities with Condition aliasing, lexically-held sets at every call
site and attribute write, thread spawn roots):

  * C201 — lock-order cycles.  Holding A while acquiring B (directly,
    or anywhere in the transitive callees of a call made under A) adds
    the edge A->B to the fleet-wide lock-order graph; a cycle means two
    paths acquire the same pair in opposite orders — the classic
    AB/BA deadlock, invisible per-file because each side is locally
    consistent.
  * C202 — re-entry into a non-reentrant lock: holding `threading.Lock`
    A and reaching (again: transitively) a second acquisition of A.
    This is the registry self-deadlock class — a method that takes the
    lock calling a sibling that takes it again.
  * C203 — unlocked shared writes: an instance attribute of a
    lock-owning class written with NO lock held, in a method reachable
    from both a background thread (threading.Thread target) and the
    request side (REST/facade entry points).  A class that owns a lock
    has declared its state shared; a bare write to that state from a
    dual-reachable method is either a missing `with self._lock:` or a
    `_locked`-suffix contract violation.

Precision notes (documented limitations, mirrored in the fixture
tests): only statically-resolved call edges propagate lock facts (an
unresolved dynamic call contributes nothing — under-approximation, no
false cycles from wild attribution); `*_locked`-named methods and
methods only ever called with a lock of their own class held are
treated as lock-protected for C203; `__init__`/`__enter__`/`__exit__`
and `start`/`stop`-shaped lifecycle setup is exempt from C203 (single-
threaded by construction).
"""
from __future__ import annotations

from typing import Dict, List, Set, Tuple

from .framework import Finding
from .project import LockId, Project, lock_kind

#: lifecycle methods whose writes are setup/teardown, not steady-state
#: shared mutation
_LIFECYCLE_METHODS = frozenset({
    "__init__", "__enter__", "__exit__", "__del__", "close",
})


def _acquired_sets(project: Project) -> Dict[str, Set[LockId]]:
    """Fixpoint: every lock a function may acquire, directly or through
    resolved callees."""
    acq: Dict[str, Set[LockId]] = {
        q: {a.lock for a in fi.acquisitions}
        for q, fi in project.functions.items()}
    changed = True
    while changed:
        changed = False
        for q, fi in project.functions.items():
            cur = acq[q]
            before = len(cur)
            for callee in project.callees(q):
                cur.update(acq.get(callee, ()))
            if len(cur) != before:
                changed = True
    return acq


def _fmt_lock(lock: LockId) -> str:
    owner, attr = lock
    short = owner.split(".", 1)[1] if "." in owner else owner
    return f"{short}.{attr}"


def lock_order_edges(project: Project) -> Dict[Tuple[LockId, LockId],
                                               Tuple[str, int]]:
    """{(held, acquired): (function qname, line)} — one witness per
    ordered pair, from direct nesting and from calls made under a
    lock into callees that acquire."""
    acq = _acquired_sets(project)
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
    for q, fi in project.functions.items():
        for a in fi.acquisitions:
            for held in a.held_before:
                if held != a.lock:
                    edges.setdefault((held, a.lock), (q, a.lineno))
        for call in fi.calls:
            if not call.held:
                continue
            inner: Set[LockId] = set()
            for target in call.targets:
                inner.update(acq.get(target, ()))
            for held in call.held:
                for got in inner:
                    if got != held:
                        edges.setdefault((held, got), (q, call.lineno))
    return edges


def lock_order_cycles(project: Project) -> List[List[LockId]]:
    """Elementary cycles in the lock-order graph (DFS, deduplicated by
    rotation)."""
    edges = lock_order_edges(project)
    graph: Dict[LockId, Set[LockId]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[List[LockId]] = []
    seen_keys: Set[Tuple[LockId, ...]] = set()

    def dfs(start: LockId, cur: LockId, path: List[LockId],
            visited: Set[LockId]) -> None:
        for nxt in sorted(graph.get(cur, ())):
            if nxt == start and len(path) > 1:
                rot = min(range(len(path)),
                          key=lambda i: path[i])
                key = tuple(path[rot:] + path[:rot])
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(list(key))
            elif nxt not in visited and nxt > start:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for node in sorted(graph):
        dfs(node, node, [node], {node})
    return cycles


def _cycle_findings(project: Project) -> List[Finding]:
    edges = lock_order_edges(project)
    findings: List[Finding] = []
    for cycle in lock_order_cycles(project):
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        witnesses = []
        for a, b in pairs:
            q, line = edges[(a, b)]
            mod = project.functions[q].module.replace(".", "/")
            witnesses.append(
                f"{_fmt_lock(a)} -> {_fmt_lock(b)} at {q} "
                f"({mod}.py:{line})")
        first = edges[pairs[0]]
        fi = project.functions[first[0]]
        path = str(project.modules[fi.module].path)
        findings.append(Finding(
            "C201", path, first[1],
            "lock-order cycle: "
            + "; ".join(witnesses)
            + " — pick one global order for these locks and acquire "
              "them in it on every path [C201]",
            symbol=first[0]))
    return findings


def _reentry_findings(project: Project) -> List[Finding]:
    acq = _acquired_sets(project)
    findings: List[Finding] = []
    for q, fi in project.functions.items():
        mod = project.modules.get(fi.module)
        if mod is None:
            continue
        path = str(mod.path)
        for a in fi.acquisitions:
            if a.lock in a.held_before \
                    and lock_kind(project, a.lock) == "lock":
                findings.append(Finding(
                    "C202", path, a.lineno,
                    f"re-entry into non-reentrant lock "
                    f"{_fmt_lock(a.lock)}: already held when acquired "
                    f"again — this self-deadlocks; hoist the work out "
                    f"of the locked region or split a _locked helper "
                    f"[C202]",
                    symbol=q))
        for call in fi.calls:
            for held in call.held:
                if lock_kind(project, held) != "lock":
                    continue
                for target in call.targets:
                    if held in acq.get(target, ()):
                        findings.append(Finding(
                            "C202", path, call.lineno,
                            f"re-entry into non-reentrant lock "
                            f"{_fmt_lock(held)}: held here while "
                            f"calling {target.split('.', 1)[-1]} which "
                            f"acquires it again — this self-deadlocks "
                            f"[C202]",
                            symbol=q))
    return findings


def _lock_protected_set(project: Project) -> Set[str]:
    """Functions whose body always runs with a lock of their own class
    held: `*_locked`-named methods (the package's contract), and —
    propagated to a fixpoint — methods whose every resolved call edge
    either lexically holds a lock of the same class or comes from an
    already-protected same-class method.  This is how
    `evaluate -> with _eval_lock: _evaluate_locked -> _solve_chunk`
    extends the lock's cover to the helpers under it."""
    by_name: Set[str] = {
        q for q, fi in project.functions.items()
        if fi.name.endswith("_locked")}
    call_sites: Dict[str, List[Tuple[str, Tuple[LockId, ...]]]] = {}
    for q, fi in project.functions.items():
        for call in fi.calls:
            for target in call.targets:
                call_sites.setdefault(target, []).append((q, call.held))
    # greatest fixpoint (optimistic init, then strip): recursion —
    # `_solve_chunk` re-entering itself on OOM halving — must not block
    # the cover from reaching a self-calling helper
    protected: Set[str] = by_name | {
        q for q, fi in project.functions.items()
        if fi.cls is not None and call_sites.get(q)}
    changed = True
    while changed:
        changed = False
        for q in list(protected):
            if q in by_name:
                continue
            fi = project.functions[q]
            ok = all(any(h[0] == fi.cls for h in held)
                     or (project.functions[caller].cls == fi.cls
                         and caller in protected)
                     for caller, held in call_sites.get(q, ()))
            if not ok:
                protected.discard(q)
                changed = True
    return protected


def _shared_write_findings(project: Project) -> List[Finding]:
    bg_roots: Set[str] = set()
    for fi in project.functions.values():
        bg_roots.update(fi.thread_targets)
    req_roots = project.entry_points()
    bg_reach = project.transitive_callees(bg_roots)
    req_reach = project.transitive_callees(req_roots)
    dual = bg_reach & req_reach
    protected = _lock_protected_set(project)
    findings: List[Finding] = []
    for q in sorted(dual):
        fi = project.functions.get(q)
        if fi is None or fi.cls is None or not fi.writes:
            continue
        if fi.name in _LIFECYCLE_METHODS or q in protected:
            continue
        ci = project.classes.get(fi.cls)
        if ci is None or not ci.lock_attrs:
            continue              # class declares no lock: out of scope
        mod = project.modules.get(fi.module)
        path = str(mod.path) if mod else fi.module
        for w in fi.writes:
            if w.held:
                continue
            if w.attr in ci.lock_attrs:
                continue          # binding the lock itself
            if w.attr not in ci.instance_attrs:
                continue
            findings.append(Finding(
                "C203", path, w.lineno,
                f"unlocked write to shared attribute self.{w.attr} in "
                f"{fi.cls.split('.')[-1]}.{fi.name} — reachable from "
                f"both a background thread and request threads with "
                f"no lock in scope; wrap it in `with "
                f"self.{sorted(ci.lock_attrs)[0]}:` or move it behind "
                f"a _locked helper [C203]",
                symbol=q))
    return findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(_cycle_findings(project))
    findings.extend(_reentry_findings(project))
    findings.extend(_shared_write_findings(project))
    return findings
