"""Drift rules: three sources of truth, machine-checked to agree.

  * D301/D302/D303 — config keys.  Declared = every `d.define("...")`
    in the package, INCLUDING f-string defines (the per-class SLO loop
    in `slo_config_def`): a JoinedStr define becomes a segment pattern
    with `{...}` parts as wildcards, so `slo.precompute.latency.ms`
    matches declared pattern `slo.*.latency.ms`.  Read = constant keys
    at `get_long/get_int/...` use sites plus `.get("dotted.key")` on
    config-named receivers (dict `.get` on non-config receivers is not
    a config read).  Documented = the key column of
    docs/CONFIGURATION.md's tables.  Any pairwise disagreement is a
    finding at the offending site.
  * D310/D311 — sensor names.  Every constant sensor name at a
    registry call site (counter/meter/timer/histogram/gauge and their
    update_* forms), plus constants flowing through first-order
    forwarder helpers (`Scheduler._mark("sched-dispatches")`), is
    mapped through THE canonical OpenMetrics transform (mirrored from
    utils/metrics.canonical_sensor_name; a unit test pins the mirror
    against the real one).  Two raw names on one canonical family are
    a collision at analysis time instead of a register-time crash;
    degenerate names that canonicalize to the empty fallback are
    invalid.
  * D320/D321 — fault sites.  Every `faults.inject("site")` armed in
    the package must be exercised somewhere under tests/ and named in
    docs/OPERATIONS.md — an injection point nobody scripts is dead
    chaos coverage, and one operators cannot read about is a prod
    footgun.
  * D322 — required fault sites.  The inverse direction:
    REQUIRED_FAULT_SITES lists injection points a subsystem's
    degradation contract PROMISES (`portfolio.search` since ISSUE 19);
    when the subsystem's modules are present but nothing arms the
    site, the chaos tests that script it silently inject nothing.
  * D330/D331 — goal fusion groups.  `analyzer/fusion.
    GOAL_FUSION_GROUPS` and `goals/registry.GOAL_CLASSES` must cover
    each other exactly: a registered goal in no group silently falls
    back to width-chunking under solver.fusion.enabled (D330); a group
    member that is not a registered goal — or sits in two groups — can
    never match a stack (D331).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .framework import Finding
from .project import Project, _call_name, _terminal_name

_GET_METHODS = {"get_long", "get_int", "get_string", "get_boolean",
                "get_double", "get_list", "get_configured_instance",
                "get_configured_instances"}

_REGISTRY_METHODS = {"counter", "meter", "timer", "update_timer",
                     "histogram", "update_histogram", "gauge"}

#: mirror of utils/metrics.canonical_sensor_name — pinned against the
#: real implementation by tests/test_analysis.py (the analyzer must not
#: import the analyzed package)
_INVALID_METRIC_CHARS = re.compile(r"[^a-zA-Z0-9_]")
OPENMETRICS_PREFIX = "cc_tpu_"


def canonical_sensor_name(name: str) -> str:
    out = _INVALID_METRIC_CHARS.sub("_", name.strip()).lower()
    out = out.strip("_") or "sensor"
    if out[0].isdigit():
        out = "_" + out
    return OPENMETRICS_PREFIX + out


# ----------------------------------------------------------------------
# config keys
# ----------------------------------------------------------------------

def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _joinedstr_pattern(node: ast.JoinedStr) -> Optional[str]:
    """Regex for an f-string key: literal parts escaped, `{...}` parts
    wildcarded within a dotted segment."""
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(re.escape(v.value))
        elif isinstance(v, ast.FormattedValue):
            parts.append(r"[^.]+")
        else:
            return None
    return "".join(parts)


def _collect_config_decls(project: Project):
    consts: Dict[str, Tuple[str, int]] = {}
    patterns: List[Tuple[re.Pattern, str, int]] = []
    for mod in project.files:
        if mod.rel is None or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node.func) != "define" or not node.args:
                continue
            arg = node.args[0]
            key = _const_str(arg)
            if key is not None:
                consts.setdefault(key, (str(mod.path), node.lineno))
            elif isinstance(arg, ast.JoinedStr):
                pat = _joinedstr_pattern(arg)
                if pat is not None:
                    patterns.append((re.compile(pat + r"\Z"),
                                     str(mod.path), node.lineno))
    return consts, patterns


def _collect_config_reads(project: Project):
    reads: List[Tuple[str, str, int]] = []
    for mod in project.files:
        if mod.rel is None or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            key = _const_str(node.args[0])
            if key is None or "." not in key:
                continue
            if func.attr in _GET_METHODS:
                reads.append((key, str(mod.path), node.lineno))
            elif func.attr == "get":
                recv = _terminal_name(func.value).lower()
                if "config" in recv:
                    reads.append((key, str(mod.path), node.lineno))
    return reads


def _documented_keys(doc_path: Path) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    if not doc_path.exists():
        return out
    for i, line in enumerate(doc_path.read_text().splitlines(), 1):
        m = re.match(r"^\|\s*([A-Za-z0-9._]+)\s*\|", line)
        if not m:
            continue
        key = m.group(1)
        if key == "name" or set(key) <= {"-", "."}:
            continue              # table header / separator rows
        out.append((key, i))
    return out


def _config_rules(project: Project, root: Path) -> List[Finding]:
    consts, patterns = _collect_config_decls(project)
    if not consts:
        return []                 # fixture trees without a config layer
    declared_match = (lambda key: key in consts or any(
        p.match(key) for p, _, _ in patterns))
    findings: List[Finding] = []
    for key, path, line in _collect_config_reads(project):
        if not declared_match(key):
            findings.append(Finding(
                "D301", path, line,
                f"config key '{key}' read here but never declared in "
                f"the typed ConfigDef — declare it (with type, "
                f"default, validator, doc) or the overlay silently "
                f"accepts typos [D301]"))
    doc_path = root / "docs" / "CONFIGURATION.md"
    documented = _documented_keys(doc_path)
    documented_set = {k for k, _ in documented}
    for key, (path, line) in sorted(consts.items()):
        if documented and key not in documented_set:
            findings.append(Finding(
                "D302", path, line,
                f"config key '{key}' declared here but missing from "
                f"docs/CONFIGURATION.md — regenerate it with "
                f"`python -m cruise_control_tpu.config.docgen` [D302]"))
    for key, line in documented:
        if not declared_match(key):
            findings.append(Finding(
                "D303", str(doc_path), line,
                f"config key '{key}' documented here but not declared "
                f"in any ConfigDef — stale docs; regenerate with "
                f"`python -m cruise_control_tpu.config.docgen` [D303]"))
    return findings


# ----------------------------------------------------------------------
# sensor names
# ----------------------------------------------------------------------

def _sensor_forwarders(project: Project) -> Dict[str, int]:
    """{function qname: positional index of the sensor-name param}: a
    helper whose body passes one of its parameters as the name argument
    of a registry call (first-order indirection, e.g. Scheduler._mark).
    """
    out: Dict[str, int] = {}
    for q, fi in project.functions.items():
        if fi.node is None:
            continue
        params = [a.arg for a in fi.node.args.args]
        if params and params[0] == "self":
            params = params[1:]
        if not params:
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node.func) not in _REGISTRY_METHODS:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in params:
                # positional index at the CALL SITE (self is not passed
                # explicitly there)
                out[q] = params.index(arg.id)
                break
    return out


def _collect_sensor_names(project: Project):
    """{raw name: (path, line) of first site}."""
    forwarders = _sensor_forwarders(project)
    sites: Dict[str, Tuple[str, int]] = {}
    for mod in project.files:
        if mod.rel is None or mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node.func) in _REGISTRY_METHODS:
                raw = _const_str(node.args[0])
                if raw is not None:
                    sites.setdefault(raw, (str(mod.path), node.lineno))
        fns = list(mod.functions.values())
        for ci in mod.classes.values():
            fns.extend(ci.methods.values())
        for fi in fns:
            for call in fi.calls:
                for target in call.targets:
                    idx = forwarders.get(target)
                    if idx is None or len(call.node.args) <= idx:
                        continue
                    raw = _const_str(call.node.args[idx])
                    if raw is not None:
                        sites.setdefault(
                            raw, (str(mod.path), call.lineno))
    return sites


def _sensor_rules(project: Project) -> List[Finding]:
    sites = _collect_sensor_names(project)
    findings: List[Finding] = []
    by_canonical: Dict[str, List[str]] = {}
    for raw, (path, line) in sorted(sites.items()):
        canon = canonical_sensor_name(raw)
        by_canonical.setdefault(canon, []).append(raw)
        if canon == OPENMETRICS_PREFIX + "sensor" or raw != raw.strip():
            findings.append(Finding(
                "D310", path, line,
                f"sensor name {raw!r} canonicalizes to a degenerate "
                f"OpenMetrics family ({canon}) — use "
                f"[a-z0-9-] words [D310]"))
    for canon, raws in sorted(by_canonical.items()):
        if len(raws) < 2:
            continue
        first = sites[raws[0]]
        others = ", ".join(repr(r) for r in raws[1:])
        findings.append(Finding(
            "D311", first[0], first[1],
            f"sensor names {raws[0]!r} and {others} collide on "
            f"OpenMetrics family {canon} — they would export as one "
            f"series; rename one [D311]"))
    return findings


# ----------------------------------------------------------------------
# fault sites
# ----------------------------------------------------------------------

#: subsystem-contract fault sites: injection points the architecture
#: PROMISES (each subsystem's degradation story depends on the site
#: existing).  If nothing in the package arms the site, the chaos tests
#: that script it silently stop injecting anywhere — D322 makes that a
#: finding at the module that is supposed to arm it.
REQUIRED_FAULT_SITES: Dict[str, str] = {
    "portfolio.search": "portfolio/engine.py",
}


def _armed_fault_sites(project: Project):
    sites: Dict[str, Tuple[str, int]] = {}
    for mod in project.files:
        if mod.rel is None or mod.tree is None:
            continue
        if mod.rel == "utils/faults.py":
            continue              # the harness itself
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if _call_name(func) != "inject":
                continue
            if isinstance(func, ast.Attribute) \
                    and _terminal_name(func.value) != "faults":
                continue
            site = _const_str(node.args[0])
            if site is not None:
                sites.setdefault(site, (str(mod.path), node.lineno))
    return sites


def _fault_rules(project: Project, root: Path) -> List[Finding]:
    sites = _armed_fault_sites(project)
    if not sites:
        return []
    tests_text = ""
    tests_dir = root / "tests"
    if tests_dir.is_dir():
        for p in sorted(tests_dir.rglob("*.py")):
            tests_text += p.read_text()
    ops_path = root / "docs" / "OPERATIONS.md"
    ops_text = ops_path.read_text() if ops_path.exists() else ""
    findings: List[Finding] = []
    for site, (path, line) in sorted(sites.items()):
        if tests_text and site not in tests_text:
            findings.append(Finding(
                "D320", path, line,
                f"fault site '{site}' armed here but never exercised "
                f"under tests/ — script it in a chaos test or the "
                f"injection point is dead coverage [D320]"))
        if ops_text and site not in ops_text:
            findings.append(Finding(
                "D321", path, line,
                f"fault site '{site}' armed here but absent from "
                f"docs/OPERATIONS.md — operators must be able to look "
                f"up every injection point [D321]"))
    for site, expected_rel in sorted(REQUIRED_FAULT_SITES.items()):
        if site in sites:
            continue
        subsystem = expected_rel.rsplit("/", 1)[0] + "/"
        owner = next((mod for mod in project.files
                      if mod.rel is not None
                      and mod.rel.startswith(subsystem)), None)
        if owner is None:
            continue              # subsystem absent (fixture trees)
        findings.append(Finding(
            "D322", str(owner.path), 1,
            f"required fault site '{site}' is armed nowhere in the "
            f"package — the subsystem contract promises this "
            f"injection point (expected in {expected_rel}); chaos "
            f"tests that script it inject nothing [D322]"))
    return findings


# ----------------------------------------------------------------------
# goal registry <-> fusion groups
# ----------------------------------------------------------------------

def _assigned_dict(tree, name: str):
    """The ast.Dict literal assigned to `name` at module level, or
    None."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in targets):
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            return value
    return None


def _fusion_rules(project: Project) -> List[Finding]:
    """D330/D331: analyzer/fusion.GOAL_FUSION_GROUPS and
    goals/registry.GOAL_CLASSES must cover each other exactly.  A
    registered goal in no fusion group silently falls back to
    width-chunking (the megaprogram never forms); a group member not in
    the registry is a typo that can never match a stack.  Checked over
    the AST (the analyzer never imports the analyzed package)."""
    registry = fusion = None
    for mod in project.files:
        if mod.rel == "analyzer/goals/registry.py" and mod.tree:
            registry = mod
        elif mod.rel == "analyzer/fusion.py" and mod.tree:
            fusion = mod
    if registry is None or fusion is None:
        return []
    reg_dict = _assigned_dict(registry.tree, "GOAL_CLASSES")
    grp_dict = _assigned_dict(fusion.tree, "GOAL_FUSION_GROUPS")
    if reg_dict is None or grp_dict is None:
        return []
    registered: Dict[str, int] = {}
    for k in reg_dict.keys:
        name = _const_str(k)
        if name is not None:
            registered[name] = k.lineno
    grouped: Dict[str, Tuple[str, int]] = {}
    findings: List[Finding] = []
    for group_key, members in zip(grp_dict.keys, grp_dict.values):
        group = _const_str(group_key) or "?"
        if not isinstance(members, (ast.List, ast.Tuple)):
            continue
        for elt in members.elts:
            name = _const_str(elt)
            if name is None:
                continue
            if name in grouped:
                findings.append(Finding(
                    "D331", str(fusion.path), elt.lineno,
                    f"goal '{name}' appears in fusion groups "
                    f"'{grouped[name][0]}' and '{group}' — a goal "
                    f"fuses under exactly one group [D331]"))
                continue
            grouped[name] = (group, elt.lineno)
            if name not in registered:
                findings.append(Finding(
                    "D331", str(fusion.path), elt.lineno,
                    f"fusion group '{group}' names '{name}' which is "
                    f"not in goals/registry.GOAL_CLASSES — a typo here "
                    f"never matches a goal stack [D331]"))
    for name, line in sorted(registered.items()):
        if name not in grouped:
            findings.append(Finding(
                "D330", str(registry.path), line,
                f"registered goal '{name}' belongs to no "
                f"analyzer/fusion.GOAL_FUSION_GROUPS entry — with "
                f"solver.fusion.enabled it silently falls back to "
                f"width-chunking; add it to a group [D330]"))
    return findings


def run(project: Project, root: Path) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(_config_rules(project, root))
    findings.extend(_sensor_rules(project))
    findings.extend(_fault_rules(project, root))
    findings.extend(_fusion_rules(project))
    return findings
