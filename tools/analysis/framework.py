"""Finding/rule framework: stable IDs, suppressions, baseline, output.

Contract (docs/ANALYSIS.md):

  * every finding carries a stable rule id (F0xx flat per-file, G1xx
    gateway reachability, C2xx concurrency, D3xx drift);
  * `# cc-lint: disable=<RULE>[,<RULE>] -- <justification>` suppresses a
    finding on its own line, or on the next line when the comment stands
    alone.  The justification text after `--` is REQUIRED — a bare
    disable is itself a finding (F008) — and a suppression that matches
    nothing is a finding too (F009): suppressions cannot rot in place;
  * a checked-in baseline (tools/analysis/baseline.json) grandfathers
    pre-existing findings.  The gate is empty-or-shrinking: a baselined
    finding that no longer fires is a STALE entry and fails the run
    until pruned (`--prune-baseline`), and nothing in the tooling adds
    entries — a new finding is fixed or suppressed inline with a
    justification, never grandfathered;
  * exit code 0 = clean (suppressed/baselined included), 1 = findings
    or stale baseline entries, 2 = usage/internal error.

Human output stays byte-compatible with the historical flat lint for
the per-file rules (`path:line: message`); `--json` emits the full
structured records.
"""
from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, List, Set, Tuple

#: rule id -> one-line description (the catalog; docs/ANALYSIS.md is the
#: prose version and tests assert the two agree)
RULES: Dict[str, str] = {
    "F001": "file does not parse (syntax error)",
    "F002": "trailing whitespace",
    "F003": "tab in indentation",
    "F004": "line longer than the column budget",
    "F005": "missing final newline",
    "F006": "unused import (honoring __all__ and cross-module "
            "re-export resolution)",
    "F007": "fully-silent `except Exception` swallow",
    "F008": "cc-lint suppression without a justification",
    "F009": "cc-lint suppression that matches no finding",
    "G101": "solve-gateway bypass: GoalOptimizer/scenario/host-fallback "
            "solve reachable outside facade/sched gateway",
    "G102": "mesh-gateway bypass: Mesh/device acquisition outside the "
            "scheduler's mesh-token path",
    "G103": "cache-gateway bypass: XLA compile outside the persistent "
            "program-cache gateways",
    "G104": "store-gateway bypass: LoadMonitor model materialization "
            "outside the facade's store-aware gateway",
    "G105": "durable-write bypass: truncating write/rename outside "
            "utils/persist.py",
    "G106": "watchdog-gateway bypass: compiled executable invoked "
            "outside health.watched_call",
    "G107": "tenant-root violation: mutable module-level state in "
            "fleet-reachable modules",
    "G108": "trace-propagation violation: naked span construction, "
            "untraced SolveJob, or unspanned ladder attempt",
    "C201": "lock-order cycle: two locks acquired in opposite orders "
            "on different call paths",
    "C202": "re-entry into a non-reentrant lock along a call path",
    "C203": "shared attribute written without a lock while reachable "
            "from both a background thread and request threads",
    "D301": "config key read at a use site but never declared in the "
            "typed ConfigDef",
    "D302": "config key declared but missing from docs/CONFIGURATION.md",
    "D303": "config key documented in docs/CONFIGURATION.md but not "
            "declared",
    "D310": "sensor name that canonicalizes to an invalid OpenMetrics "
            "family",
    "D311": "two sensor names colliding on one canonical OpenMetrics "
            "family",
    "D320": "fault site armed in code but never exercised by tests/",
    "D321": "fault site armed in code but absent from "
            "docs/OPERATIONS.md",
    "D322": "subsystem-contract fault site armed nowhere in the "
            "package (REQUIRED_FAULT_SITES)",
}

_SUPPRESS_RE = re.compile(
    r"#\s*cc-lint:\s*disable=([A-Z0-9,\s]+?)"
    r"(?:\s*--\s*(.*?))?\s*$")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str                  #: full human text (byte-compatible for
    #: the ported flat rules)
    symbol: str = ""              #: enclosing qualname, for baselines

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "description": RULES.get(self.rule, "")}

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path,
                self.symbol or _strip_positions(self.message))


def _strip_positions(message: str) -> str:
    return re.sub(r"\b\d+\b", "#", message)


@dataclasses.dataclass
class Suppression:
    path: str
    line: int                     #: line the comment sits on
    rules: Tuple[str, ...]
    justification: str
    applies_to: Tuple[int, ...]   #: line numbers it covers
    used: bool = False


def scan_suppressions(path: str, text: str) -> List[Suppression]:
    """All `# cc-lint: disable=...` comments in a file.  A trailing
    comment covers its own line; a standalone comment line covers
    itself and the next code line (continuation comment lines — a
    multi-line justification — are skipped over, not targeted)."""
    out: List[Suppression] = []
    lines = text.splitlines()
    for i, line in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",")
                      if r.strip())
        justification = (m.group(2) or "").strip()
        applies = [i]
        if line.lstrip().startswith("#"):
            for j in range(i + 1, len(lines) + 1):
                stripped = lines[j - 1].strip()
                if stripped and not stripped.startswith("#"):
                    applies.append(j)
                    break
        out.append(Suppression(path=path, line=i, rules=rules,
                               justification=justification,
                               applies_to=tuple(applies)))
    return out


def apply_suppressions(
        findings: List[Finding],
        suppressions: List[Suppression]) -> Tuple[List[Finding],
                                                  List[Finding]]:
    """(kept, suppressed).  Bare suppressions (F008) and unused ones
    (F009) are appended to `kept` as findings of their own."""
    index: Dict[Tuple[str, int], List[Suppression]] = {}
    for sup in suppressions:
        for line in sup.applies_to:
            index.setdefault((sup.path, line), []).append(sup)
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        hits = [s for s in index.get((f.path, f.line), [])
                if f.rule in s.rules and s.justification]
        if hits:
            for s in hits:
                s.used = True
            suppressed.append(f)
        else:
            kept.append(f)
    for sup in suppressions:
        if not sup.justification:
            kept.append(Finding(
                rule="F008", path=sup.path, line=sup.line,
                message=(f"cc-lint suppression of "
                         f"{','.join(sup.rules)} without a "
                         f"justification — append `-- <why>` [F008]")))
        elif not sup.used:
            kept.append(Finding(
                rule="F009", path=sup.path, line=sup.line,
                message=(f"cc-lint suppression of "
                         f"{','.join(sup.rules)} matches no finding — "
                         f"remove it [F009]")))
    return kept, suppressed


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------

def load_baseline(path: Path) -> List[dict]:
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    return list(data.get("entries", []))


def write_baseline(path: Path, entries: List[dict]) -> None:
    payload = json.dumps({"version": 1, "entries": entries}, indent=2,
                         sort_keys=True) + "\n"
    path.write_text(payload)


def apply_baseline(findings: List[Finding],
                   entries: List[dict]) -> Tuple[List[Finding],
                                                 List[Finding],
                                                 List[dict]]:
    """(kept, baselined, stale_entries)."""
    keys = {(e.get("rule", ""), e.get("path", ""), e.get("key", "")): e
            for e in entries}
    matched: Set[Tuple[str, str, str]] = set()
    kept: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        key = f.baseline_key()
        if key in keys:
            matched.add(key)
            baselined.append(f)
        else:
            kept.append(f)
    stale = [e for k, e in keys.items() if k not in matched]
    return kept, baselined, stale
