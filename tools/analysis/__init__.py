"""Whole-program static analyzer for cruise_control_tpu (ISSUE 15).

Subsumes the historical per-file `tools/lint.py`: same flat hygiene
rules (byte-compatible output), plus what per-file lint cannot do — a
project-wide symbol table and call graph (`project.py`) on which the
nine gateway invariants become reachability checks (`gateway_rules.py`),
a concurrency lint over extracted lock facts (`concurrency_rules.py`),
and drift detection between code, config, docs and tests
(`drift_rules.py`).  Rule catalog, suppression and baseline workflow:
docs/ANALYSIS.md.

Dependency-free by constraint: plain `ast`, no imports of the analyzed
code, no third-party packages.
"""
from __future__ import annotations
