"""Analyzer entry point (`make lint` / `python tools/lint.py`).

Usage: python tools/lint.py [paths...] [options]

  paths              files/directories to analyze (default: the package,
                     tests/, tools/, bench.py, __graft_entry__.py)
  --json             structured findings on stdout instead of flat lines
  --baseline FILE    baseline file (default tools/analysis/baseline.json)
  --no-baseline      ignore the baseline (report everything)
  --prune-baseline   rewrite the baseline keeping only entries that
                     still fire (the only way the tooling ever WRITES
                     the baseline: it can shrink, never grow)

Exit codes: 0 clean; 1 findings (or stale baseline entries); 2 usage or
internal error.
"""
from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import List

from . import concurrency_rules, drift_rules, flat_rules, gateway_rules
from .framework import (Finding, apply_baseline, apply_suppressions,
                        load_baseline, scan_suppressions, write_baseline)
from .project import Project

DEFAULT_PATHS = ["cruise_control_tpu", "tests", "tools", "bench.py",
                 "__graft_entry__.py"]
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def collect_files(roots: List[Path]) -> List[Path]:
    files: List[Path] = []
    for root in roots:
        if root.is_dir():
            files.extend(sorted(root.rglob("*.py")))
        elif root.exists():
            files.append(root)
    return [f for f in files if "__pycache__" not in f.parts]


def analyze(paths: List[Path], root: Path) -> List[Finding]:
    """All findings (unsuppressed, un-baselined) for a parse set."""
    project = Project.build(paths)
    findings: List[Finding] = []
    findings.extend(flat_rules.run(project))
    findings.extend(gateway_rules.run(project))
    findings.extend(concurrency_rules.run(project))
    findings.extend(drift_rules.run(project, root))
    suppressions = []
    for mod in project.files:
        suppressions.extend(scan_suppressions(str(mod.path), mod.text))
    kept, _suppressed = apply_suppressions(findings, suppressions)
    return kept


def main(argv: List[str]) -> int:
    args = list(argv)
    as_json = "--json" in args
    no_baseline = "--no-baseline" in args
    prune = "--prune-baseline" in args
    baseline_path = DEFAULT_BASELINE
    for flag in ("--json", "--no-baseline", "--prune-baseline"):
        while flag in args:
            args.remove(flag)
    if "--baseline" in args:
        i = args.index("--baseline")
        try:
            baseline_path = Path(args[i + 1])
        except IndexError:
            print("lint: --baseline needs a file argument",
                  file=sys.stderr)
            return 2
        del args[i:i + 2]
    for a in args:
        if a.startswith("-"):
            print(f"lint: unknown option {a!r}", file=sys.stderr)
            return 2

    if no_baseline and prune:
        print("lint: --no-baseline and --prune-baseline are mutually "
              "exclusive (pruning against an ignored baseline would "
              "empty it)", file=sys.stderr)
        return 2

    roots = [Path(p) for p in (args or DEFAULT_PATHS)]
    files = collect_files(roots)
    root = Path.cwd()
    findings = analyze(files, root)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    entries = [] if no_baseline else load_baseline(baseline_path)
    # staleness is judged only against files actually analyzed: a
    # subset run (`lint.py cruise_control_tpu`) must neither fail on
    # nor prune away entries for files outside its parse set
    analyzed = {str(p) for p in files}
    scoped = [e for e in entries if e.get("path") in analyzed]
    kept, baselined, stale = apply_baseline(findings, scoped)

    if prune:
        remaining = [e for e in entries if e not in stale]
        write_baseline(baseline_path, remaining)
        print(f"lint: baseline pruned to {len(remaining)} entries "
              f"(removed {len(stale)})", file=sys.stderr)
        stale = []

    if as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in kept],
            "baselined": [f.to_json() for f in baselined],
            "staleBaseline": stale,
        }, indent=2, sort_keys=True))
    else:
        for f in kept:
            print(f.render())
        for e in stale:
            print(f"{e.get('path')}: stale baseline entry for "
                  f"{e.get('rule')} ({e.get('key')}) — the finding no "
                  f"longer fires; run --prune-baseline to shrink the "
                  f"baseline")
    print(f"lint: {len(files)} files, {len(kept)} findings"
          + (f", {len(baselined)} baselined" if baselined else "")
          + (f", {len(stale)} stale baseline entries" if stale else ""),
          file=sys.stderr)
    return 1 if (kept or stale) else 0
