"""Per-file rules, ported losslessly from the historical flat lint.

Messages are byte-compatible with the old `tools/lint.py` output so CI
diffs and muscle memory survive the migration; each finding additionally
carries its stable rule id for suppressions and the JSON output.

The one behavioral upgrade (the ISSUE-15 satellite): the unused-import
check no longer skips `__init__.py` by filename heuristic.  Re-export
resolution is real now — an import (in ANY module) is "used" when some
other parsed module imports that name *from this module*, or when the
module lists it in `__all__`.  A stale re-export in an `__init__.py`
that nobody imports is finally a finding.
"""
from __future__ import annotations

import ast
from typing import List

from .framework import Finding
from .project import PACKAGE, ModuleInfo, Project, _call_name

MAX_LINE = 100

#: a broad handler "signals" when its body calls something whose name
#: carries one of these tokens (logging, alerting, sensor increments,
#: error routing) — permissive by design: the rule exists to catch the
#: FULLY silent `except Exception: pass/return` shape
_HANDLER_SIGNAL_TOKENS = ("log", "warn", "error", "exception", "debug",
                          "info", "alert", "critical", "mark", "inc",
                          "update", "record", "report", "tolerate",
                          "quarantine", "fail")


def _catches_broad(handler_type) -> bool:
    types = (handler_type.elts if isinstance(handler_type, ast.Tuple)
             else [handler_type])
    return any(isinstance(t, ast.Name)
               and t.id in ("Exception", "BaseException") for t in types)


def _handler_signals(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _call_name(node.func).lower()
            if any(tok in name for tok in _HANDLER_SIGNAL_TOKENS):
                return True
    return False


def _whitespace_findings(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    path = str(mod.path)
    lines = mod.text.splitlines()
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            out.append(Finding("F002", path, i,
                               "trailing whitespace"))
        if line[:len(line) - len(line.lstrip())].count("\t"):
            out.append(Finding("F003", path, i, "tab in indentation"))
        if len(line) > MAX_LINE:
            out.append(Finding("F004", path, i,
                               f"line longer than {MAX_LINE} cols"))
    if mod.text and not mod.text.endswith("\n"):
        out.append(Finding("F005", path, len(lines),
                           "missing final newline"))
    return out


def _silent_swallows(mod: ModuleInfo) -> List[Finding]:
    if PACKAGE not in mod.path.parts:
        return []
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler) \
                and node.type is not None \
                and _catches_broad(node.type) \
                and not _handler_signals(node):
            out.append(Finding(
                "F007", str(mod.path), node.lineno,
                "silent `except Exception` swallow — log it, "
                "re-raise, or count it in a sensor"))
    return out


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    return used


def _unused_imports(mod: ModuleInfo, project: Project) -> List[Finding]:
    out: List[Finding] = []
    exported = mod.all_names or set()
    used = _used_names(mod.tree) | {"annotations", "conftest"}
    for name, node in mod.import_nodes.items():
        if name in used or name in exported:
            continue
        # real re-export resolution (not the old __init__.py filename
        # skip): the import is live when another parsed module imports
        # this name FROM this module
        if mod.dotted and (mod.dotted, name) in project.imported_symbols:
            continue
        out.append(Finding("F006", str(mod.path), node.lineno,
                           f"unused import '{name}'"))
    return out


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.files:
        if mod.syntax_error is not None:
            findings.append(Finding(
                "F001", str(mod.path), mod.syntax_error.lineno or 1,
                f"syntax error: {mod.syntax_error.msg}"))
            continue
        findings.extend(_whitespace_findings(mod))
        findings.extend(_silent_swallows(mod))
        findings.extend(_unused_imports(mod, project))
    return findings
