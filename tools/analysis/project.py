"""Whole-program model of the package: symbol table + call graph.

One `Project` is built per analyzer run from plain `ast` parses (no
third-party dependencies, no imports of the analyzed code).  It gives
the rules what per-file lint fundamentally cannot have:

  * a symbol table of every module / class / function, with import
    resolution (absolute and relative, aliases included) so a name at a
    use site maps back to its defining module;
  * a call graph whose edges are resolved through (a) local names and
    imports, (b) `self.`-methods with base-class lookup inside the
    package, (c) unique-method-name class attribution (`x.optimizations()`
    resolves to `GoalOptimizer.optimizations` when exactly one class
    defines it), and (d) first-order local type inference
    (`opt = GoalOptimizer(cfg); opt.optimizations(...)`, parameter
    annotations, `x = self.attr` where the attr type is known from
    `__init__`) — the indirection budget the gateway reachability rules
    need to catch a bypass laundered through one helper;
  * per-function concurrency facts: which locks a function acquires
    (`with self._lock:` / module-level locks, `Condition(lock)`
    aliased to its underlying lock), which locks are lexically held at
    every call site and attribute write, and where threads are spawned
    (`threading.Thread(target=...)` roots).

Everything is lexical and conservative: unresolved calls get NO edge
(rules that need more apply their own documented heuristics on the
recorded receiver spelling).
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

#: the package whose modules participate in whole-program analysis
PACKAGE = "cruise_control_tpu"

#: method names on a `self.<attr>.<m>(...)` receiver that mutate the
#: container bound to the attribute (counted as attribute writes by the
#: shared-state rule, same as `self.<attr>[k] = v`)
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort",
})

LockId = Tuple[str, str]          #: (owner qualname, attribute/global name)


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""

    lineno: int
    name: str                     #: called attr/function name (terminal)
    recv: str                     #: terminal receiver identifier ("" if none)
    targets: Tuple[str, ...]      #: resolved callee qnames (may be empty)
    held: Tuple[LockId, ...]      #: locks lexically held at the call
    node: ast.Call = dataclasses.field(repr=False, default=None)


@dataclasses.dataclass
class LockAcq:
    lock: LockId
    lineno: int
    held_before: Tuple[LockId, ...]   #: locks already held when acquiring


@dataclasses.dataclass
class AttrWrite:
    attr: str
    lineno: int
    held: Tuple[LockId, ...]


@dataclasses.dataclass
class FunctionInfo:
    qname: str                    #: module.Class.method / module.func
    module: str                   #: dotted module
    cls: Optional[str]            #: owning class qname, if a method
    name: str
    lineno: int
    node: ast.AST = dataclasses.field(repr=False, default=None)
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    acquisitions: List[LockAcq] = dataclasses.field(default_factory=list)
    writes: List[AttrWrite] = dataclasses.field(default_factory=list)
    thread_targets: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    qname: str
    module: str
    name: str
    lineno: int
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)
    #: lock-holding attributes: attr -> ("lock"|"rlock", aliased attr or
    #: None) — `self._cond = threading.Condition(self._lock)` records
    #: ("lock", "_lock") so `with self._cond:` resolves to the SAME
    #: LockId as `with self._lock:` (sched/queue.py's shape; treating
    #: them as two locks would fabricate order edges)
    lock_attrs: Dict[str, Tuple[str, Optional[str]]] = dataclasses.field(
        default_factory=dict)
    #: instance attrs assigned `self.x = ClassName(...)` -> class qname
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    instance_attrs: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ModuleInfo:
    path: Path                    #: as given on the command line
    rel: Optional[str]            #: package-relative posix path, or None
    dotted: Optional[str]         #: dotted module name, or None
    text: str = dataclasses.field(repr=False, default="")
    tree: Optional[ast.AST] = dataclasses.field(repr=False, default=None)
    syntax_error: Optional[SyntaxError] = None
    #: local binding -> (module dotted, symbol or None for whole-module)
    imports: Dict[str, Tuple[str, Optional[str]]] = dataclasses.field(
        default_factory=dict)
    import_nodes: Dict[str, ast.AST] = dataclasses.field(
        default_factory=dict, repr=False)
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)
    module_locks: Dict[str, Tuple[str, Optional[str]]] = dataclasses.field(
        default_factory=dict)
    all_names: Optional[Set[str]] = None


def _terminal_name(node) -> str:
    """Terminal identifier of an expression: `self.goal_optimizer` ->
    'goal_optimizer', `optimizer` -> 'optimizer', `Cls(...)` -> 'Cls'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return ""


def _call_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _attr_chain(node) -> Optional[List[str]]:
    """['self', 'x', 'y'] for `self.x.y`; None for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _is_threading_call(node: ast.Call, mod: ModuleInfo, name: str) -> bool:
    """Is this `threading.<name>(...)` / `<name>(...)` imported from
    threading?"""
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr == name:
        recv = _terminal_name(func.value)
        tgt = mod.imports.get(recv)
        return recv == "threading" or (
            tgt is not None and tgt[0] == "threading")
    if isinstance(func, ast.Name) and func.id == name:
        tgt = mod.imports.get(func.id)
        return tgt is not None and tgt == ("threading", name)
    return False


def _lock_kind_of_call(node: ast.Call, mod: ModuleInfo):
    """("lock"|"rlock", aliased-attr-or-None) when the call constructs a
    threading lock/condition, else None.  A bare `Condition()` owns an
    RLock; `Condition(x)` aliases x."""
    for name, kind in (("Lock", "lock"), ("RLock", "rlock")):
        if _is_threading_call(node, mod, name):
            return (kind, None)
    if _is_threading_call(node, mod, "Condition"):
        if node.args:
            chain = _attr_chain(node.args[0])
            if chain and len(chain) == 2 and chain[0] == "self":
                return ("lock", chain[1])
            if chain and len(chain) == 1:
                return ("lock", chain[0])
        return ("rlock", None)
    return None


class Project:
    """See module docstring."""

    def __init__(self, files: List[ModuleInfo]):
        self.files = files
        self.modules: Dict[str, ModuleInfo] = {
            m.dotted: m for m in files if m.dotted}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: method name -> classes defining it (class attribution index)
        self.method_index: Dict[str, List[ClassInfo]] = {}
        #: (module dotted, symbol) imported anywhere in the parse set —
        #: the re-export evidence the unused-import rule consults
        self.imported_symbols: Set[Tuple[str, str]] = set()
        self.callers: Dict[str, Set[str]] = {}
        self._edges: Dict[str, Set[str]] = {}

    # -- construction --------------------------------------------------

    @classmethod
    def build(cls, paths: List[Path]) -> "Project":
        files = [_parse_one(p) for p in paths]
        project = cls(files)
        for mod in files:
            if mod.tree is None:
                continue
            _collect_defs(mod, project)
        for mod in files:
            if mod.tree is None:
                continue
            for name, target in mod.imports.items():
                tmod, tsym = target
                if tsym is not None:
                    project.imported_symbols.add((tmod, tsym))
        project._index()
        for mod in files:
            if mod.tree is None or mod.dotted is None:
                continue
            _resolve_module(mod, project)
        project._link()
        return project

    def _index(self) -> None:
        for mod in self.files:
            for ci in mod.classes.values():
                self.classes[ci.qname] = ci
                for mname, fi in ci.methods.items():
                    self.functions[fi.qname] = fi
                    self.method_index.setdefault(mname, []).append(ci)
            for fi in mod.functions.values():
                self.functions[fi.qname] = fi

    def _link(self) -> None:
        for fi in self.functions.values():
            tset = self._edges.setdefault(fi.qname, set())
            for call in fi.calls:
                tset.update(call.targets)
        for src, dsts in self._edges.items():
            for dst in dsts:
                self.callers.setdefault(dst, set()).add(src)

    # -- queries -------------------------------------------------------

    def callees(self, qname: str) -> Set[str]:
        return self._edges.get(qname, set())

    def transitive_callees(self, roots) -> Set[str]:
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self._edges.get(cur, ()))
        return seen

    def shortest_caller_chain(self, qname: str,
                              roots: Set[str]) -> Optional[List[str]]:
        """Shortest entry-point -> ... -> qname chain, or None."""
        if qname in roots:
            return [qname]
        prev: Dict[str, str] = {}
        frontier = [qname]
        seen = {qname}
        while frontier:
            nxt: List[str] = []
            for cur in frontier:
                for caller in sorted(self.callers.get(cur, ())):
                    if caller in seen:
                        continue
                    seen.add(caller)
                    prev[caller] = cur
                    if caller in roots:
                        chain = [caller]
                        while chain[-1] != qname:
                            chain.append(prev[chain[-1]])
                        return chain
                    nxt.append(caller)
            frontier = nxt
        return None

    def class_of(self, qname: str) -> Optional[ClassInfo]:
        fi = self.functions.get(qname)
        if fi is None or fi.cls is None:
            return None
        return self.classes.get(fi.cls)

    def resolve_method(self, ci: ClassInfo,
                       name: str) -> Optional[FunctionInfo]:
        """Method lookup through in-package base classes (by name)."""
        seen: Set[str] = set()
        stack = [ci]
        while stack:
            cur = stack.pop()
            if cur.qname in seen:
                continue
            seen.add(cur.qname)
            if name in cur.methods:
                return cur.methods[name]
            mod = self.modules.get(cur.module)
            for base in cur.bases:
                bci = self._class_named(base, mod)
                if bci is not None:
                    stack.append(bci)
        return None

    def _class_named(self, name: str,
                     mod: Optional[ModuleInfo]) -> Optional[ClassInfo]:
        if mod is not None:
            if name in mod.classes:
                return mod.classes[name]
            tgt = mod.imports.get(name)
            if tgt is not None and tgt[1] is not None:
                tmod = self.modules.get(tgt[0])
                if tmod is not None:
                    return tmod.classes.get(tgt[1])
        cands = [c for c in self.classes.values() if c.name == name]
        return cands[0] if len(cands) == 1 else None

    def entry_points(self) -> Set[str]:
        """REST/facade/process entry points for reachability evidence:
        every function in api/ modules + main.py, and the facade's
        public methods."""
        roots: Set[str] = set()
        for mod in self.files:
            if mod.rel is None:
                continue
            if mod.rel.startswith("api/") or mod.rel == "main.py":
                for fi in mod.functions.values():
                    roots.add(fi.qname)
                for ci in mod.classes.values():
                    roots.update(f.qname for f in ci.methods.values())
            if mod.rel == "facade.py":
                for ci in mod.classes.values():
                    roots.update(f.qname for f in ci.methods.values()
                                 if not f.name.startswith("_"))
        return roots


def _parse_one(path: Path) -> ModuleInfo:
    text = path.read_text()
    rel = dotted = None
    parts = path.parts
    if PACKAGE in parts:
        pkg = len(parts) - 1 - parts[::-1].index(PACKAGE)
        rel = "/".join(parts[pkg + 1:])
        stem = [PACKAGE] + list(parts[pkg + 1:-1])
        if path.name != "__init__.py":
            stem.append(path.stem)
        dotted = ".".join(stem)
    elif "analysis" in parts and path.suffix == ".py":
        # the analyzer self-analyzes: tools/analysis/ gets a synthetic
        # dotted name so its own modules join the symbol table
        pkg = len(parts) - 1 - parts[::-1].index("analysis")
        stem = ["tools", "analysis"] + list(parts[pkg + 1:-1])
        if path.name != "__init__.py":
            stem.append(path.stem)
        dotted = ".".join(stem)
    mod = ModuleInfo(path=path, rel=rel, dotted=dotted, text=text)
    try:
        mod.tree = ast.parse(text, filename=str(path))
    except SyntaxError as exc:
        mod.syntax_error = exc
    return mod


def _resolve_import_module(mod: ModuleInfo, node: ast.ImportFrom) -> str:
    if node.level == 0:
        return node.module or ""
    base = (mod.dotted or "").split(".")
    if mod.path.name != "__init__.py":
        base = base[:-1]
    cut = node.level - 1
    if cut:
        base = base[:-cut] if cut <= len(base) else []
    return ".".join(base + ([node.module] if node.module else []))


def _collect_defs(mod: ModuleInfo, project: Project) -> None:
    for node in mod.tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else name
                mod.imports[name] = (target, None)
                mod.import_nodes[name] = node
        elif isinstance(node, ast.ImportFrom):
            src = _resolve_import_module(mod, node)
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                mod.imports[name] = (src, alias.name)
                mod.import_nodes[name] = node
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        mod.all_names = set(ast.literal_eval(node.value))
                    except ValueError:
                        mod.all_names = set()
            _collect_module_lock(mod, node)
        elif isinstance(node, ast.ClassDef):
            _collect_class(mod, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _collect_function(mod, node, None)
    # nested defs inside module functions
    for fname, fi in list(mod.functions.items()):
        _collect_nested(mod, fi)
    for ci in mod.classes.values():
        for fi in list(ci.methods.values()):
            _collect_nested(mod, fi, ci)


def _collect_module_lock(mod: ModuleInfo, node: ast.Assign) -> None:
    if not isinstance(node.value, ast.Call):
        return
    kind = _lock_kind_of_call(node.value, mod)
    if kind is None:
        return
    for t in node.targets:
        if isinstance(t, ast.Name):
            mod.module_locks[t.id] = kind


def _collect_class(mod: ModuleInfo, node: ast.ClassDef) -> None:
    qname = f"{mod.dotted}.{node.name}"
    ci = ClassInfo(qname=qname, module=mod.dotted, name=node.name,
                   lineno=node.lineno,
                   bases=[_terminal_name(b) for b in node.bases])
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FunctionInfo(qname=f"{qname}.{item.name}",
                              module=mod.dotted, cls=qname,
                              name=item.name, lineno=item.lineno,
                              node=item)
            ci.methods[item.name] = fi
    # instance attributes + lock attrs + attr construction types, from
    # every method body (locks are almost always bound in __init__ but
    # lazy `_ensure_*` shapes exist too)
    for fi in ci.methods.values():
        for sub in ast.walk(fi.node):
            if not isinstance(sub, ast.Assign):
                continue
            for t in sub.targets:
                chain = _attr_chain(t)
                if not (chain and len(chain) == 2 and chain[0] == "self"):
                    continue
                attr = chain[1]
                ci.instance_attrs.add(attr)
                if isinstance(sub.value, ast.Call):
                    kind = _lock_kind_of_call(sub.value, mod)
                    if kind is not None:
                        ci.lock_attrs[attr] = kind
                    else:
                        cname = _terminal_name(sub.value.func)
                        if cname and cname[:1].isupper():
                            ci.attr_types[attr] = cname
    mod.classes[node.name] = ci


def _collect_function(mod: ModuleInfo, node, cls_qname) -> None:
    fi = FunctionInfo(qname=f"{mod.dotted}.{node.name}",
                      module=mod.dotted, cls=cls_qname, name=node.name,
                      lineno=node.lineno, node=node)
    mod.functions[node.name] = fi


def _collect_nested(mod: ModuleInfo, parent: FunctionInfo,
                    ci: Optional[ClassInfo] = None) -> None:
    """Nested `def`s become their own nodes (qname
    parent.<locals>.name): a `threading.Thread(target=loop)` root must
    not smear the parent's request-side reachability onto the
    background thread."""
    for sub in ast.walk(parent.node):
        if sub is parent.node:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qname = f"{parent.qname}.<locals>.{sub.name}"
            if qname in (f.qname for f in mod.functions.values()):
                continue
            fi = FunctionInfo(qname=qname, module=mod.dotted,
                              cls=parent.cls, name=sub.name,
                              lineno=sub.lineno, node=sub)
            mod.functions[qname] = fi


# ----------------------------------------------------------------------
# pass 2: per-function resolution
# ----------------------------------------------------------------------

def _annotation_class(node, mod: ModuleInfo,
                      project: Project) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value.split(".")[-1].split("[")[0]
    else:
        name = _terminal_name(node)
    if not name or not name[:1].isupper():
        return None
    ci = project._class_named(name, mod)
    return ci.qname if ci else None


def _local_types(fi: FunctionInfo, mod: ModuleInfo,
                 project: Project) -> Dict[str, str]:
    """name -> class qname, from parameter annotations, constructor
    assignments and `x = self.attr` aliases (first-order)."""
    env: Dict[str, str] = {}
    args = fi.node.args
    for a in list(args.posonlyargs) + list(args.args) + list(
            args.kwonlyargs):
        if a.annotation is not None:
            cq = _annotation_class(a.annotation, mod, project)
            if cq:
                env[a.arg] = cq
    owner = project.classes.get(fi.cls) if fi.cls else None
    for sub in ast.walk(fi.node):
        if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
            continue
        t = sub.targets[0]
        if not isinstance(t, ast.Name):
            continue
        v = sub.value
        if isinstance(v, ast.Call):
            cname = _terminal_name(v.func)
            ci = project._class_named(cname, mod) \
                if cname[:1].isupper() else None
            if ci is not None:
                env[t.id] = ci.qname
        elif owner is not None:
            chain = _attr_chain(v)
            if chain and len(chain) == 2 and chain[0] == "self":
                cq = owner.attr_types.get(chain[1])
                if cq:
                    ci = project._class_named(cq, mod)
                    if ci:
                        env[t.id] = ci.qname
    return env


def _lock_id(expr, fi: FunctionInfo, mod: ModuleInfo,
             project: Project) -> Optional[LockId]:
    """Resolve a `with` context expression to a lock identity, chasing
    Condition aliases to the underlying lock."""
    chain = _attr_chain(expr)
    if chain is None:
        return None
    if len(chain) == 1:
        name = chain[0]
        entry = mod.module_locks.get(name)
        if entry is None:
            return None
        _, alias = entry
        return (mod.dotted, alias or name)
    if len(chain) == 2 and chain[0] == "self" and fi.cls:
        ci = project.classes.get(fi.cls)
        if ci is None:
            return None
        entry = ci.lock_attrs.get(chain[1])
        if entry is None:
            return None
        _, alias = entry
        return (fi.cls, alias if alias in ci.lock_attrs else chain[1]) \
            if alias else (fi.cls, chain[1])
    return None


def lock_kind(project: Project, lock: LockId) -> str:
    """'lock' (non-reentrant) or 'rlock' for a resolved LockId."""
    owner, attr = lock
    ci = project.classes.get(owner)
    if ci is not None and attr in ci.lock_attrs:
        return ci.lock_attrs[attr][0]
    mod = project.modules.get(owner)
    if mod is not None and attr in mod.module_locks:
        return mod.module_locks[attr][0]
    return "lock"


def _resolve_call_targets(call: ast.Call, fi: FunctionInfo,
                          mod: ModuleInfo, project: Project,
                          env: Dict[str, str]) -> Tuple[str, ...]:
    func = call.func
    # plain name: local def, import, or constructor
    if isinstance(func, ast.Name):
        name = func.id
        nested = mod.functions.get(f"{fi.qname}.<locals>.{name}")
        if nested is not None:
            return (nested.qname,)
        if name in mod.functions:
            return (mod.functions[name].qname,)
        if name in mod.classes:
            init = mod.classes[name].methods.get("__init__")
            return (init.qname,) if init else (mod.classes[name].qname,)
        tgt = mod.imports.get(name)
        if tgt is not None and tgt[1] is not None:
            tmod = project.modules.get(tgt[0])
            if tmod is not None:
                if tgt[1] in tmod.functions:
                    return (tmod.functions[tgt[1]].qname,)
                if tgt[1] in tmod.classes:
                    ci = tmod.classes[tgt[1]]
                    init = ci.methods.get("__init__")
                    return (init.qname,) if init else (ci.qname,)
        return ()
    if not isinstance(func, ast.Attribute):
        return ()
    mname = func.attr
    recv = func.value
    # self.m(...)
    chain = _attr_chain(recv)
    if chain == ["self"] and fi.cls:
        ci = project.classes.get(fi.cls)
        if ci is not None:
            target = project.resolve_method(ci, mname)
            if target is not None:
                return (target.qname,)
        return ()
    # module.func(...)
    if isinstance(recv, ast.Name):
        tgt = mod.imports.get(recv.id)
        if tgt is not None and tgt[1] is None:
            tmod = project.modules.get(tgt[0])
            if tmod is not None:
                if mname in tmod.functions:
                    return (tmod.functions[mname].qname,)
                if mname in tmod.classes:
                    ci = tmod.classes[mname]
                    init = ci.methods.get("__init__")
                    return (init.qname,) if init else (ci.qname,)
            return ()
        # typed local: opt.m(...) with opt's class known
        cq = env.get(recv.id)
        if cq is not None:
            ci = project.classes.get(cq)
            if ci is not None:
                target = project.resolve_method(ci, mname)
                if target is not None:
                    return (target.qname,)
            return ()
    # self.attr.m(...) with attr type known from __init__
    if chain and len(chain) == 2 and chain[0] == "self" and fi.cls:
        owner = project.classes.get(fi.cls)
        if owner is not None:
            cname = owner.attr_types.get(chain[1])
            if cname:
                ci = project._class_named(cname, mod)
                if ci is not None:
                    target = project.resolve_method(ci, mname)
                    if target is not None:
                        return (target.qname,)
    # Cls(...).m(...)
    if isinstance(recv, ast.Call):
        cname = _terminal_name(recv.func)
        if cname[:1].isupper():
            ci = project._class_named(cname, mod)
            if ci is not None:
                target = project.resolve_method(ci, mname)
                if target is not None:
                    return (target.qname,)
    # unique-method-name class attribution
    cands = project.method_index.get(mname, ())
    if len(cands) == 1:
        return (cands[0].methods[mname].qname,)
    return ()


def _thread_target(call: ast.Call, fi: FunctionInfo, mod: ModuleInfo,
                   project: Project) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg != "target":
            continue
        v = kw.value
        if isinstance(v, ast.Name):
            nested = mod.functions.get(f"{fi.qname}.<locals>.{v.id}")
            if nested is not None:
                return nested.qname
            if v.id in mod.functions:
                return mod.functions[v.id].qname
        chain = _attr_chain(v)
        if chain and len(chain) == 2 and chain[0] == "self" and fi.cls:
            ci = project.classes.get(fi.cls)
            if ci is not None:
                target = project.resolve_method(ci, chain[1])
                if target is not None:
                    return target.qname
    return None


def _resolve_module(mod: ModuleInfo, project: Project) -> None:
    all_fns = list(mod.functions.values())
    for ci in mod.classes.values():
        all_fns.extend(ci.methods.values())
    for fi in all_fns:
        _resolve_function(fi, mod, project)


def _resolve_function(fi: FunctionInfo, mod: ModuleInfo,
                      project: Project) -> None:
    env = _local_types(fi, mod, project)
    nested_nodes = {f.node for f in mod.functions.values()
                    if f.qname.startswith(fi.qname + ".<locals>.")}

    def visit(node, held: Tuple[LockId, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node in nested_nodes:
            return                # analyzed as its own function
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                lock = _lock_id(item.context_expr, fi, mod, project)
                if lock is not None:
                    fi.acquisitions.append(LockAcq(
                        lock=lock, lineno=node.lineno,
                        held_before=new_held))
                    new_held = new_held + (lock,)
                for sub in ast.iter_child_nodes(item.context_expr):
                    visit(sub, held)
                if isinstance(item.context_expr, ast.Call):
                    visit_call(item.context_expr, held)
            for sub in node.body:
                visit(sub, new_held)
            return
        if isinstance(node, ast.Call):
            visit_call(node, held)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                record_write(t, held, node.lineno)
        elif isinstance(node, ast.AugAssign):
            record_write(node.target, held, node.lineno)
        for sub in ast.iter_child_nodes(node):
            visit(sub, held)

    def visit_call(node: ast.Call, held: Tuple[LockId, ...]) -> None:
        targets = _resolve_call_targets(node, fi, mod, project, env)
        func = node.func
        recv = ""
        if isinstance(func, ast.Attribute):
            recv = _terminal_name(func.value)
        fi.calls.append(CallSite(
            lineno=node.lineno, name=_call_name(func), recv=recv,
            targets=targets, held=held, node=node))
        if _is_threading_call(node, mod, "Thread"):
            tgt = _thread_target(node, fi, mod, project)
            if tgt is not None:
                fi.thread_targets.append(tgt)
        # container mutation through self.<attr>.<mutator>(...)
        if isinstance(func, ast.Attribute) \
                and func.attr in MUTATOR_METHODS:
            chain = _attr_chain(func.value)
            if chain and len(chain) == 2 and chain[0] == "self":
                fi.writes.append(AttrWrite(attr=chain[1],
                                           lineno=node.lineno,
                                           held=held))

    def record_write(target, held: Tuple[LockId, ...],
                     lineno: int) -> None:
        chain = None
        if isinstance(target, ast.Subscript):
            chain = _attr_chain(target.value)
        else:
            chain = _attr_chain(target)
        if chain and len(chain) == 2 and chain[0] == "self":
            fi.writes.append(AttrWrite(attr=chain[1], lineno=lineno,
                                       held=held))

    for stmt in fi.node.body:
        visit(stmt, ())
