"""Gateway rules: the nine historical invariants, now whole-program.

Each family keeps its module allowlist (the blessed gateways) and its
historical name-heuristic detection — byte-compatible messages for
everything the flat lint used to catch — and adds what per-file lint
cannot do: sinks resolved SEMANTICALLY on the project call graph
(class attribution, import aliases, first-order local type inference),
so a bypass laundered through one helper function —

    def _grab(cfg, state, topo):
        opt = GoalOptimizer(cfg)          # receiver spells no 'optimizer'
        return opt.optimizations(state, topo)

— is a finding even though no identifier at the call site matches the
old receiver-name patterns.  Where a semantic finding is reachable from
a REST/facade entry point, the message carries the shortest
entry-to-sink caller chain as evidence.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .framework import Finding
from .project import (PACKAGE, FunctionInfo, ModuleInfo, Project,
                      _call_name, _terminal_name)

# -- allowlists (unchanged semantics from the flat lint) ---------------

_GATEWAY_ALLOWED_RELPATHS = {"facade.py", "analyzer/optimizer.py",
                             "scenario/engine.py",
                             "portfolio/engine.py",
                             "testing/verifier.py"}

_MESH_ALLOWED_RELPATHS = {"facade.py", "main.py", "parallel/mesh.py",
                          "parallel/health.py",
                          "analyzer/optimizer.py", "scenario/engine.py",
                          "testing/virtual_mesh.py"}

_MESH_ACQUIRE_CALLS = {"Mesh", "make_mesh", "runtime_mesh", "shard_state",
                       "devices", "local_devices", "device_count"}

_PROGCACHE_ALLOWED_RELPATHS = {"analyzer/optimizer.py",
                               "scenario/engine.py",
                               "parallel/progcache.py",
                               "model/store.py",
                               "parallel/health.py"}

_MODEL_STORE_ALLOWED_RELPATHS = {"facade.py", "model/store.py",
                                 "monitor/load_monitor.py"}

_WATCHED_EXEC_FILES = {"analyzer/optimizer.py", "scenario/engine.py"}
_WATCHED_EXEC_NAMES = {"aot", "shared", "prog"}

_PERSIST_ALLOWED_RELPATHS = {"utils/persist.py"}

_OBS_RESERVED_CONSTRUCTORS = {"Span", "SpanRecord", "Trace",
                              "TraceContext", "_ActiveSpan"}

_MUTABLE_CONSTRUCTORS = {"list", "dict", "set", "bytearray", "deque",
                         "defaultdict", "OrderedDict", "Counter",
                         "WeakValueDictionary", "WeakKeyDictionary"}

#: semantic sink definitions: (family, defining module rel, qname tail)
_SOLVE_SINKS = (("analyzer/optimizer.py", "GoalOptimizer.optimizations"),
                ("scenario/engine.py", "ScenarioEngine.evaluate"),
                ("model/cpu_model.py", "host_fallback_solve"))


def _pkg_rel(mod: ModuleInfo) -> Optional[str]:
    return mod.rel


def _in_package(mod: ModuleInfo) -> bool:
    return mod.rel is not None


def _sink_qnames(project: Project, specs) -> Set[str]:
    out: Set[str] = set()
    for rel, tail in specs:
        for mod in project.files:
            if mod.rel != rel or mod.dotted is None:
                continue
            out.add(f"{mod.dotted}.{tail}")
    return out


def _chain_note(project: Project, fn: Optional[FunctionInfo],
                entries: Set[str]) -> str:
    if fn is None:
        return ""
    chain = project.shortest_caller_chain(fn.qname, entries)
    if not chain:
        return ""
    short = [q.split(".", 1)[1] if q.startswith(PACKAGE + ".") else q
             for q in chain]
    return f" (reachable from entry point: {' -> '.join(short)})"


def _enclosing_function(mod: ModuleInfo,
                        lineno: int) -> Optional[FunctionInfo]:
    best = None
    fns = list(mod.functions.values())
    for ci in mod.classes.values():
        fns.extend(ci.methods.values())
    for fi in fns:
        node = fi.node
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= lineno <= end:
            if best is None or node.lineno > best.node.lineno:
                best = fi
    return best


# ----------------------------------------------------------------------
# G101 solve gateway
# ----------------------------------------------------------------------

def _solve_rule(project: Project, entries: Set[str]) -> List[Finding]:
    sinks = _sink_qnames(project, _SOLVE_SINKS)
    findings: List[Finding] = []
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel is None or mod.tree is None:
            continue
        if rel.startswith("sched/") or rel in _GATEWAY_ALLOWED_RELPATHS:
            continue
        path = str(mod.path)
        flagged: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                recv = _terminal_name(func.value).lower()
                if func.attr == "optimizations" and "optimizer" in recv:
                    flagged.add(id(node))
                    findings.append(Finding(
                        "G101", path, node.lineno,
                        "direct GoalOptimizer solve call outside "
                        "facade.py/sched/ — route it through the "
                        "device-time scheduler (single-gateway rule)"))
                elif func.attr == "evaluate" and (
                        "scenario_engine" in recv
                        or recv == "scenarioengine"):
                    flagged.add(id(node))
                    findings.append(Finding(
                        "G101", path, node.lineno,
                        "direct scenario-engine solve call outside "
                        "facade.py/sched/ — route it through the "
                        "device-time scheduler (single-gateway rule)"))
            elif isinstance(func, ast.Name) \
                    and func.id == "host_fallback_solve":
                flagged.add(id(node))
                findings.append(Finding(
                    "G101", path, node.lineno,
                    "direct host_fallback_solve call outside "
                    "facade.py/sched/ — route it through the "
                    "device-time scheduler (single-gateway rule)"))
        # semantic pass: resolved call edges into the sink set that the
        # name heuristics above did not already flag (the laundering
        # catch the flat lint provably missed)
        fns = list(mod.functions.values())
        for ci in mod.classes.values():
            fns.extend(ci.methods.values())
        for fi in fns:
            for call in fi.calls:
                if id(call.node) in flagged:
                    continue
                hit = sinks.intersection(call.targets)
                if not hit:
                    continue
                sink = sorted(hit)[0]
                findings.append(Finding(
                    "G101", path, call.lineno,
                    f"solve gateway bypass: call resolves to "
                    f"{sink.split('.', 1)[1]} outside facade.py/sched/ "
                    f"— route it through the device-time scheduler "
                    f"(single-gateway rule)"
                    + _chain_note(project, fi, entries),
                    symbol=fi.qname))
    return findings


# ----------------------------------------------------------------------
# G102 mesh gateway
# ----------------------------------------------------------------------

def _mesh_rule(project: Project, entries: Set[str]) -> List[Finding]:
    mesh_fn_sinks = _sink_qnames(project, (
        ("parallel/mesh.py", "make_mesh"),
        ("parallel/mesh.py", "runtime_mesh"),
        ("parallel/mesh.py", "shard_state")))
    findings: List[Finding] = []
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel is None or mod.tree is None:
            continue
        if rel.startswith("sched/") or rel in _MESH_ALLOWED_RELPATHS:
            continue
        path = str(mod.path)
        allowed = "sched/, " + ", ".join(sorted(_MESH_ALLOWED_RELPATHS))
        flagged: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            aliased = mod.imports.get(name)
            is_alias_mesh = (aliased is not None
                             and aliased[1] == "Mesh"
                             and aliased[0].startswith("jax"))
            if name not in _MESH_ACQUIRE_CALLS and not is_alias_mesh:
                continue
            if name in ("devices", "local_devices", "device_count"):
                func = node.func
                if not (isinstance(func, ast.Attribute)
                        and _terminal_name(func.value) == "jax"):
                    continue
            shown = "Mesh" if is_alias_mesh else name
            flagged.add(id(node))
            findings.append(Finding(
                "G102", path, node.lineno,
                f"direct mesh/device acquisition ({shown}) outside "
                f"the allowed modules ({allowed}) — the scheduler's "
                f"mesh token is the only path to multi-chip dispatch "
                f"(mesh single-gateway rule)"))
        fns = list(mod.functions.values())
        for ci in mod.classes.values():
            fns.extend(ci.methods.values())
        for fi in fns:
            for call in fi.calls:
                if id(call.node) in flagged:
                    continue
                hit = mesh_fn_sinks.intersection(call.targets)
                if not hit:
                    continue
                sink = sorted(hit)[0]
                findings.append(Finding(
                    "G102", path, call.lineno,
                    f"mesh gateway bypass: call resolves to "
                    f"{sink.split('.', 1)[1]} outside the allowed "
                    f"modules — the scheduler's mesh token is the only "
                    f"path to multi-chip dispatch (mesh single-gateway "
                    f"rule)" + _chain_note(project, fi, entries),
                    symbol=fi.qname))
    return findings


# ----------------------------------------------------------------------
# G103 cache gateway
# ----------------------------------------------------------------------

def _progcache_rule(project: Project, entries: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    allowed = ", ".join(sorted(_PROGCACHE_ALLOWED_RELPATHS))
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel is None or mod.tree is None:
            continue
        if rel in _PROGCACHE_ALLOWED_RELPATHS:
            continue
        path = str(mod.path)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            what = None
            if isinstance(func, ast.Attribute):
                if (func.attr == "jit"
                        and _terminal_name(func.value) == "jax"):
                    what = "jax.jit"
                elif (func.attr == "compile"
                      and isinstance(func.value, ast.Call)
                      and isinstance(func.value.func, ast.Attribute)
                      and func.value.func.attr == "lower"):
                    what = ".lower().compile()"
                elif (func.attr in ("export", "deserialize",
                                    "register_pytree_node_serialization")
                      and _terminal_name(func.value) in ("export",
                                                         "jexport")):
                    what = f"jax.export.{func.attr}"
            elif isinstance(func, ast.Name):
                aliased = mod.imports.get(func.id)
                if aliased == ("jax", "jit"):
                    what = "jax.jit"
            if what is not None:
                fi = _enclosing_function(mod, node.lineno)
                findings.append(Finding(
                    "G103", path, node.lineno,
                    f"direct program compile ({what}) outside the "
                    f"compile gateways ({allowed}) — every XLA "
                    f"compile must go through the persistent program "
                    f"cache (cache-gateway rule)"
                    + _chain_note(project, fi, entries),
                    symbol=fi.qname if fi else ""))
    return findings


# ----------------------------------------------------------------------
# G104 model-store gateway
# ----------------------------------------------------------------------

def _model_store_rule(project: Project, entries: Set[str]) -> List[Finding]:
    monitor_sinks = _sink_qnames(project, (
        ("monitor/load_monitor.py", "LoadMonitor.cluster_model"),))
    findings: List[Finding] = []
    allowed = ", ".join(sorted(_MODEL_STORE_ALLOWED_RELPATHS))
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel is None or mod.tree is None:
            continue
        if rel in _MODEL_STORE_ALLOWED_RELPATHS:
            continue
        path = str(mod.path)
        flagged: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr != "cluster_model":
                continue
            recv = _terminal_name(func.value).lower()
            if "monitor" in recv:
                flagged.add(id(node))
                findings.append(Finding(
                    "G104", path, node.lineno,
                    f"direct LoadMonitor model materialization outside "
                    f"the allowed modules ({allowed}) — route it "
                    f"through the facade's store-aware gateway "
                    f"(single-store rule)"))
        fns = list(mod.functions.values())
        for ci in mod.classes.values():
            fns.extend(ci.methods.values())
        for fi in fns:
            for call in fi.calls:
                if id(call.node) in flagged:
                    continue
                if not monitor_sinks.intersection(call.targets):
                    continue
                findings.append(Finding(
                    "G104", path, call.lineno,
                    f"store gateway bypass: call resolves to "
                    f"LoadMonitor.cluster_model outside the allowed "
                    f"modules ({allowed}) — route it through the "
                    f"facade's store-aware gateway (single-store rule)"
                    + _chain_note(project, fi, entries),
                    symbol=fi.qname))
    return findings


# ----------------------------------------------------------------------
# G105 durable writes
# ----------------------------------------------------------------------

def _write_mode_of(call: ast.Call):
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _durable_write_rule(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel is None or mod.tree is None:
            continue
        if rel in _PERSIST_ALLOWED_RELPATHS:
            continue
        path = str(mod.path)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = _call_name(func)
            aliased = mod.imports.get(name) \
                if isinstance(func, ast.Name) else None
            os_rename = (
                name in ("rename", "replace")
                and ((isinstance(func, ast.Attribute)
                      and _terminal_name(func.value) == "os")
                     or aliased in (("os", "rename"), ("os", "replace"))))
            if os_rename:
                findings.append(Finding(
                    "G105", path, node.lineno,
                    f"direct os.{name} outside utils/persist.py — "
                    f"publish state through persist.atomic_write/"
                    f"atomic_rewrite/replace (durable-write rule)"))
            elif name in ("open", "fdopen"):
                if name == "open" and isinstance(func, ast.Attribute) \
                        and _terminal_name(func.value) != "os":
                    continue          # some_obj.open(...): not file io
                mode = _write_mode_of(node)
                if mode is not None and "w" in mode:
                    findings.append(Finding(
                        "G105", path, node.lineno,
                        f"truncating file open (mode={mode!r}) outside "
                        f"utils/persist.py — a crash mid-write tears "
                        f"the file; publish through "
                        f"persist.atomic_write (durable-write rule)"))
    return findings


# ----------------------------------------------------------------------
# G106 watchdog gateway
# ----------------------------------------------------------------------

def _watchdog_rule(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel not in _WATCHED_EXEC_FILES or mod.tree is None:
            continue
        covered: Set[int] = set()
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call)
                    and _call_name(node.func) == "watched_call"):
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg):
                            covered.add(id(sub))
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in _WATCHED_EXEC_NAMES
                    and id(node) not in covered):
                findings.append(Finding(
                    "G106", str(mod.path), node.lineno,
                    f"compiled-executable call ({node.func.id}(...)) "
                    f"outside the watched-dispatch gateway — wrap it "
                    f"in health.watched_call(lambda: ...) so a wedged "
                    f"dispatch cannot capture the calling thread "
                    f"(watchdog-gateway rule)"))
    return findings


# ----------------------------------------------------------------------
# G107 tenant root
# ----------------------------------------------------------------------

def _is_mutable_value(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _call_name(node.func) in _MUTABLE_CONSTRUCTORS
    return False


def _tenant_root_rule(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel is None or not rel.startswith("fleet/") \
                or mod.tree is None:
            continue
        for node in mod.tree.body:
            targets, value = [], None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None:
                targets, value = [node.target], node.value
            if value is None or not _is_mutable_value(value):
                continue
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if names and all(n.startswith("__") and n.endswith("__")
                             for n in names):
                continue          # __all__ and friends: module metadata
            findings.append(Finding(
                "G107", str(mod.path), node.lineno,
                f"mutable module-level state {names or '<assignment>'} "
                f"in a fleet module — per-tenant state may live only "
                f"under the FleetRegistry instance (tenant-root rule)"))
    return findings


# ----------------------------------------------------------------------
# G108 trace propagation
# ----------------------------------------------------------------------

def _span_scoped_calls(tree: ast.AST) -> Set[int]:
    scoped: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        opens_span = any(
            isinstance(sub, ast.Call)
            and "span" in _call_name(sub.func).lower()
            for item in node.items
            for sub in ast.walk(item.context_expr))
        if opens_span:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    scoped.add(id(sub))
    return scoped


def _trace_rule(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.files:
        rel = _pkg_rel(mod)
        if rel is None or mod.tree is None:
            continue
        in_obs = rel.startswith("obs/")
        path = str(mod.path)
        span_scoped = None
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            reserved = name in _OBS_RESERVED_CONSTRUCTORS
            if not reserved and isinstance(node.func, ast.Name):
                aliased = mod.imports.get(name)
                if aliased is not None \
                        and aliased[0].endswith("obs.trace") \
                        and aliased[1] in _OBS_RESERVED_CONSTRUCTORS:
                    reserved, name = True, aliased[1]
            if reserved and not in_obs:
                findings.append(Finding(
                    "G108", path, node.lineno,
                    f"naked span/trace construction ({name}) outside "
                    f"obs/ — go through the obs.trace helpers "
                    f"(trace-propagation rule)"))
            elif name == "SolveJob":
                if not any(kw.arg == "trace" for kw in node.keywords):
                    findings.append(Finding(
                        "G108", path, node.lineno,
                        "SolveJob(...) without trace= — every "
                        "scheduler submission must carry a "
                        "TraceContext (trace-propagation rule)"))
            elif name == "_solve_on_rung":
                if span_scoped is None:
                    span_scoped = _span_scoped_calls(mod.tree)
                if id(node) not in span_scoped:
                    findings.append(Finding(
                        "G108", path, node.lineno,
                        "ladder attempt (_solve_on_rung) outside a "
                        "span scope — wrap rung attempts in "
                        "obs.trace.span so every attempt is "
                        "attributable (trace-propagation rule)"))
    return findings


def run(project: Project) -> List[Finding]:
    entries = project.entry_points()
    findings: List[Finding] = []
    findings.extend(_solve_rule(project, entries))
    findings.extend(_mesh_rule(project, entries))
    findings.extend(_progcache_rule(project, entries))
    findings.extend(_model_store_rule(project, entries))
    findings.extend(_durable_write_rule(project))
    findings.extend(_watchdog_rule(project))
    findings.extend(_tenant_root_rule(project))
    findings.extend(_trace_rule(project))
    return findings
