"""Dependency-free lint for CI (the reference runs checkstyle+findbugs in
its `analyze` CI step, .circleci/config.yml:18-20; this environment ships
no Python linter and installs are forbidden, so the equivalent hygiene
checks are implemented on `ast`).

Checks:
  * files parse (syntax);
  * unused imports (module scope, honoring __all__ and re-export files);
  * tabs in indentation, trailing whitespace, missing final newline;
  * lines longer than 100 columns;
  * no fully-silent `except Exception` swallows in cruise_control_tpu/:
    every broad handler must log, re-raise, or increment a sensor (a
    swallowed solver/sampler failure is invisible until it pages — the
    PR-2 robustness rule);
  * single-gateway rule: no direct GoalOptimizer solve
    (`*.optimizations(...)` on an optimizer, `GoalOptimizer(...)
    .optimizations(...)`, `host_fallback_solve(...)`) or scenario-engine
    `.evaluate(...)` call outside facade.py / sched/ and the solver
    implementation itself — every device solve must enter through the
    device-time scheduler (the PR-4 invariant; its runtime half is the
    chaos stress test's under_gateway assertion);
  * mesh single-gateway rule: no `Mesh(...)`/`make_mesh`/`jax.devices()`
    acquisition outside sched/ + facade.py (and the solver
    implementation) — the scheduler's mesh token is the only path to
    multi-chip dispatch (the PR-6 invariant);
  * cache-gateway rule: no `jax.jit(...)`, `.lower(...).compile()`
    chain, or `jax.export` use in cruise_control_tpu/ outside the
    shared persistent-cache helper (parallel/progcache.py) and the
    optimizer/engine compile gateways — a compile that bypasses the
    gateway is invisible to the persistent program cache and silently
    re-pays the ~300s cold start (the PR-7 invariant);
  * watchdog-gateway rule: in the solver execution modules, compiled
    executables are only invoked inside `health.watched_call(lambda:
    ...)` — a wedged XLA dispatch must fire the watchdog, never
    capture the dispatch thread (PR-12 mesh recovery);
  * single-store rule: no direct `*.cluster_model(...)` materialization
    on a LoadMonitor outside facade.py (the `_model_for_solve` /
    `_materialize_solve_inputs` gateway), the device model store
    (model/store.py) and the monitor itself — a solve path that
    rebuilds the model directly bypasses the device-resident store and
    silently re-pays the ~3.2s host build per request (the PR-9
    incremental invariant, same pattern as the solve-gateway and
    cache-gateway rules);
  * tenant-root rule: no mutable module-level state in fleet-reachable
    modules (cruise_control_tpu/fleet/) — the FleetRegistry INSTANCE is
    the only root of per-tenant state, so draining a tenant provably
    leaves nothing behind in process globals (the PR-5 isolation
    invariant).  Module-scope assignments of list/dict/set displays,
    comprehensions, or mutable-container constructor calls are
    findings; immutable constants (tuples, frozensets, strings,
    numbers) are fine;
  * durable-write rule: no `open(..., "w"/"wb")` / `os.rename` /
    `os.replace` in cruise_control_tpu/ outside utils/persist.py — every
    persistent-state write must go through the shared atomic
    write-temp-then-rename / CRC-framing helpers, or a store silently
    loses the crash-safety contract the executor journal depends on
    (the PR-13 invariant; append-mode opens are fine — append-only
    logs are the OTHER audited durability shape);
  * trace-propagation rule (the observability invariant): every
    `SolveJob(...)` construction in the package must carry `trace=`
    (scheduler submissions carry a TraceContext so queue wait, folds
    and preemptions land in the request's span tree), every ladder
    attempt (`_solve_on_rung(...)` call) must sit inside a `with`
    whose context expression opens a span, and
    Span/SpanRecord/Trace/TraceContext objects may be constructed only
    inside cruise_control_tpu/obs/ — everyone else goes through the
    obs.trace helpers, which are what keep parenting, span caps and
    cross-thread activation coherent.

Usage: python tools/lint.py [paths...]   (default: the package + tests)
Exit code 1 when any finding is reported.
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 100
DEFAULT_PATHS = ["cruise_control_tpu", "tests", "tools", "bench.py",
                 "__graft_entry__.py"]

#: a broad handler "signals" when its body calls something whose name
#: carries one of these tokens (logging, alerting, sensor increments,
#: error routing) — permissive by design: the rule exists to catch the
#: FULLY silent `except Exception: pass/return` shape
_HANDLER_SIGNAL_TOKENS = ("log", "warn", "error", "exception", "debug",
                          "info", "alert", "critical", "mark", "inc",
                          "update", "record", "report", "tolerate",
                          "quarantine", "fail")


def _catches_broad(handler_type) -> bool:
    """Does this except clause catch Exception/BaseException?"""
    types = (handler_type.elts if isinstance(handler_type, ast.Tuple)
             else [handler_type])
    return any(isinstance(t, ast.Name)
               and t.id in ("Exception", "BaseException") for t in types)


def _call_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _handler_signals(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _call_name(node.func).lower()
            if any(tok in name for tok in _HANDLER_SIGNAL_TOKENS):
                return True
    return False


def _silent_swallows(path: Path, tree: ast.AST) -> list:
    """Every `except Exception` in the package must log, re-raise, or
    increment a sensor — no fully-silent swallows (robustness rule)."""
    if "cruise_control_tpu" not in path.parts:
        return []
    findings = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) \
                and node.type is not None \
                and _catches_broad(node.type) \
                and not _handler_signals(node):
            findings.append(
                f"{path}:{node.lineno}: silent `except Exception` "
                f"swallow — log it, re-raise, or count it in a sensor")
    return findings


#: package-relative paths allowed to call the solver directly: the
#: gateway itself (facade.py routes through sched/), the scheduler
#: package, the solver implementation (analyzer/optimizer.py recurses,
#: scenario/engine.py drives the degraded rungs), and the test-support
#: verifier harness.  Full relative paths, not bare filenames: a future
#: detector/engine.py or monitor/optimizer.py must NOT inherit the
#: exemption just by sharing a name
_GATEWAY_ALLOWED_RELPATHS = {"facade.py", "analyzer/optimizer.py",
                             "scenario/engine.py", "testing/verifier.py"}


def _receiver_name(node) -> str:
    """Terminal identifier of a call receiver: `self.goal_optimizer`
    -> 'goal_optimizer', `optimizer` -> 'optimizer', `GoalOptimizer(...)`
    -> 'GoalOptimizer'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _receiver_name(node.func)
    return ""


def _gateway_violations(path: Path, tree: ast.AST) -> list:
    """Single-gateway rule: solve entry points may only be called from
    facade.py / sched/ (and the solver implementation itself) — the
    static half of the every-solve-goes-through-the-scheduler invariant.
    """
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    if rel.startswith("sched/") or rel in _GATEWAY_ALLOWED_RELPATHS:
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            recv = _receiver_name(func.value).lower()
            if func.attr == "optimizations" and "optimizer" in recv:
                findings.append(
                    f"{path}:{node.lineno}: direct GoalOptimizer solve "
                    f"call outside facade.py/sched/ — route it through "
                    f"the device-time scheduler (single-gateway rule)")
            elif func.attr == "evaluate" and (
                    "scenario_engine" in recv
                    or recv == "scenarioengine"):
                findings.append(
                    f"{path}:{node.lineno}: direct scenario-engine solve "
                    f"call outside facade.py/sched/ — route it through "
                    f"the device-time scheduler (single-gateway rule)")
        elif isinstance(func, ast.Name) \
                and func.id == "host_fallback_solve":
            findings.append(
                f"{path}:{node.lineno}: direct host_fallback_solve call "
                f"outside facade.py/sched/ — route it through the "
                f"device-time scheduler (single-gateway rule)")
    return findings


#: package-relative paths allowed to construct a device Mesh or acquire
#: devices directly: the mesh implementation itself, the solver
#: implementations that consume a mesh, the scheduler that OWNS the
#: token, the facade + composition root that build it from config, and
#: the virtual-device test rig.  Everyone else reaches multi-chip
#: dispatch only through the scheduler's mesh token
#: (sched/runtime.current_mesh_token) — the mesh half of the
#: single-gateway invariant.
_MESH_ALLOWED_RELPATHS = {"facade.py", "main.py", "parallel/mesh.py",
                          # the mesh supervisor rebuilds the token over
                          # probe survivors — it IS the token's health
                          # authority (PR-12 elastic recovery)
                          "parallel/health.py",
                          "analyzer/optimizer.py", "scenario/engine.py",
                          "testing/virtual_mesh.py"}

#: call names that construct a mesh or acquire the device topology
_MESH_ACQUIRE_CALLS = {"Mesh", "make_mesh", "runtime_mesh", "shard_state",
                       "devices", "local_devices", "device_count"}


def _mesh_violations(path: Path, tree: ast.AST) -> list:
    """Mesh single-gateway rule: no module outside sched/ + facade.py +
    the solver implementation may construct a `Mesh` or acquire devices
    (`jax.devices()` & co.) — the scheduler's mesh token is the only
    path to multi-chip dispatch."""
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    if rel.startswith("sched/") or rel in _MESH_ALLOWED_RELPATHS:
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name not in _MESH_ACQUIRE_CALLS:
            continue
        if name in ("devices", "local_devices", "device_count"):
            # only the jax.* device-acquisition spellings count
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and _receiver_name(func.value) == "jax"):
                continue
        allowed = "sched/, " + ", ".join(sorted(_MESH_ALLOWED_RELPATHS))
        findings.append(
            f"{path}:{node.lineno}: direct mesh/device acquisition "
            f"({name}) outside the allowed modules ({allowed}) — the "
            f"scheduler's mesh token is the only path to multi-chip "
            f"dispatch (mesh single-gateway rule)")
    return findings


#: package-relative paths allowed to build XLA programs directly: the
#: two compile gateways (GoalOptimizer._compile_through_cache /
#: _jit_program and ScenarioEngine._compile_batched) and the persistent
#: cache implementation itself.  Everything else must reach compilation
#: through them — that is what makes the persistent program cache a
#: true write-through tier: a compile that bypasses the gateway is
#: invisible to the cache and silently re-pays the ~300s cold start.
_PROGCACHE_ALLOWED_RELPATHS = {"analyzer/optimizer.py",
                               "scenario/engine.py",
                               "parallel/progcache.py",
                               # the model store's delta-apply program:
                               # a handful of tiny scatters (compiles in
                               # ms, LRU'd by jit itself) — not worth a
                               # persistent-cache tier
                               "model/store.py",
                               # the health probe's known-answer
                               # program: a four-float reduction per
                               # chip, compiled once per process
                               "parallel/health.py"}


def _progcache_violations(path: Path, tree: ast.AST) -> list:
    """Cache-gateway rule: no `jax.jit(...)`, `.lower(...).compile()`
    chain, or `jax.export` use in the package outside the shared cache
    helper and the optimizer/engine compile paths — every program
    compile must go through the persistent program cache (the PR-7
    invariant, same pattern as the PR-4 single-gateway and PR-6 mesh
    rules)."""
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    if rel in _PROGCACHE_ALLOWED_RELPATHS:
        return []
    findings = []
    allowed = ", ".join(sorted(_PROGCACHE_ALLOWED_RELPATHS))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        what = None
        if (func.attr == "jit"
                and _receiver_name(func.value) == "jax"):
            what = "jax.jit"
        elif (func.attr == "compile"
              and isinstance(func.value, ast.Call)
              and isinstance(func.value.func, ast.Attribute)
              and func.value.func.attr == "lower"):
            what = ".lower().compile()"
        elif (func.attr in ("export", "deserialize",
                            "register_pytree_node_serialization")
              and _receiver_name(func.value) in ("export", "jexport")):
            what = f"jax.export.{func.attr}"
        if what is not None:
            findings.append(
                f"{path}:{node.lineno}: direct program compile ({what}) "
                f"outside the compile gateways ({allowed}) — every XLA "
                f"compile must go through the persistent program cache "
                f"(cache-gateway rule)")
    return findings


#: package-relative paths allowed to materialize the cluster model
#: directly: the facade (its _model_for_solve gateway consults the
#: device-resident store first), the store implementation, and the
#: monitor that owns the builder.  Everyone else reaches a model
#: through the facade gateway — the single-store half of the
#: incremental-model invariant (PR 9).
_MODEL_STORE_ALLOWED_RELPATHS = {"facade.py", "model/store.py",
                                 "monitor/load_monitor.py"}


def _model_store_violations(path: Path, tree: ast.AST) -> list:
    """Single-store rule: no `<monitor>.cluster_model(...)` call in the
    package outside the facade gateway, the store, and the monitor
    itself.  Receiver-based: only calls whose receiver names a monitor
    (`load_monitor`, `_load_monitor`, ...) count — the facade's public
    `cc.cluster_model()` wrapper is itself gatewayed."""
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    if rel in _MODEL_STORE_ALLOWED_RELPATHS:
        return []
    findings = []
    allowed = ", ".join(sorted(_MODEL_STORE_ALLOWED_RELPATHS))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) \
                or func.attr != "cluster_model":
            continue
        recv = _receiver_name(func.value).lower()
        if "monitor" in recv:
            findings.append(
                f"{path}:{node.lineno}: direct LoadMonitor model "
                f"materialization outside the allowed modules "
                f"({allowed}) — route it through the facade's "
                f"store-aware gateway (single-store rule)")
    return findings


#: files whose compiled-executable invocations must ride the watched-
#: dispatch gateway, and the local names those executables are bound to
#: at their call sites (GoalOptimizer._run's `aot`/`shared`, the
#: scenario engine's `prog`)
_WATCHED_EXEC_FILES = {"analyzer/optimizer.py", "scenario/engine.py"}
_WATCHED_EXEC_NAMES = {"aot", "shared", "prog"}


def _watchdog_violations(path: Path, tree: ast.AST) -> list:
    """Watchdog-gateway rule: in the solver execution modules, every
    invocation of a compiled executable (the AOT/shared/batched
    program objects) must happen INSIDE a lambda handed to
    `health.watched_call` — a bare `aot(*args)` would run on the
    dispatch thread itself, and a wedged XLA dispatch there captures
    the thread forever (mesh.watchdog.ms cannot save what never
    entered the gateway; parallel/health.py)."""
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    if rel not in _WATCHED_EXEC_FILES:
        return []
    covered = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and _call_name(node.func) == "watched_call"):
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    for sub in ast.walk(arg):
                        covered.add(id(sub))
    findings = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _WATCHED_EXEC_NAMES
                and id(node) not in covered):
            findings.append(
                f"{path}:{node.lineno}: compiled-executable call "
                f"({node.func.id}(...)) outside the watched-dispatch "
                f"gateway — wrap it in health.watched_call(lambda: "
                f"...) so a wedged dispatch cannot capture the "
                f"calling thread (watchdog-gateway rule)")
    return findings


#: constructor names whose module-scope call sites create MUTABLE
#: containers (per-tenant state could silently accrete in them)
_MUTABLE_CONSTRUCTORS = {"list", "dict", "set", "bytearray", "deque",
                         "defaultdict", "OrderedDict", "Counter",
                         "WeakValueDictionary", "WeakKeyDictionary"}


def _is_mutable_value(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = _call_name(node.func)
        return name in _MUTABLE_CONSTRUCTORS
    return False


def _fleet_mutable_globals(path: Path, tree: ast.AST) -> list:
    """Tenant-root rule: fleet-reachable modules must hold NO mutable
    module-level state — the registry instance is the only tenant root
    (see module docstring)."""
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    if not rel.startswith("fleet/"):
        return []
    findings = []
    body = tree.body if isinstance(tree, ast.Module) else []
    for node in body:
        targets, value = [], None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None or not _is_mutable_value(value):
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if names and all(n.startswith("__") and n.endswith("__")
                         for n in names):
            continue          # __all__ and friends: module metadata
        findings.append(
            f"{path}:{node.lineno}: mutable module-level state "
            f"{names or '<assignment>'} in a fleet module — per-tenant "
            f"state may live only under the FleetRegistry instance "
            f"(tenant-root rule)")
    return findings


#: package-relative paths allowed to write/rename files directly: the
#: shared durable-write helper is the ONLY one — every other module
#: reaches disk through persist.atomic_write / atomic_rewrite /
#: replace / open_append (append-mode `open` stays legal everywhere:
#: append-only logs are the other audited durability shape)
_PERSIST_ALLOWED_RELPATHS = {"utils/persist.py"}


def _write_mode_of(call: ast.Call):
    """The constant mode string of an open()/os.fdopen() call, or None
    when absent/dynamic."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _durable_write_violations(path: Path, tree: ast.AST) -> list:
    """Durable-write rule: truncating writes (`open(.., "w"/"wb")`) and
    renames (`os.rename`/`os.replace`) outside utils/persist.py fail
    lint — persistent state must be published atomically through the
    shared helpers (executor/journal.py's crash-recovery guarantees
    only hold if every store keeps the same discipline)."""
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    if rel in _PERSIST_ALLOWED_RELPATHS:
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = _call_name(func)
        if name in ("rename", "replace") \
                and isinstance(func, ast.Attribute) \
                and _receiver_name(func.value) == "os":
            findings.append(
                f"{path}:{node.lineno}: direct os.{name} outside "
                f"utils/persist.py — publish state through "
                f"persist.atomic_write/atomic_rewrite/replace "
                f"(durable-write rule)")
        elif name in ("open", "fdopen"):
            if name == "open" and isinstance(func, ast.Attribute) \
                    and _receiver_name(func.value) != "os":
                continue          # some_obj.open(...): not file io
            mode = _write_mode_of(node)
            if mode is not None and "w" in mode:
                findings.append(
                    f"{path}:{node.lineno}: truncating file open "
                    f"(mode={mode!r}) outside utils/persist.py — a "
                    f"crash mid-write tears the file; publish through "
                    f"persist.atomic_write (durable-write rule)")
    return findings


#: names whose CONSTRUCTION is reserved to cruise_control_tpu/obs/ —
#: span/trace objects built anywhere else bypass the parenting, span-cap
#: and cross-thread-activation logic of the obs.trace helpers
_OBS_RESERVED_CONSTRUCTORS = {"Span", "SpanRecord", "Trace",
                              "TraceContext", "_ActiveSpan"}


def _span_scoped_calls(tree: ast.AST) -> set:
    """id()s of every Call node lexically inside a `with` statement one
    of whose context expressions opens a span (a call whose name
    mentions 'span')."""
    scoped = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        opens_span = any(
            isinstance(sub, ast.Call)
            and "span" in _call_name(sub.func).lower()
            for item in node.items
            for sub in ast.walk(item.context_expr))
        if opens_span:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    scoped.add(id(sub))
    return scoped


def _trace_violations(path: Path, tree: ast.AST) -> list:
    """Trace-propagation rule (see module docstring): SolveJob carries
    trace=, ladder attempts run inside a span, span objects are built
    only in obs/."""
    parts = path.parts
    if "cruise_control_tpu" not in parts:
        return []
    pkg = len(parts) - 1 - parts[::-1].index("cruise_control_tpu")
    rel = "/".join(parts[pkg + 1:])
    in_obs = rel.startswith("obs/")
    findings = []
    span_scoped = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name in _OBS_RESERVED_CONSTRUCTORS and not in_obs:
            findings.append(
                f"{path}:{node.lineno}: naked span/trace construction "
                f"({name}) outside obs/ — go through the obs.trace "
                f"helpers (trace-propagation rule)")
        elif name == "SolveJob":
            if not any(kw.arg == "trace" for kw in node.keywords):
                findings.append(
                    f"{path}:{node.lineno}: SolveJob(...) without "
                    f"trace= — every scheduler submission must carry a "
                    f"TraceContext (trace-propagation rule)")
        elif name == "_solve_on_rung":
            if span_scoped is None:
                span_scoped = _span_scoped_calls(tree)
            if id(node) not in span_scoped:
                findings.append(
                    f"{path}:{node.lineno}: ladder attempt "
                    f"(_solve_on_rung) outside a span scope — wrap "
                    f"rung attempts in obs.trace.span so every attempt "
                    f"is attributable (trace-propagation rule)")
    return findings


def _imported_names(tree: ast.AST):
    """{local binding name: node} for every module-scope import."""
    out = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                out[name] = node
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = node
    return out


def _used_names(tree: ast.AST):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    return used


def _exported(tree: ast.AST):
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        return set(ast.literal_eval(node.value))
                    except ValueError:
                        return set()
    return None


def lint_file(path: Path) -> list:
    findings = []
    text = path.read_text()
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]

    lines = text.splitlines()
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            findings.append(f"{path}:{i}: trailing whitespace")
        if line[:len(line) - len(line.lstrip())].count("\t"):
            findings.append(f"{path}:{i}: tab in indentation")
        if len(line) > MAX_LINE:
            findings.append(f"{path}:{i}: line longer than {MAX_LINE} cols")
    if text and not text.endswith("\n"):
        findings.append(f"{path}:{len(lines)}: missing final newline")

    findings.extend(_silent_swallows(path, tree))
    findings.extend(_gateway_violations(path, tree))
    findings.extend(_mesh_violations(path, tree))
    findings.extend(_progcache_violations(path, tree))
    findings.extend(_model_store_violations(path, tree))
    findings.extend(_watchdog_violations(path, tree))
    findings.extend(_durable_write_violations(path, tree))
    findings.extend(_fleet_mutable_globals(path, tree))
    findings.extend(_trace_violations(path, tree))

    # unused imports: __init__.py files are re-export surfaces; a module
    # __all__ also marks intentional re-exports; `annotations` is the
    # future import; `conftest` imports in tests exist for their side
    # effect (forcing the CPU platform before jax initializes)
    if path.name != "__init__.py":
        exported = _exported(tree) or set()
        used = _used_names(tree) | {"annotations", "conftest"}
        for name, node in _imported_names(tree).items():
            if name not in used and name not in exported:
                findings.append(
                    f"{path}:{node.lineno}: unused import '{name}'")
    return findings


def main(argv) -> int:
    roots = [Path(p) for p in (argv or DEFAULT_PATHS)]
    files = []
    for root in roots:
        if root.is_dir():
            files.extend(sorted(root.rglob("*.py")))
        elif root.exists():
            files.append(root)
    findings = []
    for f in files:
        if "__pycache__" in f.parts:
            continue
        findings.extend(lint_file(f))
    for line in findings:
        print(line)
    print(f"lint: {len(files)} files, {len(findings)} findings",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
