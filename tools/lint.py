"""Dependency-free lint for CI (the reference runs checkstyle+findbugs in
its `analyze` CI step, .circleci/config.yml:18-20; this environment ships
no Python linter and installs are forbidden, so the equivalent hygiene
checks are implemented on `ast`).

Checks:
  * files parse (syntax);
  * unused imports (module scope, honoring __all__ and re-export files);
  * tabs in indentation, trailing whitespace, missing final newline;
  * lines longer than 100 columns.

Usage: python tools/lint.py [paths...]   (default: the package + tests)
Exit code 1 when any finding is reported.
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 100
DEFAULT_PATHS = ["cruise_control_tpu", "tests", "tools", "bench.py",
                 "__graft_entry__.py"]


def _imported_names(tree: ast.AST):
    """{local binding name: node} for every module-scope import."""
    out = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                out[name] = node
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = node
    return out


def _used_names(tree: ast.AST):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    return used


def _exported(tree: ast.AST):
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        return set(ast.literal_eval(node.value))
                    except ValueError:
                        return set()
    return None


def lint_file(path: Path) -> list:
    findings = []
    text = path.read_text()
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]

    lines = text.splitlines()
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            findings.append(f"{path}:{i}: trailing whitespace")
        if line[:len(line) - len(line.lstrip())].count("\t"):
            findings.append(f"{path}:{i}: tab in indentation")
        if len(line) > MAX_LINE:
            findings.append(f"{path}:{i}: line longer than {MAX_LINE} cols")
    if text and not text.endswith("\n"):
        findings.append(f"{path}:{len(lines)}: missing final newline")

    # unused imports: __init__.py files are re-export surfaces; a module
    # __all__ also marks intentional re-exports; `annotations` is the
    # future import; `conftest` imports in tests exist for their side
    # effect (forcing the CPU platform before jax initializes)
    if path.name != "__init__.py":
        exported = _exported(tree) or set()
        used = _used_names(tree) | {"annotations", "conftest"}
        for name, node in _imported_names(tree).items():
            if name not in used and name not in exported:
                findings.append(
                    f"{path}:{node.lineno}: unused import '{name}'")
    return findings


def main(argv) -> int:
    roots = [Path(p) for p in (argv or DEFAULT_PATHS)]
    files = []
    for root in roots:
        if root.is_dir():
            files.extend(sorted(root.rglob("*.py")))
        elif root.exists():
            files.append(root)
    findings = []
    for f in files:
        if "__pycache__" in f.parts:
            continue
        findings.extend(lint_file(f))
    for line in findings:
        print(line)
    print(f"lint: {len(files)} files, {len(findings)} findings",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
