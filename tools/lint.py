"""Lint entry point — now the whole-program analyzer (tools/analysis/).

The historical 694-line per-file lint lived here; ISSUE 15 replaced it
with the project-wide analyzer, which keeps every old rule (byte-
compatible flat output) and adds gateway reachability, concurrency
lint and config/sensor/fault-site drift detection.  This shim keeps
`python tools/lint.py [paths...]` as the single stable entry point for
the Makefile, CI and muscle memory.  Rule catalog and workflow:
docs/ANALYSIS.md.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
