"""Run bench.py across the five BASELINE.json eval configs and collect
one JSON line each into BENCH_CONFIGS_r{N}.json (round-3 VERDICT missing
item 2: per-round eval-config results must be published every round).

Usage: python tools/run_bench_configs.py <round-number> [configs...]
Writes BENCH_CONFIGS_r{N}.json at the repo root with one object per
config: the bench metric line plus the violated-broker stderr summary.
"""
import json
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config(cfg: str) -> dict:
    env = dict(os.environ, BENCH_CONFIG=cfg)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        capture_output=True, text=True, env=env, cwd=ROOT)
    out = proc.stdout.strip().splitlines()
    try:
        metric = json.loads(out[-1]) if out else {}
        if not isinstance(metric, dict):
            metric = {"raw": metric}
    except ValueError:
        metric = {"error": "unparseable stdout", "last_line": out[-1][:200]}
    summary = {}
    for line in proc.stderr.splitlines():
        m = re.match(r"# (proposals|violated broker counts|rounds by goal)"
                     r"[ :](.*)", line)
        if m:
            summary[m.group(1)] = m.group(2).strip()
    metric["config"] = cfg
    metric["summary"] = summary
    metric["rc"] = proc.returncode
    if proc.returncode:
        metric["stderr_tail"] = proc.stderr.strip().splitlines()[-5:]
    return metric


def main() -> None:
    if len(sys.argv) < 2:
        sys.exit("usage: run_bench_configs.py <round-number> [configs...]")
    rnd = int(sys.argv[1])
    configs = sys.argv[2:] or ["1", "2", "3", "4", "5"]
    results = []
    for cfg in configs:
        print(f"# running BENCH_CONFIG={cfg} ...", file=sys.stderr,
              flush=True)
        results.append(run_config(cfg))
        print(json.dumps(results[-1])[:300], file=sys.stderr, flush=True)
    path = os.path.join(ROOT, f"BENCH_CONFIGS_r{rnd:02d}.json")
    with open(path, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
