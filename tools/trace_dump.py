"""Fetch and pretty-print solve traces from a running server's TRACES
endpoint (the flight recorder, cruise_control_tpu/obs/).

One-shot::

    python tools/trace_dump.py --trace-id 5f1c9aa2b3d44e01
    python tools/trace_dump.py --outcome degraded --limit 8
    python tools/trace_dump.py --cluster alpha

Operator drill (tail mode)::

    python tools/trace_dump.py --follow --interval 2

--follow polls the recorder and prints every NEW trace as it completes
(newest last, like `tail -f`), so an operator can watch a drill's
requests decompose into queue-wait / rung attempts / materialization /
device segments live.  Exit with Ctrl-C.

The tree rendering shows per-span wall-clock, tags, and events::

    trace 5f1c9aa2 rest.REBALANCE ok 1243.2ms cluster=alpha
      +- solve.optimizations                1240.1ms
         +- sched.queue-wait                  12.4ms klass=USER_INTERACTIVE
         +- sched.dispatch                  1220.9ms
            +- solve.rung-attempt           1219.8ms rung=FUSED
               +- model.materialize            3.1ms outcome=hit
               +- device.solve              1210.2ms
                  +- device.instrument-fetch  88.0ms
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional


def fetch_traces(base: str, trace_id: Optional[str] = None,
                 cluster: Optional[str] = None,
                 outcome: Optional[str] = None,
                 limit: Optional[int] = None,
                 verbose: bool = True,
                 auth: Optional[str] = None,
                 since_ms: Optional[float] = None,
                 min_duration_ms: Optional[float] = None) -> dict:
    params = {"verbose": "true" if verbose else "false"}
    if trace_id:
        params["trace_id"] = trace_id
    if cluster:
        params["cluster"] = cluster
    if outcome:
        params["outcome"] = outcome
    if limit is not None:
        params["limit"] = str(limit)
    if since_ms is not None:
        params["since"] = repr(float(since_ms))
    if min_duration_ms is not None:
        params["min_duration_ms"] = repr(float(min_duration_ms))
    url = f"{base.rstrip('/')}/traces?{urllib.parse.urlencode(params)}"
    req = urllib.request.Request(url, method="GET")
    if auth:
        req.add_header("Authorization", auth)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read() or b"{}")


def _fmt_tags(tags: Dict[str, object]) -> str:
    if not tags:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in sorted(tags.items())
                          if k != "error")


def render_span(node: dict, indent: int, out: List[str]) -> None:
    pad = "  " * indent + "+- "
    name = node.get("name", "?")
    dur = node.get("durationMs", 0.0)
    line = f"{pad}{name:<{max(1, 46 - len(pad))}} {dur:9.1f}ms"
    line += _fmt_tags(node.get("tags", {}))
    if node.get("tags", {}).get("error"):
        line += f"  ERROR: {node['tags']['error']}"
    out.append(line)
    for ev in node.get("events", []):
        ev_tags = {k: v for k, v in ev.items()
                   if k not in ("name", "atS")}
        out.append("  " * (indent + 1) + f"*  {ev.get('name')}"
                   + _fmt_tags(ev_tags))
    for child in node.get("children", []):
        render_span(child, indent + 1, out)


def render_trace(doc: dict) -> str:
    out: List[str] = []
    tags = doc.get("tags", {})
    head = (f"trace {doc.get('traceId')} {doc.get('name', '?')} "
            f"{doc.get('outcome')} {doc.get('durationMs', 0.0):.1f}ms")
    head += _fmt_tags(tags)
    if doc.get("droppedSpans"):
        head += f"  (+{doc['droppedSpans']} spans dropped)"
    out.append(head)
    root = doc.get("root")
    if root:
        for child in root.get("children", []):
            render_span(child, 1, out)
        for ev in root.get("events", []):
            ev_tags = {k: v for k, v in ev.items()
                       if k not in ("name", "atS")}
            out.append("  " + f"*  {ev.get('name')}" + _fmt_tags(ev_tags))
    else:
        out.append("  (span tree not included — re-fetch with "
                   "?trace_id= or --verbose)")
    return "\n".join(out)


def follow(args) -> int:
    """Tail mode: poll and print every NEW trace as it completes.

    Polls are COMPACT (verbose=false) so they never export — only the
    per-trace tree fetch of a trace we actually PRINT unpins it; the
    startup history-skip in particular must not silently unpin (and
    thereby doom to eviction) incident traces it never displayed.

    Under load-harness rates each poll is additionally BOUNDED with
    `?since=` so a tail of a churning ring pages only the recent tail,
    never the full ring.  Traces enter the ring at FINISH but filter by
    START time, so the bound backs off a generous horizon (10 polls,
    min 60s) behind the newest start seen — a solve slower than the
    poll interval still shows up; only something slower than the whole
    horizon could slip past, and `seen` keeps the overlap deduped."""
    seen: set = set()
    newest_start_ms: Optional[float] = None
    slack_ms = max(60_000.0, 10 * args.interval * 1000.0)
    first = True
    while True:
        since = (None if newest_start_ms is None
                 else newest_start_ms - slack_ms)
        try:
            body = fetch_traces(args.address, cluster=args.cluster,
                                outcome=args.outcome,
                                limit=args.limit or 64,
                                verbose=False, auth=args.auth,
                                since_ms=(args.since if first
                                          else since),
                                min_duration_ms=args.min_duration_ms)
        except (urllib.error.URLError, OSError) as exc:
            print(f"# fetch failed: {exc}", file=sys.stderr)
            time.sleep(args.interval)
            continue
        fresh = [t for t in reversed(body.get("traces", []))
                 if t.get("traceId") not in seen]
        for t in body.get("traces", []):
            start = t.get("startMs")
            if start is not None:
                newest_start_ms = max(newest_start_ms or 0.0,
                                      float(start))
        for doc in fresh:
            tid = doc.get("traceId")
            seen.add(tid)
            if first:
                continue           # don't replay history on startup
            try:
                full = fetch_traces(args.address, trace_id=tid,
                                    auth=args.auth).get("traces", [])
            except (urllib.error.URLError, OSError) as exc:
                print(f"# fetch of {tid} failed: {exc}",
                      file=sys.stderr)
                full = []
            print(render_trace(full[0] if full else doc))
            print()
        if first:
            print(f"# following {args.address}/traces "
                  f"({len(seen)} existing traces skipped); Ctrl-C to "
                  f"stop", file=sys.stderr)
            first = False
        time.sleep(args.interval)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace_dump",
        description="fetch/pretty-print solve traces from the TRACES "
                    "endpoint (flight recorder)")
    parser.add_argument("-a", "--address",
                        default="http://127.0.0.1:9090/kafkacruisecontrol",
                        help="base URL of the REST API")
    parser.add_argument("--auth", help="Authorization header value")
    parser.add_argument("--trace-id", help="fetch ONE trace's full tree")
    parser.add_argument("--cluster", help="fleet tenant filter")
    parser.add_argument("--outcome",
                        choices=["ok", "failed", "degraded", "fallback",
                                 "preempted", "rejected"])
    parser.add_argument("--limit", type=int)
    parser.add_argument("--since", type=float, metavar="EPOCH_MS",
                        help="only traces started at/after this "
                             "epoch-ms timestamp (drills under load "
                             "never page the whole ring)")
    parser.add_argument("--min-duration-ms", type=float,
                        help="only traces at least this slow (the "
                             "'show me the outliers' drill filter)")
    parser.add_argument("--json", action="store_true",
                        help="raw JSON instead of the rendered tree")
    parser.add_argument("--follow", action="store_true",
                        help="tail mode: print new traces as they "
                             "complete")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="--follow poll interval seconds")
    args = parser.parse_args(argv)

    if args.follow:
        try:
            return follow(args)
        except KeyboardInterrupt:
            return 0
    try:
        body = fetch_traces(args.address, trace_id=args.trace_id,
                            cluster=args.cluster, outcome=args.outcome,
                            limit=args.limit, verbose=True,
                            auth=args.auth, since_ms=args.since,
                            min_duration_ms=args.min_duration_ms)
    except (urllib.error.URLError, OSError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body, indent=2, sort_keys=True))
        return 0
    traces = body.get("traces", [])
    if not traces:
        print("no matching traces", file=sys.stderr)
        return 1
    for doc in traces:
        print(render_trace(doc))
        print()
    rec = body.get("recorder", {})
    if rec:
        print(f"# recorder: {rec.get('retained', 0)} retained, "
              f"{rec.get('pinned', 0)} pinned, "
              f"{rec.get('recorded', 0)} recorded", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
