"""Scripted chaos sweep: drive the solver degradation ladder through
deterministic fault scenarios against an in-process simulated stack and
report one JSON line per scenario.

The operational counterpart of tests/test_chaos.py: where the test suite
pins the contract, this tool lets an operator (or CI job) replay the
scenarios against the CURRENT build and inspect the ladder's behavior —
rungs visited, breaker transitions, anomalies emitted, quarantine
counts.  Exit code 0 = every scenario behaved; 1 = a scenario deviated.

Usage: JAX_PLATFORMS=cpu python tools/chaos_sweep.py [--json]
       JAX_PLATFORMS=cpu python tools/chaos_sweep.py --drill mesh [--json]
       JAX_PLATFORMS=cpu python tools/chaos_sweep.py --drill executor-crash

`--drill mesh` runs the PR-12 elastic-mesh drill on the virtual 8-CPU
mesh: condemn a chip mid-solve, assert span shrink + recovery without a
process bounce, and print time-to-first-good-solve.

`--drill executor-crash` runs the PR-13 crash-recovery drill: kill a
simulated process mid-rebalance (throttles applied, reassignments in
flight), replay the executor journal in a fresh "process", and assert
the resumed execution completes byte-equal to an uncrashed twin with
no duplicate submissions and no leaked throttles (docs/EXECUTOR.md).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from cruise_control_tpu.analyzer.degradation import (BreakerState,  # noqa: E402
                                                     SolverRung)
from cruise_control_tpu.cluster.simulated import SimulatedCluster  # noqa: E402
from cruise_control_tpu.cluster.types import TopicPartition  # noqa: E402
from cruise_control_tpu.detector.anomalies import SolverDegraded  # noqa: E402
from cruise_control_tpu.detector.notifier import (AnomalyNotifier,  # noqa: E402
                                                  NotificationAction)
from cruise_control_tpu.facade import CruiseControl  # noqa: E402
from cruise_control_tpu.monitor.sampling.sampler import (  # noqa: E402
    SimulatedClusterSampler)
from cruise_control_tpu.utils import faults  # noqa: E402

GOALS = ["RackAwareGoal", "DiskCapacityGoal", "ReplicaDistributionGoal",
         "DiskUsageDistributionGoal"]


class _Recorder(AnomalyNotifier):
    def __init__(self):
        self.anomalies = []

    def on_anomaly(self, anomaly):
        self.anomalies.append(anomaly)
        return NotificationAction.ignore()

    def self_healing_enabled(self):
        return {}


def build_stack(num_brokers=4, partitions=12, **cc_kwargs):
    sim = SimulatedCluster()
    clock = {"now": 10_000.0}
    for b in range(num_brokers):
        sim.add_broker(b, rack=f"rack{b % 2}")
    assignments = [[0, 1] for _ in range(partitions)]   # skewed on 0/1
    sim.create_topic("t0", assignments, size_bytes=1e4)
    for p in range(partitions):
        sim.set_partition_load(TopicPartition("t0", p), leader_cpu=2.0,
                               nw_in=100.0, nw_out=300.0)
    notifier = _Recorder()
    cc = CruiseControl(
        sim, SimulatedClusterSampler(sim),
        anomaly_notifier=notifier,
        time_fn=lambda: clock["now"],
        sleep_fn=lambda s: (sim.advance(s),
                            clock.__setitem__("now", clock["now"] + s)),
        monitor_kwargs=dict(num_windows=3, window_ms=10_000,
                            min_samples_per_window=1,
                            sampling_interval_ms=5_000),
        executor_kwargs=dict(progress_check_interval_s=1.0),
        auto_warmup=False,
        solver_breaker_cooldown_s=50.0,
        goal_names=GOALS, **cc_kwargs)
    cc.start_up(do_sampling=False, start_detection=False)
    return sim, cc, clock, notifier


def feed(cc, clock, rounds=8):
    for _ in range(rounds):
        cc.load_monitor.task_runner.sample_once()
        clock["now"] += 10.0


def scenario_quarantine():
    """NaN samples are dropped at ingest, behind a counter."""
    sim, cc, clock, _ = build_stack()
    try:
        feed(cc, clock)
        fetcher = cc.load_monitor._fetcher
        orig = fetcher._sampler.get_samples

        def corrupting(*args, **kwargs):
            out = orig(*args, **kwargs)
            out.partition_samples = [
                type(s)(s.broker_id, s.tp, s.sample_time_ms,
                        {k: float("nan") for k in s.values})
                for s in out.partition_samples]
            return out

        fetcher._sampler.get_samples = corrupting
        try:
            cc.load_monitor.task_runner.sample_once()
        finally:
            fetcher._sampler.get_samples = orig
        quarantined = fetcher.num_quarantined_samples
        return {"scenario": "quarantine", "ok": quarantined > 0,
                "quarantined": quarantined}
    finally:
        cc.shutdown()


def scenario_ladder_descent_and_recovery():
    """Persistent device faults: fused -> eager -> CPU, breaker pins,
    cooldown elapses, probes climb back, breaker re-closes."""
    sim, cc, clock, notifier = build_stack()
    try:
        feed(cc, clock)
        cc.optimizations()
        path = [cc.solver_ladder.rung.name]
        feed(cc, clock, rounds=1)
        plan = faults.FaultPlan() \
            .fail_always("optimizer.compile") \
            .fail_always("optimizer.execute")
        with faults.injected(plan):
            cc.optimizations(ignore_proposal_cache=True)
        path.append(cc.solver_ladder.rung.name)
        breaker_open = cc.solver_breaker.state is BreakerState.OPEN
        clock["now"] += 55.0
        feed(cc, clock, rounds=8)
        cc.optimizations(ignore_proposal_cache=True)
        path.append(cc.solver_ladder.rung.name)
        feed(cc, clock, rounds=1)
        cc.optimizations(ignore_proposal_cache=True)
        path.append(cc.solver_ladder.rung.name)
        cc.anomaly_detector.process_all()
        events = [str(a) for a in notifier.anomalies
                  if isinstance(a, SolverDegraded)]
        recovered = (cc.solver_ladder.rung is SolverRung.FUSED
                     and cc.solver_breaker.state is BreakerState.CLOSED)
        return {"scenario": "ladder-descent-recovery",
                "ok": (path == ["FUSED", "CPU", "EAGER", "FUSED"]
                       and breaker_open and recovered
                       and len(events) == 3),
                "rungPath": path, "breakerTripped": breaker_open,
                "anomalies": events}
    finally:
        cc.shutdown()


def scenario_retry_bit_for_bit():
    """A solve retried after a mid-pipeline fault matches the
    fault-free solve exactly (re-materialized inputs)."""
    def fingerprint(result):
        placements = sorted(
            (p.partition.topic, p.partition.partition,
             tuple(r.broker_id for r in p.old_replicas),
             tuple(r.broker_id for r in p.new_replicas))
            for p in result.proposals)
        return placements, np.asarray(result.final_state.replica_broker)

    sim, cc, clock, _ = build_stack()
    try:
        feed(cc, clock)
        baseline = cc.optimizations()
    finally:
        cc.shutdown()
    sim2, cc2, clock2, _ = build_stack()
    try:
        feed(cc2, clock2)
        with faults.injected(
                faults.FaultPlan().fail_nth("optimizer.execute", 2)):
            retried = cc2.optimizations()
        retries = cc2.metrics.to_json()["solver-retries"]["count"]
    finally:
        cc2.shutdown()
    bp, bs = fingerprint(baseline)
    rp, rs = fingerprint(retried)
    ok = bp == rp and np.array_equal(bs, rs) and retries == 1
    return {"scenario": "retry-bit-for-bit", "ok": ok,
            "proposals": len(bp), "retries": retries}


def scenario_mesh_drill():
    """Operator mesh drill (`--drill mesh`): condemn a chip mid-solve
    on the virtual 8-CPU mesh, assert the supervisor shrinks the span
    and completes the solve without a restart, report time-to-first-
    good-solve, then prove probe recovery climbs back once the chip
    answers again.  The operational counterpart of
    tests/test_meshhealth.py — run it against the CURRENT build before
    trusting mesh.recovery.enabled in production."""
    import time as _real_time
    from cruise_control_tpu.parallel import health
    from cruise_control_tpu.testing.virtual_mesh import force_cpu_devices
    force_cpu_devices(8)
    import jax
    dead = jax.devices()[5].id
    sim, cc, clock, notifier = build_stack(
        num_brokers=6,
        mesh_enabled=True, mesh_watchdog_ms=30_000.0,
        mesh_probe_interval_ms=1e12)
    try:
        feed(cc, clock)
        plan = (faults.FaultPlan()
                .fail_always(f"mesh.probe.dev{dead}")
                .fail_nth("optimizer.mesh", 1))
        t0 = _real_time.monotonic()
        with faults.injected(plan):
            result = cc.optimizations()
        recovery_s = _real_time.monotonic() - t0
        sup = cc.mesh_supervisor
        shrunk_ok = (sup is not None and sup.span == 4
                     and sup.condemned == [dead]
                     and result.mesh_devices == 4
                     and len(result.proposals) > 0)
        cc.anomaly_detector.process_all()
        from cruise_control_tpu.detector.anomalies import MeshDegraded
        anomalies = [str(a) for a in notifier.anomalies
                     if isinstance(a, MeshDegraded)]
        # the chip comes back: one probe cycle climbs the span home
        sup.probe_interval_ms = 0.0
        clock["now"] += 60.0
        again = cc.optimizations(ignore_proposal_cache=True)
        recovered = (sup.span == 8 and sup.condemned == []
                     and again.mesh_devices == 8)
        return {"scenario": "mesh-drill",
                "ok": shrunk_ok and recovered and len(anomalies) >= 1,
                "condemned": [dead], "spanPath": [8, 4, 8],
                "timeToFirstGoodSolveS": round(recovery_s, 3),
                "anomalies": anomalies}
    finally:
        cc.shutdown()


def scenario_executor_crash_drill():
    """Operator crash-recovery drill (`--drill executor-crash`): the
    operational counterpart of tests/test_executor_recovery.py — run
    it against the CURRENT build before trusting executor.journal.dir
    + executor.recovery.mode=resume in production."""
    import tempfile
    import time as _real_time
    from cruise_control_tpu.analyzer.proposals import (ExecutionProposal,
                                                       ReplicaPlacement)
    from cruise_control_tpu.executor import ExecutionJournal, Executor
    from cruise_control_tpu.model.builder import PartitionId

    def proposal(part, old, new, size=40e6):
        return ExecutionProposal(
            partition=PartitionId("t", part), old_leader=old[0],
            old_replicas=tuple(ReplicaPlacement(b) for b in old),
            new_replicas=tuple(ReplicaPlacement(b) for b in new),
            partition_size=size)

    def make_sim():
        sim = SimulatedCluster()
        sim._move_rate = 20e6
        for b in range(4):
            sim.add_broker(b, rack=f"r{b % 2}")
        sim.create_topic("t", [[0, 1], [1, 2]], size_bytes=40e6)
        return sim

    def placement(sim):
        snap = sim.describe_cluster()
        return {p: (list(snap.partition(TopicPartition("t", p)).replicas),
                    snap.partition(TopicPartition("t", p)).leader)
                for p in range(2)}

    proposals = [proposal(0, [0, 1], [2, 1]), proposal(1, [1, 2], [3, 2])]
    twin_sim = make_sim()
    Executor(twin_sim, progress_check_interval_s=1.0,
             time_fn=lambda: twin_sim.now_ms() / 1000.0,
             sleep_fn=twin_sim.advance).execute_proposals(
        proposals, reason="twin", wait=True)
    twin = placement(twin_sim)

    sim = make_sim()
    with tempfile.TemporaryDirectory() as jdir:
        journal = ExecutionJournal(
            jdir, time_fn=lambda: sim.now_ms() / 1000.0)
        dead = {"dead": False}

        class Proxy:
            def __getattr__(self, name):
                real = getattr(sim, name)
                if not callable(real):
                    return real

                def call(*a, **k):
                    if dead["dead"]:
                        raise RuntimeError("process is dead")
                    return real(*a, **k)
                return call

        ex = Executor(Proxy(), progress_check_interval_s=1.0,
                      journal=journal,
                      replication_throttle_bytes_per_s=100e6,
                      time_fn=lambda: sim.now_ms() / 1000.0)
        sleeps = {"n": 0}

        def crashing_sleep(s):
            sleeps["n"] += 1
            if sleeps["n"] == 2:      # mid-inter-broker phase
                dead["dead"] = True
                journal.broken = True
                raise RuntimeError("SIGKILL (simulated)")
            sim.advance(s)
        ex._sleep = crashing_sleep
        uuid = ex.execute_proposals(proposals, reason="drill", wait=True)
        half_moved = placement(sim) != twin
        in_flight = bool(sim.list_partition_reassignments())

        dead["dead"] = False          # the replacement process boots
        t0 = _real_time.monotonic()
        journal2 = ExecutionJournal(
            jdir, time_fn=lambda: sim.now_ms() / 1000.0)
        ex2 = Executor(sim, progress_check_interval_s=1.0,
                       journal=journal2,
                       time_fn=lambda: sim.now_ms() / 1000.0,
                       sleep_fn=sim.advance)
        report = ex2.recover(mode="resume", wait=True)
        recovery_s = _real_time.monotonic() - t0
    resumed_ok = (report is not None and report["uuid"] == uuid
                  and placement(sim) == twin
                  and all(b.throttle is None
                          for b in sim._brokers.values()))
    return {"scenario": "executor-crash-drill",
            "ok": half_moved and in_flight and resumed_ok,
            "uuidPreserved": bool(report and report["uuid"] == uuid),
            "report": report,
            "timeToRecoveredS": round(recovery_s, 3)}


SCENARIOS = [scenario_quarantine, scenario_ladder_descent_and_recovery,
             scenario_retry_bit_for_bit]


def main(argv) -> int:
    as_json = "--json" in argv
    scenarios = list(SCENARIOS)
    if "--drill" in argv:
        which = argv[argv.index("--drill") + 1] \
            if argv.index("--drill") + 1 < len(argv) else ""
        drills = {"mesh": scenario_mesh_drill,
                  "executor-crash": scenario_executor_crash_drill}
        if which not in drills:
            print(f"unknown drill {which!r}; valid: "
                  f"{', '.join(sorted(drills))}", file=sys.stderr)
            return 2
        scenarios = [drills[which]]
    results = []
    for fn in scenarios:
        try:
            results.append(fn())
        except Exception as exc:  # noqa: BLE001 - a crash fails the sweep
            results.append({"scenario": fn.__name__, "ok": False,
                            "error": f"{type(exc).__name__}: {exc}"})
    ok = all(r["ok"] for r in results)
    if as_json:
        print(json.dumps({"ok": ok, "scenarios": results}))
    else:
        for r in results:
            print(("PASS" if r["ok"] else "FAIL"), r["scenario"],
                  {k: v for k, v in r.items()
                   if k not in ("scenario", "ok")})
        print("chaos sweep:", "OK" if ok else "FAILED", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
