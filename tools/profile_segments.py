"""Segment-level solve profiler CLI (VERDICT round-5 missing item #1).

Runs one full multi-goal solve over a BASELINE.json eval config with the
segment profiler active (CC_TPU_PROFILE) and prints the per-segment
attribution table: prebalance / per-goal table rounds / per-goal stats
epilogues / leadership / final diff / instrument transfer — the
shards-vs-replicates breakdown of the north wall-clock.

    python tools/profile_segments.py              # BENCH_CONFIG=north
    BENCH_CONFIG=2 python tools/profile_segments.py
    python tools/profile_segments.py --json out.json

Profile mode inserts explicit sync points and runs one program per goal,
so the total here is NOT comparable to an unprofiled `python bench.py`
run — use it for attribution, bench.py for the headline number.  The
first solve additionally pays per-goal program compiles (the fused
warmup programs do not cover the profile-mode segmentation); pass
--solves 2 to also time a compile-warm second solve.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("CC_TPU_PROFILE", "1")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", help="also write the profile as JSON here")
    ap.add_argument("--solves", type=int, default=1,
                    help="profiled solves to run (2 = add a compile-warm "
                         "pass; only the LAST solve is reported)")
    args = ap.parse_args()

    import logging
    logging.basicConfig(stream=sys.stderr, level=logging.INFO,
                        format="# %(message)s")

    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])

    import bench
    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.utils import profiling

    config = os.environ.get("BENCH_CONFIG", "north")
    num_b = int(os.environ.get("BENCH_BROKERS", 2600 if config in
                               ("north", "4", "5") else 200))
    num_p = int(os.environ.get("BENCH_PARTITIONS", 200_000 if config in
                               ("north", "4", "5") else 20_000))
    rf = int(os.environ.get("BENCH_RF", 3))
    rounds = int(os.environ.get("BENCH_ROUNDS", 192))
    names = (os.environ.get("BENCH_GOALS").split(",")
             if os.environ.get("BENCH_GOALS") else None)

    backend = jax.devices()[0].platform
    print(f"# profile_segments config={config} backend={backend}",
          file=sys.stderr)
    state, topo = bench._build(config, num_b, num_p, rf)
    print(f"# model: B={state.num_brokers} P={state.num_partitions} "
          f"R={state.num_replicas}", file=sys.stderr)

    optimizer = GoalOptimizer(default_goals(max_rounds=rounds, names=names))
    profiler = profiling.install()
    result = None
    for i in range(max(1, args.solves)):
        profiler.reset()
        t0 = time.time()
        result = optimizer.optimizations(state, topo, OptimizationOptions(),
                                         check_sanity=False)
        print(f"# solve {i}: {time.time() - t0:.1f}s (profiled; includes "
              f"sync points{' + compiles' if i == 0 else ''})",
              file=sys.stderr)

    print(profiler.table())
    print(f"proposals={len(result.proposals)} "
          f"violated_after={len(result.violated_goals_after)} "
          f"balancedness={result.balancedness_score():.1f}")
    if args.json:
        payload = profiler.to_json()
        payload["config"] = config
        payload["backend"] = backend
        payload["rounds_by_goal"] = result.rounds_by_goal
        with open(args.json, "w") as fh:
            json.dump(payload, fh, indent=1)
        print(f"# wrote {args.json}", file=sys.stderr)


if __name__ == "__main__":
    main()
