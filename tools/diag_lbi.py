"""Diagnose the LeaderBytesIn residual at north scale.

Solves the north config (cached programs), then — on the FINAL state —
enumerates every lbi-over broker's candidate leadership transfers and
classifies the veto that blocks each: the goal's own bounds (dest
already over / improve gate), the leader-count band, the CPU band, the
NW_OUT band, structural (no eligible sibling).  The north-scale analog
of tests/test_leader_semantics.py's hand enumeration: it separates
"strict-priority semantics the reference would also leave" from
"search interference this framework should fix".
"""
import os
import sys

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])

import jax.numpy as jnp  # noqa: E402

from cruise_control_tpu.analyzer.context import (  # noqa: E402
    OptimizationOptions, make_context, make_round_cache)
from cruise_control_tpu.analyzer.goals.registry import (  # noqa: E402
    default_goals)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer  # noqa: E402
from cruise_control_tpu.model import state as S  # noqa: E402
from cruise_control_tpu.testing.random_cluster import (  # noqa: E402
    RandomClusterSpec, random_cluster)


def main() -> None:
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=2600, num_partitions=200_000, replication_factor=3,
        num_racks=26, num_topics=100, seed=4, skew_fraction=0.2))
    goals = default_goals(max_rounds=192)
    opt = GoalOptimizer(goals, pipeline_segment_size=2)
    opt.warmup(state, topo, OptimizationOptions())
    res = opt.optimizations(state, topo, OptimizationOptions(),
                            check_sanity=False)
    fs = res.final_state
    print("violated:", {g: c for g, c in res.violated_broker_counts.items()
                        if any(c)})

    ctx = make_context(fs, opt.constraint, OptimizationOptions(), topo)
    cache = make_round_cache(fs, 0, ctx)
    lbi_goal = next(g for g in goals
                    if g.name == "LeaderBytesInDistributionGoal")
    lr_goal = next(g for g in goals
                   if g.name == "LeaderReplicaDistributionGoal")
    prev = goals[:goals.index(lbi_goal)]

    @jax.jit
    def classify(fs, cache):
        lbi = cache.leader_bytes_in
        # _bounds returns a scalar threshold; broadcast per broker
        upper = jnp.broadcast_to(lbi_goal._bounds(fs, lbi),
                                 (fs.num_brokers,))
        over = fs.broker_alive & (lbi > upper)
        rows = ctx.partition_replicas
        rows_safe = jnp.maximum(rows, 0)
        cur = S.partition_leader_replica(fs)
        cur_safe = jnp.maximum(cur, 0)
        src_b = fs.replica_broker[cur_safe]
        # partitions whose leader sits on an over-lbi broker and carries
        # positive bytes-in
        value = fs.replica_base_load[cur_safe, 1] * fs.replica_valid[
            cur_safe]
        live = (cur >= 0) & over[src_b] & (value > 0.0)
        cand_b = fs.replica_broker[rows_safe]
        struct = ((rows >= 0) & (rows != cur[:, None])
                  & fs.replica_valid[rows_safe]
                  & fs.broker_alive[cand_b] & ctx.broker_leader_ok[cand_b])
        # own-goal: dest stays under the lbi upper bound
        arrive = fs.replica_base_load[rows_safe, 1]
        own_ok = lbi[cand_b] + arrive <= upper[cand_b]
        # per-prior-goal acceptance, evaluated separately
        per_goal_ok = {}
        for g in prev:
            a = g.accept_leadership(fs, ctx, cache, cur_safe[:, None],
                                    rows_safe)
            per_goal_ok[g.name] = a
        all_prev = jnp.ones_like(struct)
        for a in per_goal_ok.values():
            all_prev &= a
        fixable = live[:, None] & struct & own_ok & all_prev
        # per-partition: does ANY option survive everything?
        has_fix = jnp.any(fixable, axis=1) & live
        # veto attribution: options passing struct+own but killed by
        # exactly this goal (all other prev goals accept)
        attribution = {}
        base_ok = live[:, None] & struct & own_ok
        for name, a in per_goal_ok.items():
            others = jnp.ones_like(struct)
            for n2, a2 in per_goal_ok.items():
                if n2 != name:
                    others &= a2
            sole = base_ok & others & ~a
            attribution[name] = jnp.sum(jnp.any(sole, axis=1)
                                        & ~has_fix & live)
        return (jnp.sum(over), jnp.sum(live), jnp.sum(has_fix),
                jnp.sum(live & ~jnp.any(struct & own_ok, axis=1)),
                attribution)

    over_n, live_n, fix_n, own_blocked, attr = jax.device_get(
        classify(fs, cache))
    print(f"over-lbi brokers: {over_n}")
    print(f"live candidate partitions (leader on over broker): {live_n}")
    print(f"partitions with a FULLY acceptable fixing transfer: {fix_n}")
    print(f"partitions blocked by own-goal/structural alone: {own_blocked}")
    print("sole-veto attribution (options alive but for this ONE goal):")
    for name, n in attr.items():
        if int(n):
            print(f"  {name}: {int(n)} partitions")


if __name__ == "__main__":
    main()
