"""Warm-start measurement: cold solve vs a solve seeded from the previous
final placement after a load perturbation.

The production shape this measures (facade.optimizations warm path): the
precompute loop solved generation N; new samples arrive (loads change a
few percent, placement unchanged), the generation moves, and the next
request's solve warm-starts from generation N's final placement
(GoalOptimizer.optimizations(warm_start=...)).  The reference serves its
proposal cache only while the generation is UNCHANGED
(reference GoalOptimizer.java:210-217, 275-330); the warm start extends
the same cached artifact across generation moves.

Usage:  python tools/bench_warmstart.py          (north scale by default)
Env:    WARM_BROKERS / WARM_PARTITIONS / WARM_RF / WARM_NOISE (default
        0.03 = ±3% multiplicative load jitter, the "new samples" model).

Prints per-phase wall-clock on stderr and ONE JSON line on stdout:
  {"metric": "warm-start solve ...", "value": <warm seconds>,
   "cold_s": ..., "speedup": ...}
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def main() -> None:
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import numpy as np

    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.testing.random_cluster import (RandomClusterSpec,
                                                           random_cluster)

    num_b = int(os.environ.get("WARM_BROKERS", 2600))
    num_p = int(os.environ.get("WARM_PARTITIONS", 200_000))
    rf = int(os.environ.get("WARM_RF", 3))
    noise = float(os.environ.get("WARM_NOISE", 0.03))

    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=num_b, num_partitions=num_p, replication_factor=rf,
        num_racks=max(8, num_b // 100), num_topics=max(8, num_p // 2000),
        seed=4, skew_fraction=0.2))
    optimizer = GoalOptimizer(default_goals(max_rounds=192),
                              pipeline_segment_size=2)

    t0 = time.time()
    optimizer.warmup(state, topo, OptimizationOptions())
    print(f"# warmup (parallel AOT) {time.time()-t0:.1f}s", file=sys.stderr)

    # cold solve = generation N's precompute pass
    t0 = time.time()
    cold = optimizer.optimizations(state, topo, check_sanity=False)
    cold_s = time.time() - t0
    print(f"# cold solve {cold_s:.1f}s rounds="
          f"{sum(cold.rounds_by_goal.values())}", file=sys.stderr)

    # generation N+1: same placement/topology, loads jittered ±noise —
    # the "new samples arrived" model
    rng = np.random.default_rng(11)
    jit_r = (1.0 + noise * (2.0 * rng.random(
        (state.num_replicas, 1)) - 1.0)).astype(np.float32)
    jit_p = (1.0 + noise * (2.0 * rng.random(
        (state.num_partitions, 1)) - 1.0)).astype(np.float32)
    perturbed = state.replace(
        replica_base_load=state.replica_base_load * jit_r,
        partition_leader_bonus=state.partition_leader_bonus * jit_p)

    t0 = time.time()
    warm = optimizer.optimizations(perturbed, topo, check_sanity=False,
                                   warm_start=cold.final_state)
    warm_s = time.time() - t0
    print(f"# warm-start solve {warm_s:.1f}s rounds="
          f"{sum(warm.rounds_by_goal.values())} "
          f"proposals={len(warm.proposals)} "
          f"balancedness={warm.balancedness_score():.1f}", file=sys.stderr)

    # control: the same perturbed model solved COLD (what the warm start
    # saves against)
    t0 = time.time()
    control = optimizer.optimizations(perturbed, topo, check_sanity=False)
    control_s = time.time() - t0
    print(f"# perturbed cold control {control_s:.1f}s rounds="
          f"{sum(control.rounds_by_goal.values())} "
          f"balancedness={control.balancedness_score():.1f}",
          file=sys.stderr)

    print("# warm violated after-all: "
          + ", ".join(f"{g}={a}" for g, (b, o, a)
                      in warm.violated_broker_counts.items() if a),
          file=sys.stderr)
    print(json.dumps({
        "metric": (f"warm-start solve {num_b}b/{num_p/1000:g}Kp rf{rf} "
                   f"noise={noise:g}"),
        "value": round(warm_s, 3), "unit": "s",
        "cold_s": round(control_s, 3),
        "speedup": round(control_s / warm_s, 2),
    }))


if __name__ == "__main__":
    main()
