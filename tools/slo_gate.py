"""SLO regression gate over load-harness run artifacts.

The soak rig's pass/fail edge (docs/LOADGEN.md "Baseline workflow"):
diff a run artifact (cruise_control_tpu/loadgen/artifact.py) against a
recorded baseline and exit non-zero on breach, so a perf PR cites a
green gate instead of eyeballed percentiles.

Record a baseline from a known-good run::

    python tools/slo_gate.py --artifact run.json --write-baseline \
        baseline.json

Gate a later run::

    python tools/slo_gate.py --artifact run.json --baseline \
        baseline.json
    # exit 0 = within objectives AND within tolerance of the baseline
    # exit 1 = breach (each breach printed on stderr)
    # exit 2 = unusable input (invalid artifact / missing file)

What breaches (each independently):

* the artifact fails structural validation;
* the run's own SLO block reports burn >= the alert threshold for any
  class (`--max-burn` overrides the artifact's threshold);
* the error rate exceeds `--max-error-rate`, or the 429-rejection rate
  exceeds `--max-rejected-rate` (backpressure is by design — the cap
  only catches a server that rejected the bulk of the load);
* a per-class client p99 regressed beyond `--p99-tolerance` x baseline
  (classes absent from the baseline are skipped: no silent cap);
* a per-class DEVICE-TIME p99 (from span trees) regressed beyond the
  same tolerance — catching a solver regression that queue-wait
  improvements would otherwise mask.

`BENCH_CONFIG=soak` (bench.py) runs a seeded profile, writes the
artifact, self-baselines the clean run, and asserts this gate passes
clean and fails under an injected `sched.dispatch` latency fault.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from cruise_control_tpu.loadgen.artifact import validate_artifact  # noqa: E402

BASELINE_VERSION = 1

#: default tolerances (CLI-overridable)
DEFAULT_P99_TOLERANCE = 1.5
DEFAULT_MAX_ERROR_RATE = 0.02
DEFAULT_MAX_REJECTED_RATE = 0.5


def distill_baseline(artifact: dict) -> dict:
    """The gate-relevant slice of a known-good artifact: per-class
    client p99 + device-time p99, plus provenance (profile, seed, plan
    digest) so a baseline silently reused against a DIFFERENT workload
    is detectable."""
    classes = {}
    for klass, block in artifact.get("latency", {}).items():
        classes[klass] = {"p99Ms": block.get("p99Ms", 0.0),
                          "count": block.get("count", 0)}
    for klass, block in artifact.get("decomposition", {}).items():
        classes.setdefault(klass, {})["deviceP99Ms"] = \
            block.get("deviceMs", {}).get("p99", 0.0)
    return {
        "sloBaseline": BASELINE_VERSION,
        "profile": artifact.get("profile", {}).get("name"),
        "seed": artifact.get("seed"),
        "planDigest": artifact.get("planDigest"),
        "classes": classes,
    }


def gate(artifact: dict, baseline: Optional[dict] = None,
         p99_tolerance: float = DEFAULT_P99_TOLERANCE,
         max_error_rate: float = DEFAULT_MAX_ERROR_RATE,
         max_rejected_rate: float = DEFAULT_MAX_REJECTED_RATE,
         max_burn: Optional[float] = None) -> List[str]:
    """Every breach as a human-readable string ([] = gate passes).
    `baseline` may be a distilled baseline or a full prior artifact."""
    breaches: List[str] = []
    problems = validate_artifact(artifact)
    if problems:
        return [f"invalid artifact: {p}" for p in problems]

    # 1. the run's own SLO burn
    slo = artifact.get("slo") or {}
    if slo.get("enabled"):
        alert_at = (max_burn if max_burn is not None
                    else float(slo.get("alertThreshold", 2.0)))
        for klass, cls in sorted((slo.get("classes") or {}).items()):
            burn = float(cls.get("burn", 0.0))
            if burn >= alert_at:
                dominant = ("queue-wait"
                            if cls.get("queueWaitBurn", 0.0)
                            >= cls.get("deviceTimeBurn", 0.0)
                            else "device-time")
                breaches.append(
                    f"SLO burn: {klass} at {burn:.2f}x budget "
                    f"(alert {alert_at:.1f}x, {dominant}-driven)")

    # 2. error / rejection rates over EXECUTED requests (rig-only kinds
    # skipped against a remote server must not dilute the caps)
    requests = artifact.get("requests", {})
    executed = max(1, requests.get(
        "executed",
        requests.get("total", 0) - requests.get("skipped", 0)))
    error_rate = requests.get("errors", 0) / executed
    if error_rate > max_error_rate:
        breaches.append(f"error rate {error_rate:.3f} > "
                        f"{max_error_rate} "
                        f"({requests.get('errors')}/{executed})")
    rejected_rate = requests.get("rejected", 0) / executed
    if rejected_rate > max_rejected_rate:
        breaches.append(f"rejected rate {rejected_rate:.3f} > "
                        f"{max_rejected_rate}")

    # 3. vs baseline
    if baseline is not None:
        base_classes = (baseline.get("classes")
                        if "sloBaseline" in baseline
                        else distill_baseline(baseline)["classes"])
        if baseline.get("planDigest") \
                and artifact.get("planDigest") \
                and baseline["planDigest"] != artifact["planDigest"]:
            breaches.append(
                "baseline was recorded from a DIFFERENT plan "
                f"(digest {str(baseline['planDigest'])[:12]}... vs "
                f"{str(artifact['planDigest'])[:12]}...); re-record it "
                "or run the matching profile/seed")
        for klass, base in sorted((base_classes or {}).items()):
            run = artifact.get("latency", {}).get(klass)
            base_p99 = float(base.get("p99Ms", 0.0) or 0.0)
            if run is not None and base_p99 > 0.0:
                p99 = float(run.get("p99Ms", 0.0))
                if p99 > base_p99 * p99_tolerance:
                    breaches.append(
                        f"{klass} client p99 regressed: {p99:.1f}ms vs "
                        f"baseline {base_p99:.1f}ms "
                        f"(> {p99_tolerance:.2f}x)")
            base_dev = float(base.get("deviceP99Ms", 0.0) or 0.0)
            run_dev = (artifact.get("decomposition", {})
                       .get(klass, {}).get("deviceMs", {}).get("p99"))
            if base_dev > 0.0 and run_dev is not None:
                if float(run_dev) > base_dev * p99_tolerance:
                    breaches.append(
                        f"{klass} device-time p99 regressed: "
                        f"{float(run_dev):.1f}ms vs baseline "
                        f"{base_dev:.1f}ms (> {p99_tolerance:.2f}x)")
    return breaches


def _load(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="slo_gate",
        description="gate a loadgen run artifact against its SLOs and "
                    "a recorded baseline (exit 0 pass / 1 breach)")
    parser.add_argument("--artifact", required=True,
                        help="run artifact JSON (loadgen harness output)")
    parser.add_argument("--baseline",
                        help="recorded baseline (or a prior artifact)")
    parser.add_argument("--write-baseline", metavar="PATH",
                        help="distill the artifact into a baseline at "
                             "PATH and exit (no gating)")
    parser.add_argument("--p99-tolerance", type=float,
                        default=DEFAULT_P99_TOLERANCE,
                        help="allowed p99 growth factor vs baseline "
                             f"(default {DEFAULT_P99_TOLERANCE})")
    parser.add_argument("--max-error-rate", type=float,
                        default=DEFAULT_MAX_ERROR_RATE,
                        help="allowed fraction of errored requests "
                             f"(default {DEFAULT_MAX_ERROR_RATE})")
    parser.add_argument("--max-rejected-rate", type=float,
                        default=DEFAULT_MAX_REJECTED_RATE,
                        help="allowed fraction of 429-rejected requests "
                             f"(default {DEFAULT_MAX_REJECTED_RATE})")
    parser.add_argument("--max-burn", type=float,
                        help="burn threshold override (default: the "
                             "artifact's own alert threshold)")
    args = parser.parse_args(argv)

    try:
        artifact = _load(args.artifact)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"error: cannot read artifact: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        problems = validate_artifact(artifact)
        if problems:
            for p in problems:
                print(f"error: invalid artifact: {p}", file=sys.stderr)
            return 2
        with open(args.write_baseline, "w") as fh:
            json.dump(distill_baseline(artifact), fh, indent=2,
                      sort_keys=True)
            fh.write("\n")
        print(f"baseline written to {args.write_baseline}")
        return 0

    baseline = None
    if args.baseline:
        try:
            baseline = _load(args.baseline)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"error: cannot read baseline: {exc}", file=sys.stderr)
            return 2

    breaches = gate(artifact, baseline,
                    p99_tolerance=args.p99_tolerance,
                    max_error_rate=args.max_error_rate,
                    max_rejected_rate=args.max_rejected_rate,
                    max_burn=args.max_burn)
    if breaches:
        for b in breaches:
            print(f"BREACH: {b}", file=sys.stderr)
        print(f"slo_gate: {len(breaches)} breach(es)", file=sys.stderr)
        return 1
    print("slo_gate: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
