"""Operator CLI for the persistent compiled-program cache.

Subcommands (all take ``--dir``, default ``.progcache``):

  list     table of entries: program, goal/shape signature, fingerprint,
           age, size, recorded hit count (current fingerprint only by
           default; --all shows stale generations)
  inspect  one entry's sidecar meta + the deserialized export's
           input avals / device span
  verify   deserialize every entry; corrupt ones are reported and (with
           --quarantine) moved aside exactly like the serving path does
  evict    delete entries: --all, --stale (non-current fingerprints),
           --older-than SECONDS, or --max-bytes N (oldest-first down to
           the cap)
  warm     pre-populate the cache for the DEFAULT goal stack offline
           (`make warm-cache`): builds a synthetic cluster of the given
           geometry and runs the cache-first warmup, so the next
           process/tenant with that shape bucket cold-starts in seconds

Exit code 1 when verify finds corrupt entries; 0 otherwise.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cache(args):
    from cruise_control_tpu.parallel import progcache
    cache = progcache.get_cache()
    cache.configure(enabled=True, cache_dir=args.dir)
    return cache


def _fmt_age(seconds: float) -> str:
    if seconds < 120:
        return f"{seconds:.0f}s"
    if seconds < 7200:
        return f"{seconds / 60:.0f}m"
    if seconds < 172800:
        return f"{seconds / 3600:.1f}h"
    return f"{seconds / 86400:.1f}d"


def cmd_list(args) -> int:
    cache = _cache(args)
    entries = cache.entries(all_fingerprints=args.all)
    if args.json:
        print(json.dumps([e.to_json() for e in entries], indent=1))
        return 0
    current = cache.fingerprint()
    print(f"{'#':>3} {'program':<28} {'goal':<10} {'shapes':<10} "
          f"{'fprint':<10} {'age':>6} {'size':>9} {'hits':>5}")
    total = 0
    for i, e in enumerate(entries):
        stale = "" if e.fingerprint == current else " (stale)"
        print(f"{i:>3} {e.program:<28} {e.goal_sig[:8]:<10} "
              f"{e.shape_sig[:8]:<10} {e.fingerprint[:8]:<10}"
              f"{stale} {_fmt_age(e.age_s):>6} {e.size_bytes:>9} "
              f"{e.hits:>5}")
        total += e.size_bytes
    print(f"# {len(entries)} entries, {total} bytes "
          f"(fingerprint {current})", file=sys.stderr)
    return 0


def _pick(args, cache):
    entries = cache.entries(all_fingerprints=True)
    sel = args.entry
    if sel.isdigit() and int(sel) < len(entries):
        return entries[int(sel)]
    for e in entries:
        if e.path == sel or e.program == sel:
            return e
    sys.exit(f"no entry matching {sel!r} (index, program name or path)")


def cmd_inspect(args) -> int:
    cache = _cache(args)
    entry = _pick(args, cache)
    out = entry.to_json()
    out["meta"] = entry.meta
    exported = cache.load_exported(entry.program, entry.goal_sig,
                                   entry.shape_sig)
    if exported is not None:
        out["inAvals"] = [f"{tuple(a.shape)}:{a.dtype}"
                          for a in exported.in_avals]
        out["nrDevices"] = int(getattr(exported, "nr_devices", 1))
        out["platforms"] = list(getattr(exported, "platforms", ()))
    else:
        out["deserialize"] = "FAILED (entry quarantined)"
    print(json.dumps(out, indent=1))
    return 0


def cmd_verify(args) -> int:
    cache = _cache(args)
    entries = cache.entries(all_fingerprints=True)
    bad = 0
    for e in entries:
        try:
            from jax import export as jexport
            from cruise_control_tpu.parallel.progcache import \
                ensure_export_registrations
            ensure_export_registrations()
            with open(e.path, "rb") as fh:
                jexport.deserialize(bytearray(fh.read()))
            status = "ok"
        except Exception as exc:  # noqa: BLE001 - verify reports ANY
            # undeserializable entry, whatever broke it
            status = f"CORRUPT ({type(exc).__name__})"
            bad += 1
            if args.quarantine:
                cache.quarantine(e.program, e.goal_sig, e.shape_sig)
                status += " -> quarantined"
        print(f"{e.path}: {status}")
    print(f"# {len(entries)} entries, {bad} corrupt", file=sys.stderr)
    return 1 if bad else 0


def cmd_evict(args) -> int:
    cache = _cache(args)
    entries = cache.entries(all_fingerprints=True)
    current = cache.fingerprint()
    victims = []
    if args.all:
        victims = entries
    elif args.stale:
        victims = [e for e in entries if e.fingerprint != current]
    elif args.older_than is not None:
        victims = [e for e in entries if e.age_s > args.older_than]
    elif args.max_bytes is not None:
        total = sum(e.size_bytes for e in entries)
        for e in entries:  # oldest first
            if total <= args.max_bytes:
                break
            victims.append(e)
            total -= e.size_bytes
    else:
        sys.exit("evict needs one of --all / --stale / "
                 "--older-than / --max-bytes")
    removed = sum(1 for e in victims if cache.evict_entry(e))
    print(f"# evicted {removed}/{len(victims)} entries",
          file=sys.stderr)
    return 0


def cmd_warm(args) -> int:
    import time
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(args.dir, "xla"))
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    cache = _cache(args)
    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.goals.registry import default_goals
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.testing.random_cluster import (
        RandomClusterSpec, random_cluster)

    names = args.goals.split(",") if args.goals else None
    state, topo = random_cluster(RandomClusterSpec(
        num_brokers=args.brokers, num_partitions=args.partitions,
        replication_factor=args.rf, seed=7))
    if args.bucket_floor:
        # pad to the fleet shape bucket so the warmed entries address
        # the same keys tenant solves will (fleet/buckets.py geometry)
        from cruise_control_tpu.fleet.buckets import BucketIndex
        state = BucketIndex(floor=args.bucket_floor).pad(state)
    optimizer = GoalOptimizer(default_goals(names=names),
                              pipeline_segment_size=args.segment)
    mesh = None
    if args.mesh > 1:
        from cruise_control_tpu.parallel.mesh import runtime_mesh
        mesh = runtime_mesh(enabled=True, max_devices=args.mesh).mesh
    t0 = time.time()
    optimizer.warmup(state, topo, OptimizationOptions(), mesh=mesh)
    stats = cache.stats()
    print(json.dumps({
        "warmS": round(time.time() - t0, 2),
        "brokers": state.num_brokers,
        "partitions": state.num_partitions,
        "mesh": args.mesh,
        "hits": stats["hits"],
        "stores": stats["stores"],
        "freshCompiles": stats["freshCompiles"],
    }))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="program_cache",
        description="inspect/maintain the persistent compiled-program "
                    "cache (docs/PROGRAM_CACHE.md)")
    parser.add_argument("--dir", default=".progcache",
                        help="cache directory (progcache.dir)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("list", help="list entries")
    p.add_argument("--all", action="store_true",
                   help="include stale fingerprint generations")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_list)
    p = sub.add_parser("inspect", help="show one entry's metadata")
    p.add_argument("entry", help="index (from list), program name or path")
    p.set_defaults(fn=cmd_inspect)
    p = sub.add_parser("verify", help="deserialize every entry")
    p.add_argument("--quarantine", action="store_true",
                   help="move corrupt entries aside")
    p.set_defaults(fn=cmd_verify)
    p = sub.add_parser("evict", help="delete entries")
    p.add_argument("--all", action="store_true")
    p.add_argument("--stale", action="store_true",
                   help="non-current fingerprints only")
    p.add_argument("--older-than", type=float, default=None,
                   metavar="SECONDS")
    p.add_argument("--max-bytes", type=int, default=None)
    p.set_defaults(fn=cmd_evict)
    p = sub.add_parser("warm",
                       help="pre-populate the cache for the default "
                            "goal stack (make warm-cache)")
    p.add_argument("--brokers", type=int,
                   default=int(os.environ.get("WARM_BROKERS", 64)))
    p.add_argument("--partitions", type=int,
                   default=int(os.environ.get("WARM_PARTITIONS", 2000)))
    p.add_argument("--rf", type=int, default=3)
    p.add_argument("--segment", type=int, default=4)
    p.add_argument("--goals", default="",
                   help="comma-separated goal names (default stack "
                        "when empty)")
    p.add_argument("--mesh", type=int, default=1,
                   help="warm the @meshN programs over N devices")
    p.add_argument("--bucket-floor", type=int, default=0,
                   help="pad the model to the fleet shape bucket first "
                        "(fleet.bucket.floor) so fleet tenants hit the "
                        "warmed entries")
    p.set_defaults(fn=cmd_warm)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
