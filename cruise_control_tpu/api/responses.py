"""JSON response builders for the REST API.

Reference CC/servlet/response/ (30 classes, ~2,900 LoC): BrokerStats for
LOAD, PartitionLoadState for PARTITION_LOAD, KafkaClusterState,
OptimizationResult for PROPOSALS/rebalance-style endpoints.  Re-designed
over the tensor ClusterState: every stat is a vectorized reduction instead
of the reference's per-broker object walks.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional

import numpy as np

from cruise_control_tpu.analyzer.optimizer import OptimizerResult
from cruise_control_tpu.cluster.types import ClusterSnapshot
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model import state as S
from cruise_control_tpu.model.builder import ClusterTopology
from cruise_control_tpu.model.state import ClusterState

_RESOURCE_KEYS = {
    Resource.CPU: "CpuPct", Resource.NW_IN: "NwInRate",
    Resource.NW_OUT: "NwOutRate", Resource.DISK: "DiskMB",
}


def broker_stats(state: ClusterState, topology: ClusterTopology) -> dict:
    """LOAD endpoint body (reference response/stats/BrokerStats.java)."""
    load = np.asarray(S.broker_load(state))            # [B, RES]
    cap = np.asarray(state.broker_capacity)
    alive = np.asarray(state.broker_alive)
    rb = np.asarray(state.replica_broker)
    valid = np.asarray(state.replica_valid)
    leader = np.asarray(state.replica_is_leader) & valid
    num_b = state.num_brokers
    replica_counts = np.bincount(rb[valid], minlength=num_b)
    leader_counts = np.bincount(rb[leader], minlength=num_b)
    util_pct = np.divide(load, np.maximum(cap, 1e-9)) * 100.0

    hosts: Dict[str, dict] = {}
    brokers = []
    for i, bid in enumerate(topology.broker_ids):
        row = {
            "Broker": bid,
            "Host": topology.broker_hosts[i]
            if hasattr(topology, "broker_hosts") else f"broker-{bid}",
            "Rack": topology.rack_ids[int(np.asarray(
                state.broker_rack)[i])],
            "BrokerState": "ALIVE" if alive[i] else "DEAD",
            "Replicas": int(replica_counts[i]),
            "Leaders": int(leader_counts[i]),
            "CpuPct": round(float(load[i, Resource.CPU]), 3),
            "NwInRate": round(float(load[i, Resource.NW_IN]), 3),
            "NwOutRate": round(float(load[i, Resource.NW_OUT]), 3),
            "DiskMB": round(float(load[i, Resource.DISK]), 3),
            "DiskPct": round(float(util_pct[i, Resource.DISK]), 3),
        }
        brokers.append(row)
        h = hosts.setdefault(row["Host"], {
            "Host": row["Host"], "Replicas": 0, "Leaders": 0,
            "CpuPct": 0.0, "NwInRate": 0.0, "NwOutRate": 0.0, "DiskMB": 0.0})
        h["Replicas"] += row["Replicas"]
        h["Leaders"] += row["Leaders"]
        for k in ("CpuPct", "NwInRate", "NwOutRate", "DiskMB"):
            h[k] = round(h[k] + row[k], 3)
    return {"brokers": brokers, "hosts": sorted(hosts.values(),
                                                key=lambda h: h["Host"])}


def partition_load(state: ClusterState, topology: ClusterTopology,
                   resource: int = Resource.DISK,
                   entries: Optional[int] = None,
                   topic_pattern: Optional[str] = None,
                   min_load: bool = False) -> List[dict]:
    """PARTITION_LOAD body: partitions sorted by leader-replica load on
    `resource`, descending (ascending when min_load)."""
    valid = np.asarray(state.replica_valid)
    leader = np.asarray(state.replica_is_leader) & valid
    part_of = np.asarray(state.replica_partition)
    base = np.asarray(state.replica_base_load)         # [R, RES]
    rb = np.asarray(state.replica_broker)

    pat = re.compile(topic_pattern) if topic_pattern else None
    rows = []
    leader_rows = np.nonzero(leader)[0]
    order = np.argsort(base[leader_rows, resource])
    if not min_load:
        order = order[::-1]
    # group follower rows by partition once — a per-partition full-array
    # scan would make this endpoint O(partitions x replicas)
    f_rows = np.nonzero(valid & ~leader)[0]
    f_sorted = f_rows[np.argsort(part_of[f_rows], kind="stable")]
    f_parts = part_of[f_sorted]
    starts = np.searchsorted(f_parts, np.arange(
        int(part_of.max()) + 2 if part_of.size else 1))
    for r in leader_rows[order]:
        p = int(part_of[r])
        pid = topology.partitions[p]
        if pat is not None and not pat.match(pid.topic):
            continue
        follower_rows = f_sorted[starts[p]:starts[p + 1]]
        rows.append({
            "topic": pid.topic,
            "partition": pid.partition,
            "leader": topology.broker_ids[int(rb[r])],
            "followers": [topology.broker_ids[int(rb[f])]
                          for f in follower_rows],
            "cpu": round(float(base[r, Resource.CPU]), 4),
            "networkInbound": round(float(base[r, Resource.NW_IN]), 4),
            "networkOutbound": round(float(base[r, Resource.NW_OUT]), 4),
            "disk": round(float(base[r, Resource.DISK]), 4),
        })
        if entries is not None and len(rows) >= entries:
            break
    return rows


def kafka_cluster_state(snapshot: ClusterSnapshot) -> dict:
    """KAFKA_CLUSTER_STATE body (reference response/KafkaClusterState.java):
    raw metadata view — per-broker counts + per-topic partition detail."""
    leader_count: Dict[int, int] = {}
    replica_count: Dict[int, int] = {}
    out_of_sync: Dict[int, int] = {}
    offline: Dict[int, int] = {}
    for p in snapshot.partitions:
        if p.leader is not None:
            leader_count[p.leader] = leader_count.get(p.leader, 0) + 1
        for b in p.replicas:
            replica_count[b] = replica_count.get(b, 0) + 1
            if b not in p.in_sync:
                out_of_sync[b] = out_of_sync.get(b, 0) + 1
        for b in p.offline_replicas:
            offline[b] = offline.get(b, 0) + 1

    topics: Dict[str, dict] = {}
    for p in snapshot.partitions:
        t = topics.setdefault(p.tp.topic, {})
        t[str(p.tp.partition)] = {
            "leader": p.leader, "replicas": list(p.replicas),
            "in-sync": list(p.in_sync),
            "out-of-sync": [b for b in p.replicas if b not in p.in_sync],
            "offline": list(p.offline_replicas),
        }
    return {
        "KafkaBrokerState": {
            "LeaderCountByBrokerId":
                {str(b.broker_id): leader_count.get(b.broker_id, 0)
                 for b in snapshot.brokers},
            "ReplicaCountByBrokerId":
                {str(b.broker_id): replica_count.get(b.broker_id, 0)
                 for b in snapshot.brokers},
            "OutOfSyncCountByBrokerId":
                {str(b.broker_id): out_of_sync.get(b.broker_id, 0)
                 for b in snapshot.brokers if out_of_sync.get(b.broker_id)},
            "OfflineReplicaCountByBrokerId":
                {str(b.broker_id): offline.get(b.broker_id, 0)
                 for b in snapshot.brokers if offline.get(b.broker_id)},
            "IsController":
                {str(b.broker_id): b.broker_id == snapshot.controller_id
                 for b in snapshot.brokers},
        },
        "KafkaPartitionState": topics,
    }


def optimization_result(result: OptimizerResult,
                        verbose: bool = False) -> dict:
    """PROPOSALS / rebalance-style body (reference
    response/OptimizationResult.java)."""
    out = {
        "summary": {
            "numReplicaMovements": result.num_replica_movements,
            "numLeaderMovements": result.num_leadership_movements,
            "dataToMoveMB": round(result.data_to_move / 1e6, 3),
            "numProposals": len(result.proposals),
            "excludedTopics": [],
            "onDemandBalancednessScoreBefore": None,
            "onDemandBalancednessScoreAfter":
                round(result.balancedness_score(), 3),
            "provisionStatus": "UNDECIDED",
        },
        "goalSummary": [
            {"goal": name,
             "status": ("VIOLATED" if name in result.violated_goals_after
                        else "NO-ACTION" if name
                        in result.violated_goals_before else "FIXED")}
            for name in result.stats_by_goal],
        "violatedGoalsBefore": result.violated_goals_before,
        "violatedGoalsAfter": result.violated_goals_after,
    }
    if result.solver_provenance is not None:
        # which solver actually produced this answer (portfolio/):
        # absent entirely for a plain greedy solve with no portfolio in
        # play, keeping pre-portfolio response bodies byte-identical
        out["solverProvenance"] = dict(result.solver_provenance)
    if verbose:
        out["proposals"] = [p.to_json() for p in result.proposals]
    return out
