"""Pluggable REST security.

Reference CC/servlet/security/ (17 files): SecurityProvider SPI with HTTP
Basic, JWT, SPNEGO and trusted-proxy implementations over a three-role
model ADMIN > USER > VIEWER (docs/wiki "Security").  Here: the SPI, the
role model and endpoint→role mapping, an HTTP Basic provider (stdlib
base64), a standards-based `JwtSecurityProvider` (RFC 7515/7519 compact
JWS: HS256 via stdlib hmac, RS256 via the `cryptography` package when
present — reference servlet/security/jwt/JwtLoginService.java:1-226), a
lightweight HMAC signed-token provider (`TokenSecurityProvider`, the
non-JOSE flavor), and a trusted-proxy provider.

**SPNEGO/Kerberos is an explicit non-goal** of this framework: it needs a
live KDC and a Kerberos client stack that this runtime does not carry.
Deployments that require Kerberos should terminate it at a fronting proxy
and use `TrustedProxySecurityProvider` (the reference's own trusted-proxy
flow exists for exactly this topology).
"""
from __future__ import annotations

import abc
import base64
import dataclasses
import enum
import hashlib
import hmac
import json
import time as _time
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple

from cruise_control_tpu.api.parameters import POST_ENDPOINTS


class Role(enum.IntEnum):
    """VIEWER < USER < ADMIN (reference security docs)."""

    VIEWER = 0
    USER = 1
    ADMIN = 2


#: minimum role per endpoint: viewers see state; users may run GETs that
#: compute; admins mutate (reference DefaultRoleSecurityProvider mapping)
def required_role(endpoint: str) -> Role:
    if endpoint in POST_ENDPOINTS or endpoint == "REVIEW":
        return Role.ADMIN
    if endpoint in ("PROPOSALS", "BOOTSTRAP", "TRAIN"):
        return Role.USER
    return Role.VIEWER


@dataclasses.dataclass(frozen=True)
class Principal:
    name: str
    role: Role


class AuthenticationError(Exception):
    """401 — missing or invalid credentials."""


class AuthorizationError(Exception):
    """403 — authenticated but not permitted."""


class SecurityProvider(abc.ABC):
    """SPI — reference servlet/security/SecurityProvider.java."""

    @abc.abstractmethod
    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        """Return the principal or raise AuthenticationError."""

    def authorize(self, principal: Principal, endpoint: str) -> None:
        if principal.role < required_role(endpoint):
            raise AuthorizationError(
                f"{principal.name} (role {principal.role.name}) may not "
                f"call {endpoint}")

    def auth_challenge_headers(self) -> Mapping[str, str]:
        """Headers attached to 401 responses (e.g. a WWW-Authenticate
        challenge advertising the login provider)."""
        return {}


class NoSecurityProvider(SecurityProvider):
    """Everything allowed (security disabled, the reference default)."""

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        return Principal("anonymous", Role.ADMIN)


class BasicSecurityProvider(SecurityProvider):
    """HTTP Basic auth against a static credential table (reference
    BasicSecurityProvider reading auth.credentials.file).

    `users` maps username -> (password, Role).
    """

    def __init__(self, users: Mapping[str, Tuple[str, Role]]) -> None:
        self._users = dict(users)

    @staticmethod
    def from_credentials_file(path: str) -> "BasicSecurityProvider":
        """Jetty-property-file flavor: `user: password,ROLE`."""
        users: Dict[str, Tuple[str, Role]] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                name, rest = line.split(":", 1)
                password, role = rest.rsplit(",", 1)
                users[name.strip()] = (password.strip(),
                                       Role[role.strip().upper()])
        return BasicSecurityProvider(users)

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        auth = _header(headers, "Authorization")
        if not auth or not auth.startswith("Basic "):
            raise AuthenticationError("missing Basic credentials")
        try:
            decoded = base64.b64decode(auth[6:]).decode()
            name, password = decoded.split(":", 1)
        except Exception:
            raise AuthenticationError("malformed Basic credentials")
        entry = self._users.get(name)
        if entry is None or not hmac.compare_digest(entry[0], password):
            raise AuthenticationError("bad username or password")
        return Principal(name, entry[1])


class TokenSecurityProvider(SecurityProvider):
    """Lightweight HMAC-signed bearer tokens (payload.signature — NOT
    JWT; for standards-based JWT use `JwtSecurityProvider`).  Useful for
    service-to-service auth where both ends are this framework.
    """

    def __init__(self, secret: bytes,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._secret = secret
        self._time = time_fn or _time.time

    # -- token issue (the reference's login service) --
    def issue(self, name: str, role: Role, ttl_s: float = 3600.0) -> str:
        payload = {"sub": name, "role": role.name,
                   "exp": self._time() + ttl_s}
        body = _b64url(json.dumps(payload).encode())
        sig = _b64url(hmac.new(self._secret, body.encode(),
                               hashlib.sha256).digest())
        return f"{body}.{sig}"

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        auth = _header(headers, "Authorization")
        if not auth or not auth.startswith("Bearer "):
            raise AuthenticationError("missing Bearer token")
        token = auth[7:]
        try:
            body, sig = token.rsplit(".", 1)
            want = _b64url(hmac.new(self._secret, body.encode(),
                                    hashlib.sha256).digest())
            if not hmac.compare_digest(want, sig):
                raise AuthenticationError("bad token signature")
            payload = json.loads(_b64url_decode(body))
        except AuthenticationError:
            raise
        except Exception:
            raise AuthenticationError("malformed token")
        if payload.get("exp", 0) < self._time():
            raise AuthenticationError("token expired")
        return Principal(payload["sub"], Role[payload["role"]])


class JwtSecurityProvider(SecurityProvider):
    """Standards-based JWT bearer authentication (RFC 7519 claims over an
    RFC 7515 compact JWS; reference servlet/security/jwt/
    JwtLoginService.java:1-226 + JwtAuthenticator).

    Supported algorithms: HS256 (shared secret, stdlib hmac) and RS256
    (RSA public key, PKCS#1 v1.5 over SHA-256 via the `cryptography`
    package).  The accepted algorithm set is pinned at construction —
    `alg: none` and algorithm-confusion tokens are rejected outright.

    Claims honored: `exp`/`nbf` (with `leeway_s`), optional expected
    `iss` and `aud`, `sub` as the principal name, and a role claim
    (default `"role"`, values VIEWER/USER/ADMIN; absent → `default_role`).
    """

    def __init__(self, *, hs256_secret: Optional[bytes] = None,
                 rs256_public_key_pem: Optional[bytes] = None,
                 issuer: Optional[str] = None,
                 audience: Optional[str] = None,
                 audiences: Optional[Sequence[str]] = None,
                 cookie_name: Optional[str] = None,
                 login_url: Optional[str] = None,
                 role_claim: str = "role",
                 default_role: Role = Role.USER,
                 leeway_s: float = 30.0,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        if hs256_secret is None and rs256_public_key_pem is None:
            raise ValueError("JwtSecurityProvider needs an HS256 secret "
                             "and/or an RS256 public key")
        self._hs256_secret = hs256_secret
        self._rs256_key = None
        if rs256_public_key_pem is not None:
            from cryptography.hazmat.primitives.serialization import (
                load_pem_public_key)
            self._rs256_key = load_pem_public_key(rs256_public_key_pem)
        self._issuer = issuer
        #: accepted aud claims (reference jwt.expected.audiences; the
        #: scalar `audience` form merges in)
        self._audiences = ([audience] if audience else []) \
            + list(audiences or [])
        #: cookie carrying the token (reference jwt.cookie.name)
        self._cookie_name = cookie_name
        #: login provider advertised on 401 (reference
        #: jwt.authentication.provider.url)
        self._login_url = login_url
        self._role_claim = role_claim
        self._default_role = default_role
        self._leeway = leeway_s
        self._time = time_fn or _time.time

    def auth_challenge_headers(self) -> Mapping[str, str]:
        if self._login_url:
            return {"WWW-Authenticate":
                    f'Bearer realm="{self._login_url}"'}
        return {"WWW-Authenticate": "Bearer"}

    # -- token issue (test/tooling convenience; the reference's login
    # service issues its tokens out-of-band) --
    def issue_hs256(self, claims: Mapping[str, object]) -> str:
        if self._hs256_secret is None:
            raise ValueError("no HS256 secret configured")
        header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        body = _b64url(json.dumps(dict(claims)).encode())
        signing_input = f"{header}.{body}".encode()
        sig = _b64url(hmac.new(self._hs256_secret, signing_input,
                               hashlib.sha256).digest())
        return f"{header}.{body}.{sig}"

    def _verify_signature(self, alg: str, signing_input: bytes,
                          sig: bytes) -> None:
        if alg == "HS256" and self._hs256_secret is not None:
            want = hmac.new(self._hs256_secret, signing_input,
                            hashlib.sha256).digest()
            if not hmac.compare_digest(want, sig):
                raise AuthenticationError("bad JWT signature")
            return
        if alg == "RS256" and self._rs256_key is not None:
            from cryptography.exceptions import InvalidSignature
            from cryptography.hazmat.primitives import hashes
            from cryptography.hazmat.primitives.asymmetric import padding
            try:
                self._rs256_key.verify(sig, signing_input,
                                       padding.PKCS1v15(), hashes.SHA256())
            except InvalidSignature:
                raise AuthenticationError("bad JWT signature")
            return
        raise AuthenticationError(f"JWT algorithm {alg!r} not accepted")

    def _token_from_cookie(self, headers: Mapping[str, str]
                           ) -> Optional[str]:
        if not self._cookie_name:
            return None
        raw = _header(headers, "Cookie") or ""
        for part in raw.split(";"):
            name, _, value = part.strip().partition("=")
            if name == self._cookie_name and value:
                return value
        return None

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        auth = _header(headers, "Authorization")
        if auth and auth.startswith("Bearer "):
            token = auth[7:].strip()
        else:
            token = self._token_from_cookie(headers)
            if not token:
                raise AuthenticationError("missing Bearer token")
        parts = token.split(".")
        if len(parts) != 3:
            raise AuthenticationError("malformed JWT")
        try:
            header = json.loads(_b64url_decode(parts[0]))
            claims = json.loads(_b64url_decode(parts[1]))
            sig = _b64url_decode(parts[2])
        except Exception:
            raise AuthenticationError("malformed JWT")
        if not isinstance(header, dict) or not isinstance(claims, dict):
            raise AuthenticationError("malformed JWT")
        alg = header.get("alg")
        signing_input = f"{parts[0]}.{parts[1]}".encode()
        self._verify_signature(alg, signing_input, sig)

        now = self._time()

        def _numeric(name):
            try:
                return float(claims[name])
            except (TypeError, ValueError):
                # must surface as 401, not a generic ValueError (the
                # server maps ValueError to 400 bad-parameter)
                raise AuthenticationError(f"malformed {name} claim")

        if "exp" in claims and now > _numeric("exp") + self._leeway:
            raise AuthenticationError("JWT expired")
        if "nbf" in claims and now < _numeric("nbf") - self._leeway:
            raise AuthenticationError("JWT not yet valid")
        if self._issuer is not None and claims.get("iss") != self._issuer:
            raise AuthenticationError("JWT issuer mismatch")
        if self._audiences:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if not any(a in auds for a in self._audiences):
                raise AuthenticationError("JWT audience mismatch")
        sub = claims.get("sub")
        if not sub:
            raise AuthenticationError("JWT missing sub claim")
        role_name = claims.get(self._role_claim)
        try:
            role = (Role[str(role_name).upper()] if role_name
                    else self._default_role)
        except KeyError:
            raise AuthenticationError(f"unknown role {role_name!r}")
        return Principal(str(sub), role)


class TrustedProxySecurityProvider(SecurityProvider):
    """Authenticates a fronting proxy and trusts its asserted user
    (reference TrustedProxySecurityProvider: the proxy authenticates via
    its own provider and passes the end user in `doAs`)."""

    def __init__(self, proxy_provider: SecurityProvider,
                 trusted_proxies: Sequence[str],
                 role_fn: Callable[[str], Role] = lambda name: Role.USER,
                 ip_regex: Optional[str] = None
                 ) -> None:
        import re
        self._proxy_provider = proxy_provider
        self._trusted = set(trusted_proxies)
        self._role_fn = role_fn
        #: source-address filter (reference
        #: trusted.proxy.services.ip.regex): the asserting proxy must
        #: connect from a matching address; the server passes the peer
        #: address as the X-Remote-Addr pseudo-header
        self._ip_re = re.compile(ip_regex) if ip_regex else None

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        proxy = self._proxy_provider.authenticate(headers)
        if proxy.name not in self._trusted:
            raise AuthenticationError(
                f"{proxy.name} is not a trusted proxy")
        if self._ip_re is not None:
            addr = _header(headers, "X-Remote-Addr") or ""
            if not self._ip_re.fullmatch(addr):
                raise AuthenticationError(
                    f"proxy address {addr!r} not allowed")
        do_as = _header(headers, "doAs") or _header(headers, "X-DoAs-User")
        if not do_as:
            raise AuthenticationError("trusted proxy must assert doAs user")
        return Principal(do_as, self._role_fn(do_as))


def _header(headers: Mapping[str, str], name: str) -> Optional[str]:
    for k, v in headers.items():
        if k.lower() == name.lower():
            return v
    return None


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
