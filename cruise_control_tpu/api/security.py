"""Pluggable REST security.

Reference CC/servlet/security/ (17 files): SecurityProvider SPI with HTTP
Basic, JWT, SPNEGO and trusted-proxy implementations over a three-role
model ADMIN > USER > VIEWER (docs/wiki "Security").  Here: the SPI, the
role model and endpoint→role mapping, an HTTP Basic provider (stdlib
base64), and a signed-token provider (stdlib hmac — structurally the JWT
flow without external JOSE dependencies).
"""
from __future__ import annotations

import abc
import base64
import dataclasses
import enum
import hashlib
import hmac
import json
import time as _time
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple

from cruise_control_tpu.api.parameters import GET_ENDPOINTS, POST_ENDPOINTS


class Role(enum.IntEnum):
    """VIEWER < USER < ADMIN (reference security docs)."""

    VIEWER = 0
    USER = 1
    ADMIN = 2


#: minimum role per endpoint: viewers see state; users may run GETs that
#: compute; admins mutate (reference DefaultRoleSecurityProvider mapping)
def required_role(endpoint: str) -> Role:
    if endpoint in POST_ENDPOINTS or endpoint == "REVIEW":
        return Role.ADMIN
    if endpoint in ("PROPOSALS", "BOOTSTRAP", "TRAIN"):
        return Role.USER
    return Role.VIEWER


@dataclasses.dataclass(frozen=True)
class Principal:
    name: str
    role: Role


class AuthenticationError(Exception):
    """401 — missing or invalid credentials."""


class AuthorizationError(Exception):
    """403 — authenticated but not permitted."""


class SecurityProvider(abc.ABC):
    """SPI — reference servlet/security/SecurityProvider.java."""

    @abc.abstractmethod
    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        """Return the principal or raise AuthenticationError."""

    def authorize(self, principal: Principal, endpoint: str) -> None:
        if principal.role < required_role(endpoint):
            raise AuthorizationError(
                f"{principal.name} (role {principal.role.name}) may not "
                f"call {endpoint}")


class NoSecurityProvider(SecurityProvider):
    """Everything allowed (security disabled, the reference default)."""

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        return Principal("anonymous", Role.ADMIN)


class BasicSecurityProvider(SecurityProvider):
    """HTTP Basic auth against a static credential table (reference
    BasicSecurityProvider reading auth.credentials.file).

    `users` maps username -> (password, Role).
    """

    def __init__(self, users: Mapping[str, Tuple[str, Role]]) -> None:
        self._users = dict(users)

    @staticmethod
    def from_credentials_file(path: str) -> "BasicSecurityProvider":
        """Jetty-property-file flavor: `user: password,ROLE`."""
        users: Dict[str, Tuple[str, Role]] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                name, rest = line.split(":", 1)
                password, role = rest.rsplit(",", 1)
                users[name.strip()] = (password.strip(),
                                       Role[role.strip().upper()])
        return BasicSecurityProvider(users)

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        auth = _header(headers, "Authorization")
        if not auth or not auth.startswith("Basic "):
            raise AuthenticationError("missing Basic credentials")
        try:
            decoded = base64.b64decode(auth[6:]).decode()
            name, password = decoded.split(":", 1)
        except Exception:
            raise AuthenticationError("malformed Basic credentials")
        entry = self._users.get(name)
        if entry is None or not hmac.compare_digest(entry[0], password):
            raise AuthenticationError("bad username or password")
        return Principal(name, entry[1])


class TokenSecurityProvider(SecurityProvider):
    """HMAC-signed bearer tokens (the JWT flow of the reference's
    JwtSecurityProvider/JwtLoginService.java:1-226, with stdlib crypto:
    header.payload.signature, HS256-equivalent).
    """

    def __init__(self, secret: bytes,
                 time_fn: Optional[Callable[[], float]] = None) -> None:
        self._secret = secret
        self._time = time_fn or _time.time

    # -- token issue (the reference's login service) --
    def issue(self, name: str, role: Role, ttl_s: float = 3600.0) -> str:
        payload = {"sub": name, "role": role.name,
                   "exp": self._time() + ttl_s}
        body = _b64url(json.dumps(payload).encode())
        sig = _b64url(hmac.new(self._secret, body.encode(),
                               hashlib.sha256).digest())
        return f"{body}.{sig}"

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        auth = _header(headers, "Authorization")
        if not auth or not auth.startswith("Bearer "):
            raise AuthenticationError("missing Bearer token")
        token = auth[7:]
        try:
            body, sig = token.rsplit(".", 1)
            want = _b64url(hmac.new(self._secret, body.encode(),
                                    hashlib.sha256).digest())
            if not hmac.compare_digest(want, sig):
                raise AuthenticationError("bad token signature")
            payload = json.loads(_b64url_decode(body))
        except AuthenticationError:
            raise
        except Exception:
            raise AuthenticationError("malformed token")
        if payload.get("exp", 0) < self._time():
            raise AuthenticationError("token expired")
        return Principal(payload["sub"], Role[payload["role"]])


class TrustedProxySecurityProvider(SecurityProvider):
    """Authenticates a fronting proxy and trusts its asserted user
    (reference TrustedProxySecurityProvider: the proxy authenticates via
    its own provider and passes the end user in `doAs`)."""

    def __init__(self, proxy_provider: SecurityProvider,
                 trusted_proxies: Sequence[str],
                 role_fn: Callable[[str], Role] = lambda name: Role.USER
                 ) -> None:
        self._proxy_provider = proxy_provider
        self._trusted = set(trusted_proxies)
        self._role_fn = role_fn

    def authenticate(self, headers: Mapping[str, str]) -> Principal:
        proxy = self._proxy_provider.authenticate(headers)
        if proxy.name not in self._trusted:
            raise AuthenticationError(
                f"{proxy.name} is not a trusted proxy")
        do_as = _header(headers, "doAs") or _header(headers, "X-DoAs-User")
        if not do_as:
            raise AuthenticationError("trusted proxy must assert doAs user")
        return Principal(do_as, self._role_fn(do_as))


def _header(headers: Mapping[str, str], name: str) -> Optional[str]:
    for k, v in headers.items():
        if k.lower() == name.lower():
            return v
    return None


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
