"""Query-parameter parsing and validation.

Reference CC/servlet/parameters/ (24 classes + ParameterUtils.java:1-1038):
every endpoint declares its legal parameter names; unknown parameters are
rejected; values are parsed with typed helpers (booleans, CSV integer
lists, regex patterns, doubles).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from cruise_control_tpu.common.resources import Resource


class ParameterError(ValueError):
    """400-level: bad query parameter."""


#: legal query parameters per endpoint (reference each *Parameters class)
VALID_PARAMS: Dict[str, Set[str]] = {
    "STATE": {"substates", "verbose", "json"},   # substates incl. sensors
    "LOAD": {"allow_capacity_estimation", "json"},
    "PARTITION_LOAD": {"resource", "entries", "topic", "min_valid_partition_ratio",
                       "max_load", "json"},
    "PROPOSALS": {"goals", "ignore_proposal_cache", "verbose",
                  "excluded_topics", "portfolio_width", "json"},
    "KAFKA_CLUSTER_STATE": {"verbose", "json"},
    "USER_TASKS": {"user_task_ids", "json"},
    "REVIEW_BOARD": {"review_ids", "json"},
    "BOOTSTRAP": {"start", "end", "clearmetrics", "json"},
    "TRAIN": {"start", "end", "json"},
    "REBALANCE": {"goals", "dryrun", "verbose", "excluded_topics",
                  "concurrent_partition_movements_per_broker",
                  "concurrent_leader_movements", "json", "reason",
                  "ignore_proposal_cache", "destination_broker_ids",
                  "replication_throttle", "replica_movement_strategies",
                  "kafka_assigner", "portfolio_width", "review_id"},
    "ADD_BROKER": {"brokerid", "goals", "dryrun", "verbose", "json",
                   "reason", "throttle_added_broker",
                   "replication_throttle", "review_id"},
    "REMOVE_BROKER": {"brokerid", "goals", "dryrun", "verbose", "json",
                      "reason", "throttle_removed_broker",
                      "destination_broker_ids", "replication_throttle",
                      "review_id"},
    "DEMOTE_BROKER": {"brokerid", "dryrun", "verbose", "json", "reason",
                      "skip_urp_demotion", "exclude_follower_demotion",
                      "replication_throttle", "review_id"},
    "FIX_OFFLINE_REPLICAS": {"goals", "dryrun", "verbose", "json", "reason",
                             "review_id"},
    "STOP_PROPOSAL_EXECUTION": {"force_stop", "json", "review_id"},
    "PAUSE_SAMPLING": {"reason", "json", "review_id"},
    "RESUME_SAMPLING": {"reason", "json", "review_id"},
    "ADMIN": {"disable_self_healing_for", "enable_self_healing_for",
              "concurrent_partition_movements_per_broker",
              "concurrent_leader_movements", "json", "review_id"},
    "REVIEW": {"approve", "discard", "reason", "json"},
    "TOPIC_CONFIGURATION": {"topic", "replication_factor", "goals",
                            "dryrun", "verbose", "json", "reason",
                            "review_id"},
    # batched what-if analysis (framework extension, scenario/ engine):
    # the scenario list rides in the JSON request BODY (see
    # scenario/spec.py SCENARIOS_REQUEST_SCHEMA), not the query string
    "SCENARIOS": {"verbose", "json", "reason", "review_id"},
    # flight-recorder queries (framework extension, obs/): the span
    # trees of recent solves — `?trace_id=` fetches the tree a solve
    # response's `traceId` named, `?outcome=degraded` the pinned
    # incident traces (docs/OBSERVABILITY.md)
    # `since` (epoch ms) + `min_duration_ms` bound drill queries under
    # load so --follow tails never page the full ring
    "TRACES": {"trace_id", "outcome", "limit", "verbose", "json",
               "since", "min_duration_ms"},
}

#: fleet tenancy (framework extension, fleet/): EVERY endpoint accepts
#: `cluster=<id>` selecting the tenant — 404 on an unknown id, the
#: default tenant when omitted (docs/FLEET.md)
for _params in VALID_PARAMS.values():
    _params.add("cluster")

#: fleet-level tenant listing (GET; no `cluster` param — it spans the
#: whole fleet by definition)
VALID_PARAMS["FLEET"] = {"verbose", "json"}

#: POST endpoints subject to purgatory review when two-step is enabled
POST_ENDPOINTS = {
    "REBALANCE", "ADD_BROKER", "REMOVE_BROKER", "DEMOTE_BROKER",
    "FIX_OFFLINE_REPLICAS", "STOP_PROPOSAL_EXECUTION", "PAUSE_SAMPLING",
    "RESUME_SAMPLING", "ADMIN", "TOPIC_CONFIGURATION", "SCENARIOS",
}
GET_ENDPOINTS = set(VALID_PARAMS) - POST_ENDPOINTS - {"REVIEW"}


class QueryParams:
    """Typed accessors over a parsed query dict (values = last occurrence)."""

    def __init__(self, endpoint: str, raw: Dict[str, List[str]]) -> None:
        self.endpoint = endpoint
        legal = VALID_PARAMS.get(endpoint)
        if legal is None:
            raise ParameterError(f"unknown endpoint {endpoint!r}")
        unknown = {k.lower() for k in raw} - legal
        if unknown:
            raise ParameterError(
                f"unrecognized parameters {sorted(unknown)} for "
                f"{endpoint}; legal: {sorted(legal)}")
        self._raw = {k.lower(): v[-1] for k, v in raw.items()}

    def get(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self._raw.get(name, default)

    def get_bool(self, name: str, default: bool = False) -> bool:
        v = self._raw.get(name)
        if v is None:
            return default
        if v.lower() in ("true", "1", "yes"):
            return True
        if v.lower() in ("false", "0", "no"):
            return False
        raise ParameterError(f"{name} must be boolean, got {v!r}")

    def get_int(self, name: str, default: Optional[int] = None
                ) -> Optional[int]:
        v = self._raw.get(name)
        if v is None:
            return default
        try:
            return int(v)
        except ValueError:
            raise ParameterError(f"{name} must be an integer, got {v!r}")

    def get_float(self, name: str, default: Optional[float] = None
                  ) -> Optional[float]:
        v = self._raw.get(name)
        if v is None:
            return default
        try:
            return float(v)
        except ValueError:
            raise ParameterError(f"{name} must be a number, got {v!r}")

    def get_csv(self, name: str) -> Optional[List[str]]:
        v = self._raw.get(name)
        if v is None or v == "":
            return None
        return [s.strip() for s in v.split(",") if s.strip()]

    def get_csv_ints(self, name: str) -> Optional[List[int]]:
        vals = self.get_csv(name)
        if vals is None:
            return None
        try:
            return [int(s) for s in vals]
        except ValueError:
            raise ParameterError(f"{name} must be CSV integers")

    def get_resource(self, name: str, default: int = Resource.DISK) -> int:
        v = self._raw.get(name)
        if v is None:
            return default
        try:
            return {"cpu": Resource.CPU, "nw_in": Resource.NW_IN,
                    "networkinbound": Resource.NW_IN,
                    "nw_out": Resource.NW_OUT,
                    "networkoutbound": Resource.NW_OUT,
                    "disk": Resource.DISK}[v.lower()]
        except KeyError:
            raise ParameterError(f"unknown resource {v!r}")
