"""Config-driven endpoint -> handler/parameter class wiring.

Reference CC/config/constants/CruiseControlRequestConfig.java and
CruiseControlParametersConfig.java: the servlet instantiates each
endpoint's Request and Parameters classes from config
(`<endpoint>.request.class` / `<endpoint>.parameters.class`, 20 + 20
keys), so deployments can swap per-endpoint behavior without forking the
server.  Here the same keys resolve dotted Python classes: the
parameters class builds the endpoint's QueryParams (subclass to accept
extra parameters or re-validate), and the request class produces the
response body (subclass `Request` to override an endpoint end to end).
"""
from __future__ import annotations

import importlib
from typing import Dict, Tuple, Type

from cruise_control_tpu.api.parameters import QueryParams

#: endpoint -> config-key stem (reference key names use the stem with
#: ".request.class" / ".parameters.class" suffixes)
ENDPOINT_KEY_STEMS: Dict[str, str] = {
    "BOOTSTRAP": "bootstrap",
    "TRAIN": "train",
    "LOAD": "load",
    "PARTITION_LOAD": "partition.load",
    "PROPOSALS": "proposals",
    "STATE": "state",
    "KAFKA_CLUSTER_STATE": "kafka.cluster.state",
    "USER_TASKS": "user.tasks",
    "REVIEW_BOARD": "review.board",
    "ADD_BROKER": "add.broker",
    "REMOVE_BROKER": "remove.broker",
    "FIX_OFFLINE_REPLICAS": "fix.offline.replicas",
    "DEMOTE_BROKER": "demote.broker",
    "REBALANCE": "rebalance",
    "STOP_PROPOSAL_EXECUTION": "stop.proposal",
    "PAUSE_SAMPLING": "pause.sampling",
    "RESUME_SAMPLING": "resume.sampling",
    "ADMIN": "admin",
    "REVIEW": "review",
    "TOPIC_CONFIGURATION": "topic.configuration",
}

DEFAULT_REQUEST_CLASS = "cruise_control_tpu.api.request_registry.Request"
DEFAULT_PARAMETERS_CLASS = "cruise_control_tpu.api.parameters.QueryParams"


class Request:
    """Default request handler: delegates to the app's built-in dispatch
    (reference handler/sync + handler/async Request classes; subclasses
    override `handle_sync` or `operation`)."""

    def __init__(self, endpoint: str) -> None:
        self.endpoint = endpoint

    def handle_sync(self, app, params) -> dict:
        """Synchronous endpoints: return the JSON body."""
        return app.default_sync_handler(self.endpoint, params)

    def operation(self, app, params):
        """Async endpoints: return the zero-arg callable the user-task
        executor runs."""
        return app.default_operation(self.endpoint, params)


def _import_class(dotted: str):
    mod, _, name = dotted.rpartition(".")
    return getattr(importlib.import_module(mod), name)


def resolve_endpoint_classes(config) -> Dict[str, Tuple[Type[Request],
                                                        Type[QueryParams]]]:
    """{endpoint: (request class, parameters class)} from the 40 config
    keys; invalid classes raise at startup (reference
    getConfiguredInstance semantics)."""
    out = {}
    for endpoint, stem in ENDPOINT_KEY_STEMS.items():
        req_cls = _import_class(config.get_string(f"{stem}.request.class"))
        par_cls = _import_class(
            config.get_string(f"{stem}.parameters.class"))
        if not issubclass(req_cls, Request):
            raise TypeError(f"{stem}.request.class {req_cls} does not "
                            f"extend api.request_registry.Request")
        if not issubclass(par_cls, QueryParams):
            raise TypeError(f"{stem}.parameters.class {par_cls} does not "
                            f"extend api.parameters.QueryParams")
        out[endpoint] = (req_cls, par_cls)
    return out


def request_config_def(d) -> None:
    """Define the 40 endpoint wiring keys (reference
    CruiseControlRequestConfig + CruiseControlParametersConfig)."""
    from cruise_control_tpu.common.config import Importance, Type as CType
    for stem in sorted(set(ENDPOINT_KEY_STEMS.values())):
        d.define(f"{stem}.request.class", CType.CLASS,
                 DEFAULT_REQUEST_CLASS, None, Importance.LOW,
                 f"Request handler class for the {stem} endpoint.")
        d.define(f"{stem}.parameters.class", CType.CLASS,
                 DEFAULT_PARAMETERS_CLASS, None, Importance.LOW,
                 f"Parameter validation class for the {stem} endpoint.")
